"""Zero-copy Arrow IPC streaming frontend for the ingestion plane.

The wire format is the standard Arrow IPC *stream* (schema message, then
record batches, then EOS): anything that speaks Arrow — a Flight client, a
``pa.ipc.new_stream`` writer, polars, DuckDB ``COPY TO`` — can feed a
streaming session directly. Decoding is zero-copy over the received
buffer: each record batch's columns are views into the payload bytes, and
`deequ_tpu.data.Dataset` keeps them lazy (dictionary-encoded string
columns map straight onto the engine's cached distinct-value hash path;
numeric columns reach the device as buffer views).

Failure contract (each frame is one atomic micro-batch fold):

- a payload whose declared xxhash64 checksum does not match, or whose
  bytes fail structural decode with the stream fully present, raises a
  typed :class:`MalformedFrameError` BEFORE anything folds;
- a stream that ends mid-frame raises a typed :class:`FeedDisconnectError`
  — frames that decoded completely before the tear have already folded,
  the torn tail never touches state;
- both paths are fault-injectable at the ``frame_decode`` site (kind
  ``frame_corrupt``), flight-recorded, and counted on the export plane.

Arrow IPC itself carries NO data checksum — a flipped byte inside a
buffer body decodes silently (verified against pyarrow 22) — so producers
that care about integrity send the optional xxhash64 digest of the whole
payload (the ``X-Deequ-Checksum`` header on the HTTP plane, the
``checksum=`` argument in-process). Verification uses the same vectorized
`deequ_tpu.integrity.checksum_bytes` the durable state plane uses.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, List, Optional, Sequence, Tuple, Union

_logger = logging.getLogger(__name__)

from ..exceptions import FeedDisconnectError, MalformedFrameError

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is in the base image
    pa = None

#: HTTP header carrying the optional xxhash64 hex digest of the raw body
CHECKSUM_HEADER = "X-Deequ-Checksum"

#: pyarrow error fragments that mean "the stream ran out of bytes" (the
#: producer died / the payload was truncated) rather than "the bytes are
#: structurally wrong". Pinned against pyarrow 22 by tests.
_TRUNCATION_MARKERS = (
    "Expected to be able to read",
    "but only read",
    "bytes available",
    "was null or length 0",
)


def _looks_truncated(exc: BaseException) -> bool:
    msg = str(exc)
    return any(marker in msg for marker in _TRUNCATION_MARKERS)


def encode_ipc_stream(
    data: Union["pa.Table", Sequence["pa.RecordBatch"]],
    *,
    max_chunksize: Optional[int] = None,
) -> bytes:
    """Serialize a table (or record batches) to Arrow IPC stream bytes —
    the producer side of the wire contract, used by tests, the soak tool
    and the chaos drills."""
    import io

    if isinstance(data, pa.Table):
        batches = data.to_batches(max_chunksize=max_chunksize)
        schema = data.schema
    else:
        batches = list(data)
        if not batches:
            raise ValueError("cannot encode an empty batch sequence")
        schema = batches[0].schema
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as writer:
        for batch in batches:
            writer.write_batch(batch)
    return sink.getvalue()


def iter_frames(
    payload: Union[bytes, bytearray, memoryview, "pa.Buffer"],
    *,
    source: str = "<bytes>",
    complete: bool = True,
) -> Iterator[Tuple[int, "pa.RecordBatch"]]:
    """Decode an Arrow IPC stream payload into ``(index, record_batch)``
    pairs with the typed failure contract.

    ``complete=True`` asserts the whole declared payload is present (the
    checksum verified, or the transport delivered its full Content-Length)
    — every decode error is then a :class:`MalformedFrameError`, because
    nothing more is coming. ``complete=False`` means the transport may
    have delivered a prefix; truncation-shaped decode errors become
    :class:`FeedDisconnectError`."""
    from ..reliability.faults import fault_point

    if not isinstance(payload, pa.Buffer):
        payload = pa.py_buffer(payload)
    n_bytes = payload.size
    try:
        reader = pa.ipc.open_stream(pa.BufferReader(payload))
    except Exception as exc:  # noqa: BLE001 - typed below
        if not complete and _looks_truncated(exc):
            raise FeedDisconnectError(
                source, frames_decoded=0, bytes_read=n_bytes, detail=str(exc)
            ) from exc
        raise MalformedFrameError(source, str(exc), frame_index=0) from exc
    index = 0
    while True:
        # chaos site: an injected frame_corrupt stands in for garbled
        # bytes the structural decode cannot see (IPC has no checksum)
        fault_point("frame_decode", tag=str(index))
        try:
            batch = reader.read_next_batch()
        except StopIteration:
            return
        except MalformedFrameError:
            raise
        except Exception as exc:  # noqa: BLE001 - typed below
            if not complete and _looks_truncated(exc):
                raise FeedDisconnectError(
                    source, frames_decoded=index, bytes_read=n_bytes,
                    detail=str(exc),
                ) from exc
            raise MalformedFrameError(
                source, str(exc), frame_index=index
            ) from exc
        yield index, batch
        index += 1


@dataclass
class IngestReport:
    """What one stream fold accomplished: per-frame verification results
    plus the byte/row accounting the export plane mirrors."""

    source: str
    frames: int = 0
    rows: int = 0
    bytes: int = 0
    results: List[Any] = field(default_factory=list)

    @property
    def statuses(self) -> List[str]:
        out = []
        for r in self.results:
            status = getattr(r, "status", None)
            out.append(getattr(status, "value", str(status)))
        return out

    def to_dict(self) -> dict:
        return {
            "source": self.source,
            "frames": self.frames,
            "rows": self.rows,
            "bytes": self.bytes,
            "statuses": self.statuses,
        }


def fold_stream(
    session,
    payload: Union[bytes, bytearray, memoryview, "pa.Buffer"],
    *,
    checksum: Optional[str] = None,
    complete: bool = True,
    source: str = "<bytes>",
    timeout: Optional[float] = None,
) -> IngestReport:
    """Fold every record batch of an Arrow IPC stream payload into a
    :class:`~deequ_tpu.service.streaming.StreamingSession`, one atomic
    micro-batch merge per frame. The shared implementation behind the HTTP
    ingest endpoint and in-process Arrow feeds.

    ``checksum`` (xxhash64 hex of the raw payload) is verified BEFORE any
    decode — a mismatch is a :class:`MalformedFrameError` and nothing
    folds. Schema drift, admission shedding and session lifecycle errors
    propagate typed from ``session.ingest`` exactly as on the in-process
    path; frames already folded when a later frame fails stay committed
    (the report in the raised error's ``__notes__`` is not needed — the
    session's ``batches_ingested`` is the commit log).
    """
    from ..observability import record_failure
    from ..observability import trace as _trace

    from .columnar import as_dataset

    if not isinstance(payload, pa.Buffer):
        payload = pa.py_buffer(payload)
    report = IngestReport(source=source, bytes=payload.size)
    metrics = session.service.metrics
    labels = {"tenant": session.tenant, "dataset": session.dataset}
    with _trace.span(
        "ingest_stream", kind="ingest", source=source,
        tenant=session.tenant, dataset=session.dataset,
        payload_bytes=payload.size,
    ) as sp:
        metrics.inc("deequ_service_ingest_sessions_total", **labels)
        if checksum is not None:
            try:
                from ..integrity import checksum_bytes

                # memoryview over the arrow buffer: the digest reads the
                # payload in place, no second copy of a large stream
                actual = checksum_bytes(memoryview(payload))
                if actual != str(checksum).lower():
                    raise MalformedFrameError(
                        source,
                        f"payload checksum mismatch (declared {checksum}, "
                        f"computed {actual})",
                    )
            except MalformedFrameError as exc:
                record_failure(exc)
                metrics.inc(
                    "deequ_service_ingest_malformed_total", **labels
                )
                raise
        _fold_frames(
            session,
            iter_frames(payload, source=source, complete=complete),
            report, sp, timeout,
        )
        # bytes count once per COMPLETED stream: a rejected payload's
        # bytes were never ingested, so MB/s on the plane stays honest
        metrics.inc(
            "deequ_service_ingest_bytes_total", float(payload.size), **labels
        )
    return report


def _fold_frames(session, frames, report: IngestReport, sp, timeout) -> None:
    """The shared per-frame fold loop (buffered and incremental paths):
    one atomic micro-batch merge per decoded frame, typed failures
    counted + flight-recorded, committed leading frames never rolled
    back."""
    from ..observability import record_failure

    from .columnar import as_dataset

    metrics = session.service.metrics
    labels = {"tenant": session.tenant, "dataset": session.dataset}
    decode_labels = {
        "tenant": session.tenant,
        "priority": getattr(
            session.priority, "name", str(session.priority)
        ).lower(),
    }
    frames = iter(frames)
    try:
        while True:
            # the next() pull IS the frame decode (both generators do
            # their read_next_batch inside) — time it per frame
            t0 = time.perf_counter()
            try:
                index, batch = next(frames)
            except StopIteration:
                break
            metrics.observe(
                "deequ_service_ingest_decode_seconds",
                time.perf_counter() - t0, **decode_labels,
            )
            data = as_dataset(batch)
            result = session.ingest(data, timeout=timeout)
            report.frames += 1
            report.rows += int(data.num_rows)
            report.results.append(result)
            metrics.inc_many([
                ("deequ_service_ingest_batches_total", 1.0, labels),
                ("deequ_service_ingest_rows_total",
                 float(data.num_rows), labels),
            ])
            sp.add_event(
                "frame_folded", frame=index, rows=int(data.num_rows)
            )
    except MalformedFrameError as exc:
        record_failure(exc)
        metrics.inc("deequ_service_ingest_malformed_total", **labels)
        sp.add_event("malformed_frame", frame=report.frames)
        raise
    except FeedDisconnectError as exc:
        record_failure(exc)
        metrics.inc("deequ_service_ingest_disconnects_total", **labels)
        sp.add_event("feed_disconnect", frames_folded=report.frames)
        raise


class BoundedReader:
    """File-like view over a transport stream that reads at most ``limit``
    bytes (an HTTP body must never be over-read: the bytes after it belong
    to the next request) and counts what actually arrived. A short read —
    the producer died — surfaces to the Arrow decoder as truncation, which
    the typed contract maps to :class:`FeedDisconnectError`."""

    def __init__(self, raw, limit: int):
        self._raw = raw
        self._remaining = int(limit)
        self.bytes_read = 0
        #: True once the transport delivered FEWER bytes than declared —
        #: what tells a real disconnect (the producer died mid-body) from
        #: a fully-delivered payload whose bytes are structurally bad
        self.short = False

    def read(self, n: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        if n is None or n < 0 or n > self._remaining:
            n = self._remaining
        data = self._raw.read(n)
        got = len(data)
        self._remaining -= got
        self.bytes_read += got
        if got < n:
            self.short = True
            self._remaining = 0  # transport exhausted: everything after
            # this is a short read, never a block on a dead socket
        return data

    def drain(self) -> None:
        """Consume any unread remainder (trailing bytes after the Arrow
        EOS marker) so a keep-alive connection stays framed."""
        while self._remaining > 0:
            if not self.read(min(self._remaining, 1 << 16)):
                break

    # the minimal file-object surface pyarrow's PythonFile wrapper probes
    closed = False

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return False

    def flush(self) -> None:
        pass

    def close(self) -> None:
        # the transport (HTTP rfile) outlives this view; never close it
        pass


def fold_stream_reader(
    session,
    reader: BoundedReader,
    *,
    source: str = "<stream>",
    timeout: Optional[float] = None,
) -> IngestReport:
    """INCREMENTAL stream fold: decode Arrow IPC frames straight off a
    transport reader and fold each as it arrives — a GB-scale stream
    holds ONE frame in memory instead of buffering its whole body (the
    unbuffered HTTP ingest path). Checksummed requests cannot ride this
    path: the digest must verify over the complete payload BEFORE
    anything folds, so the endpoint keeps those on the buffered
    :func:`fold_stream` (the documented tripwire semantics, unchanged).

    Failure contract mirrors ``fold_stream(complete=False)``: whole
    leading frames fold and stay committed; a truncated tail — or a
    transport error mid-read — raises typed :class:`FeedDisconnectError`;
    structurally bad bytes with the stream still flowing raise
    :class:`MalformedFrameError`."""
    from ..observability import record_failure
    from ..observability import trace as _trace

    report = IngestReport(source=source)
    metrics = session.service.metrics
    labels = {"tenant": session.tenant, "dataset": session.dataset}

    def frames():
        from ..reliability.faults import fault_point

        def classify(exc, index):
            # truncation-shaped errors are a DISCONNECT only when the
            # transport actually under-delivered; a fully-delivered body
            # that still runs out of bytes is structurally malformed
            if isinstance(exc, OSError) or (
                _looks_truncated(exc) and reader.short
            ):
                return FeedDisconnectError(
                    source, frames_decoded=index,
                    bytes_read=reader.bytes_read, detail=str(exc),
                )
            return MalformedFrameError(source, str(exc), frame_index=index)

        try:
            arrow_reader = pa.ipc.open_stream(reader)
        except Exception as exc:  # noqa: BLE001 - typed below
            raise classify(exc, 0) from exc
        index = 0
        while True:
            fault_point("frame_decode", tag=str(index))
            try:
                batch = arrow_reader.read_next_batch()
            except StopIteration:
                return
            except MalformedFrameError:
                raise
            except Exception as exc:  # noqa: BLE001 - typed below
                raise classify(exc, index) from exc
            yield index, batch
            index += 1

    with _trace.span(
        "ingest_stream", kind="ingest", source=source,
        tenant=session.tenant, dataset=session.dataset, incremental=True,
    ) as sp:
        metrics.inc("deequ_service_ingest_sessions_total", **labels)
        _fold_frames(session, frames(), report, sp, timeout)
        report.bytes = reader.bytes_read
        metrics.inc(
            "deequ_service_ingest_bytes_total",
            float(reader.bytes_read), **labels,
        )
    return report


def describe_ingest_metrics(metrics) -> None:
    """Register HELP text for the ingest-plane series (idempotent; called
    by the endpoint and the soak so a scrape is documented either way)."""
    metrics.describe(
        "deequ_service_ingest_sessions_total",
        "Ingest streams opened against a session (HTTP or in-process "
        "Arrow feeds).",
    )
    metrics.describe(
        "deequ_service_ingest_batches_total",
        "Record-batch frames folded through the Arrow ingestion plane.",
    )
    metrics.describe(
        "deequ_service_ingest_rows_total",
        "Rows folded through the Arrow ingestion plane.",
    )
    metrics.describe(
        "deequ_service_ingest_bytes_total",
        "Payload bytes of COMPLETED ingest streams (rejected payloads "
        "never count).",
    )
    metrics.describe(
        "deequ_service_ingest_malformed_total",
        "Ingest payloads rejected typed: checksum mismatch or structural "
        "decode failure (MalformedFrameError). Nothing folded.",
    )
    metrics.describe(
        "deequ_service_ingest_disconnects_total",
        "Ingest streams torn mid-frame (FeedDisconnectError). Complete "
        "leading frames stayed committed.",
    )
    metrics.describe(
        "deequ_service_ingest_shed_total",
        "Ingest frames shed by bounded admission (ServiceOverloaded "
        "surfaced as HTTP 429 / typed error).",
    )
    metrics.describe_histogram(
        "deequ_service_ingest_decode_seconds",
        "Arrow IPC frame decode time on the ingestion plane, per tenant "
        "and priority class (pow2 buckets, seconds).",
    )
