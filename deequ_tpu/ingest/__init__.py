"""High-throughput ingestion plane: socket to device.

The engine scans orders of magnitude faster than the feed links that were
serving it (ROADMAP item 4: the bench's feed probe read 6-30 MB/s against
a ~GB/s device appetite). This package is the missing frontend:

- :mod:`.columnar` — in-process coercion: dict-of-numpy / Arrow tables /
  record batches become :class:`~deequ_tpu.data.Dataset` with no pandas
  hop (`as_dataset`);
- :mod:`.arrow_stream` — the zero-copy Arrow IPC wire format: typed,
  checksummed, fault-injectable frame decode (`iter_frames`) and the
  per-frame atomic fold into a streaming session (`fold_stream`);
- :mod:`.endpoint` — the HTTP frontend riding the MetricsExporter plane
  (``POST /ingest/v1/<tenant>/<dataset>``);
- :mod:`.rowgate` — row-level ingest gating: one vectorized conformance
  mask per frame BEFORE the fold, clean rows fold bit-exact, rejects go
  to a typed, bounded, content-addressed Arrow quarantine sidecar
  (`RowGate`, `QuarantineSidecar`);
- :mod:`.prefetch` — the double-buffered host->device feed pipeline the
  engine's device pass pulls batches through
  (`PrefetchingBatchIterator`, ``DEEQU_TPU_PREFETCH_DEPTH``).
"""

from ..exceptions import (
    FeedDisconnectError,
    FeedStallError,
    MalformedFrameError,
)
from .arrow_stream import (
    CHECKSUM_HEADER,
    BoundedReader,
    IngestReport,
    encode_ipc_stream,
    fold_stream,
    fold_stream_reader,
    iter_frames,
)
from .columnar import as_dataset, payload_bytes
from .endpoint import INGEST_PREFIX, IngestEndpoint
from .rowgate import (
    DEFAULT_QUARANTINE_MAX_ROWS,
    QUARANTINE_MAX_ROWS_ENV,
    FrameQuarantinedError,
    QuarantineSidecar,
    RowGate,
    quarantine_max_rows,
)
from .prefetch import (
    DEFAULT_FEED_STALL_S,
    DEFAULT_PREFETCH_DEPTH,
    FEED_STALL_ENV,
    PREFETCH_DEPTH_ENV,
    PrefetchingBatchIterator,
    feed_stall_s,
    prefetch_depth,
)

__all__ = [
    "as_dataset", "payload_bytes",
    "encode_ipc_stream", "iter_frames", "fold_stream", "IngestReport",
    "fold_stream_reader", "BoundedReader",
    "CHECKSUM_HEADER", "INGEST_PREFIX", "IngestEndpoint",
    "PrefetchingBatchIterator", "prefetch_depth", "feed_stall_s",
    "PREFETCH_DEPTH_ENV", "DEFAULT_PREFETCH_DEPTH",
    "FEED_STALL_ENV", "DEFAULT_FEED_STALL_S",
    "MalformedFrameError", "FeedDisconnectError", "FeedStallError",
    "RowGate", "QuarantineSidecar", "FrameQuarantinedError",
    "quarantine_max_rows", "QUARANTINE_MAX_ROWS_ENV",
    "DEFAULT_QUARANTINE_MAX_ROWS",
]
