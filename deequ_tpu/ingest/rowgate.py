"""Row-level ingest gating: the streaming promotion of
`deequ_tpu.schema` onto the Arrow ingest path.

The reference's row-level validator (`schema/RowLevelSchemaValidator.
scala:25-223`) is a BATCH tool: hand it a DataFrame, get a valid/invalid
split back. At fleet scale the split has to happen on the WIRE, before
anything folds — one tenant's malformed rows must never reach a session's
persisted algebraic states, and the rejected rows must stay recoverable
for producer triage rather than vanishing into a counter. This module is
that gate:

- **one vectorized conformance mask per frame** — the gate calls
  :func:`deequ_tpu.schema.compute_conformance`, the EXACT pass the batch
  validator uses, so the two paths can never diverge on a verdict (pinned
  by the ported ``RowLevelSchemaValidatorTest`` scenarios, run against
  both);
- **clean rows fold bit-exact** — the accept side is an Arrow
  ``table.filter`` of the ORIGINAL buffers (no pandas round-trip, no
  cast), so folding the gated stream equals folding a pre-filtered copy
  of it, metric for metric;
- **typed, bounded, content-addressed quarantine** — rejected rows write
  as Arrow IPC sidecar files named by their payload checksum (the
  partition store's ``.quarantine`` convention), bounded by
  ``DEEQU_TPU_ROWGATE_QUARANTINE_MAX_ROWS`` with overflow counted, and
  :meth:`QuarantineSidecar.read_all` decodes them back to exactly the
  rejected rows;
- **a frame with ZERO conforming rows raises** a typed
  :class:`FrameQuarantinedError` (HTTP 422 on the endpoint) — folding
  nothing silently would report SUCCESS for a producer whose every row
  is garbage;
- the ``row_gate`` fault site wires the gate into the chaos plane
  (`deequ_tpu.reliability.faults`): an injected ``corrupt`` fault stands
  in for a frame whose mask cannot even be computed.

Gate policy normally arrives from the tenant catalog
(`deequ_tpu.service.catalog`, the ``row_gate`` document section); the
class is equally constructible by hand for in-process streams.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

_logger = logging.getLogger(__name__)

from ..exceptions import MetricCalculationRuntimeException
from ..schema import RowLevelSchema, compute_conformance
from ..utils import env_number

#: row budget of one QuarantineSidecar before overflow rows are DROPPED
#: (counted, never silently): a producer whose every frame is garbage
#: must not fill the disk with its own rejects. Warn-once parser.
QUARANTINE_MAX_ROWS_ENV = "DEEQU_TPU_ROWGATE_QUARANTINE_MAX_ROWS"
DEFAULT_QUARANTINE_MAX_ROWS = 100_000


def quarantine_max_rows() -> int:
    return int(env_number(
        QUARANTINE_MAX_ROWS_ENV, DEFAULT_QUARANTINE_MAX_ROWS, int, minimum=0
    ))


class FrameQuarantinedError(MetricCalculationRuntimeException):
    """Every row of an ingest frame failed the tenant's row-level schema:
    nothing folded, the whole frame went to the quarantine sidecar.
    Raised INSTEAD of folding an empty delta — a producer whose entire
    output is nonconforming must hear a typed rejection (HTTP 422), not a
    SUCCESS verdict computed over zero of its rows. Partial rejections do
    NOT raise: the conforming rows fold, the rest quarantine, and the
    split surfaces on the ``deequ_service_rowgate_*`` series."""

    def __init__(self, tenant: str, dataset: str, rows: int,
                 detail: str = ""):
        self.tenant = str(tenant)
        self.dataset = str(dataset)
        self.rows = int(rows)
        super().__init__(
            f"all {rows} row(s) of a frame for {tenant}/{dataset} failed "
            "row-level schema validation; nothing folded, the frame is "
            "quarantined" + (f": {detail}" if detail else "")
        )


def describe_rowgate_metrics(metrics) -> None:
    """Register HELP text for every export-plane series the row gate
    increments (idempotent). Literal per-series calls — the statlint
    export-completeness check matches these statically."""
    metrics.describe(
        "deequ_service_rowgate_frames_total",
        "Ingest frames that passed through a row-level gate (clean and "
        "split frames both count).",
    )
    metrics.describe(
        "deequ_service_rowgate_rows_total",
        "Rows ACCEPTED by row-level gates (the clean side of the split "
        "that went on to fold).",
    )
    metrics.describe(
        "deequ_service_rowgate_rejected_rows_total",
        "Rows rejected by row-level gates and routed to the quarantine "
        "sidecar (never folded).",
    )
    metrics.describe(
        "deequ_service_rowgate_quarantined_frames_total",
        "Frames FULLY rejected by a row-level gate (typed "
        "FrameQuarantinedError; HTTP 422; nothing folded).",
    )
    metrics.describe(
        "deequ_service_rowgate_quarantine_bytes_total",
        "Arrow IPC bytes written to row-gate quarantine sidecars.",
    )
    metrics.describe(
        "deequ_service_rowgate_quarantine_dropped_rows_total",
        "Rejected rows DROPPED because the quarantine sidecar hit its "
        "row budget (DEEQU_TPU_ROWGATE_QUARANTINE_MAX_ROWS).",
    )


def _sanitize(component: str) -> str:
    from urllib.parse import quote

    return quote(str(component), safe="")


class QuarantineSidecar:
    """Bounded, content-addressed Arrow quarantine for rejected rows.

    Layout: ``<root>/t-<tenant>/d-<dataset>/<checksum>.arrows`` — each
    file one Arrow IPC stream of rejected rows, named by the xxhash64
    checksum of its own payload (the partition store's ``.quarantine``
    naming), so re-quarantining identical rejects is idempotent and every
    file self-verifies. Bounded by ``max_rows`` across the sidecar's
    lifetime in this process; overflow rows are counted and dropped,
    never written. Writes are best-effort: a full disk must not turn a
    survivable rejection into a crash (the rows still COUNT as rejected
    either way — the gate's accept side never depends on the sidecar)."""

    def __init__(self, path: str, max_rows: Optional[int] = None):
        self.path = str(path)
        self.max_rows = (
            quarantine_max_rows() if max_rows is None else int(max_rows)
        )
        self._lock = threading.Lock()
        self.rows_written = 0
        self.rows_dropped = 0
        self.bytes_written = 0

    def quarantine(self, table, tenant: str, dataset: str) -> int:
        """Write ``table``'s rows (an arrow Table of rejects) into the
        sidecar, honoring the row budget. Returns the bytes written (0
        when the budget dropped everything or the write failed)."""
        from .arrow_stream import encode_ipc_stream

        with self._lock:
            budget = (
                max(self.max_rows - self.rows_written, 0)
                if self.max_rows else table.num_rows
            )
            keep = min(int(table.num_rows), budget)
            dropped = int(table.num_rows) - keep
            self.rows_dropped += dropped
            self.rows_written += keep
        if keep == 0:
            return 0
        payload = encode_ipc_stream(table.slice(0, keep))
        from .. import io as dio
        from ..integrity import checksum_bytes

        side_dir = dio.join(
            self.path, f"t-{_sanitize(tenant)}", f"d-{_sanitize(dataset)}"
        )
        name = f"{checksum_bytes(payload)}.arrows"
        try:
            dio.makedirs(side_dir)
            with dio.open_file(dio.join(side_dir, name), "wb") as fh:
                fh.write(payload)
        except Exception:  # noqa: BLE001 - best-effort preservation
            _logger.warning(
                "could not write row-gate quarantine sidecar under %s",
                side_dir, exc_info=True,
            )
            return 0
        with self._lock:
            self.bytes_written += len(payload)
        return len(payload)

    def read_all(self, tenant: str, dataset: str):
        """Decode every sidecar file for ``(tenant, dataset)`` back into
        ONE arrow Table of the rejected rows (None when nothing was
        quarantined) — the triage/acceptance read path: the quarantine
        must decode back to exactly the rows the gate rejected."""
        import pyarrow as pa

        from .. import io as dio

        side_dir = dio.join(
            self.path, f"t-{_sanitize(tenant)}", f"d-{_sanitize(dataset)}"
        )
        def plain(table):
            # frames arrive with per-frame encoding decisions (adaptive
            # dictionary encoding probes each dataset independently), so
            # sibling sidecar files can disagree on a column's encoding;
            # decode to the value type so the concat is one uniform table
            # of the rejected VALUES
            for i, f in enumerate(table.schema):
                if pa.types.is_dictionary(f.type):
                    table = table.set_column(
                        i, f.name, table.column(i).cast(f.type.value_type)
                    )
            return table

        tables = []
        for name in dio.list_files(side_dir):
            if not name.endswith(".arrows"):
                continue
            with dio.open_file(dio.join(side_dir, name), "rb") as fh:
                with pa.ipc.open_stream(fh.read()) as reader:
                    tables.append(plain(reader.read_all()))
        if not tables:
            return None
        return pa.concat_tables(tables)


class RowGate:
    """The per-session streaming gate: one conformance mask per frame,
    BEFORE the fold. Stateless between frames except the sidecar's row
    budget; thread-safety rides the session's fold serialization (the
    gate runs on the ingest caller's thread, before submission)."""

    def __init__(
        self,
        schema: RowLevelSchema,
        *,
        sidecar: Optional[QuarantineSidecar] = None,
        metrics=None,
    ):
        self.schema = schema
        self.sidecar = sidecar
        self.metrics = metrics
        if metrics is not None:
            describe_rowgate_metrics(metrics)

    def split(self, data, tenant: str, dataset: str):
        """Gate one frame: returns the Dataset of CONFORMING rows (the
        original dataset object, untouched, when every row conforms — the
        zero-copy fast path), quarantines the rest, and raises typed
        :class:`FrameQuarantinedError` when nothing conforms."""
        from ..data import Dataset
        from ..observability import trace as _trace
        from ..reliability.faults import fault_point

        # chaos site: a `corrupt` fault here stands in for a frame the
        # conformance mask cannot be computed over — surfaced typed
        # BEFORE anything folds, exactly like a real undecodable frame
        fault_point("row_gate", tag=f"{tenant}/{dataset}")
        table = data.arrow
        # convert only the columns the schema reads, as bare Series: the
        # mask is row-level, so the frame's other (often wide, often
        # numeric) columns never pay the pandas hop — and the gated ones
        # skip DataFrame construction entirely
        names = set(table.schema.names)
        cols = {
            cd.name: table.column(cd.name).to_pandas()
            for cd in self.schema.column_definitions
            if cd.name in names
        }
        n = int(table.num_rows)
        matches, _ = compute_conformance(cols, self.schema, num_rows=n)
        accepted = int(matches.sum())
        labels = {"tenant": tenant, "dataset": dataset}
        updates = [
            ("deequ_service_rowgate_frames_total", 1.0, labels),
            ("deequ_service_rowgate_rows_total", float(accepted), labels),
        ]
        if accepted == n:
            if self.metrics is not None:
                self.metrics.inc_many(updates)
            return data
        import pyarrow as pa

        mask = pa.array(matches)
        rejected = table.filter(pa.array(~matches))
        quarantine_bytes = 0
        if self.sidecar is not None:
            dropped_before = self.sidecar.rows_dropped
            quarantine_bytes = self.sidecar.quarantine(
                rejected, tenant, dataset
            )
            dropped = self.sidecar.rows_dropped - dropped_before
            if dropped:
                updates.append((
                    "deequ_service_rowgate_quarantine_dropped_rows_total",
                    float(dropped), labels,
                ))
            if quarantine_bytes:
                updates.append((
                    "deequ_service_rowgate_quarantine_bytes_total",
                    float(quarantine_bytes), labels,
                ))
        updates.append((
            "deequ_service_rowgate_rejected_rows_total",
            float(n - accepted), labels,
        ))
        _trace.add_event(
            "rowgate_rejected", session=f"{tenant}/{dataset}",
            rows=n - accepted, accepted=accepted,
            quarantine_bytes=quarantine_bytes,
        )
        if accepted == 0:
            updates.append((
                "deequ_service_rowgate_quarantined_frames_total", 1.0, labels,
            ))
            if self.metrics is not None:
                self.metrics.inc_many(updates)
            exc = FrameQuarantinedError(tenant, dataset, n)
            from ..observability import record_failure

            # a fully-rejected frame is a typed failure an operator will
            # want the trace artifact for (which producer, which frame)
            record_failure(exc)
            raise exc
        if self.metrics is not None:
            self.metrics.inc_many(updates)
        # the accept side filters the ORIGINAL arrow buffers: no pandas
        # hop, no cast — folding these rows is bit-exact with folding a
        # pre-filtered copy of the producer's stream. probe_encoding=False
        # because this is a derived view of an already-probed dataset: the
        # parent's dictionary-encoding verdict stands, so a filtered frame
        # can never drift its session's schema contract by re-probing a
        # now-smaller column as low-cardinality
        return Dataset(table.filter(mask), probe_encoding=False)
