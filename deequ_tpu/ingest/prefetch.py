"""Double-buffered host->device feed pipeline.

The engine's device pass is a chain of async XLA dispatches; what gates it
is the HOST side of each batch — feature build plus the ``jax.device_put``
host->device copy. This module is the reusable seam: a dedicated feed
thread stages batch k+1 (and with depth 2, k+2) — building features and
starting its device transfer — while batch k's fold executes, so the
transfer time hides under device compute instead of serializing with it.
Batch shapes stay pow2-bucketed upstream (`service.streaming` /
`runners.engine.effective_batch_size`), so staging ahead never provokes a
recompile — every staged batch reuses the one compiled program shape.

``DEEQU_TPU_PREFETCH_DEPTH`` sizes the pipeline (default 2 = classic
double buffering: one batch in flight on device, one staged, one being
built). ``0`` disables the feed thread entirely — batches produce inline
on the consumer thread — which is the measured "serial" baseline the
PERF.md overlap numbers compare against. Unparseable values warn once and
keep the default (the watchdog env convention).

Failure contract: an exception inside the feed thread (a poisoned batch,
an injected ``feed_stall``, a device_put infrastructure error) propagates
to the consumer on its next pull — same semantics as the inline path —
and the pipeline shuts down; a feed thread that goes SILENT (a hung
transfer that neither returns nor raises) trips the consumer's stall
deadline (``DEEQU_TPU_FEED_STALL_S``, default 120s, <=0 disables) as a
typed ``FeedStallError``, which is a ``DeviceFailureException`` — the
pass fails over to the host tier exactly like a thrown device fault. The
``prefetch`` fault site fires before each staged batch so chaos tests
can wedge or kill the feed on demand.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional

#: env var sizing the staged-batch pipeline (0 = serial, no feed thread)
PREFETCH_DEPTH_ENV = "DEEQU_TPU_PREFETCH_DEPTH"
DEFAULT_PREFETCH_DEPTH = 2

#: env var: seconds the consumer waits on a silent feed thread before
#: declaring it wedged with a typed FeedStallError (<= 0 disables).
#: Generous by default — a healthy produce is sub-second per batch, and a
#: first-batch tunnel transfer is seconds — so only a genuinely hung
#: device_put / wedged source trips it.
FEED_STALL_ENV = "DEEQU_TPU_FEED_STALL_S"
DEFAULT_FEED_STALL_S = 120.0

def prefetch_depth() -> int:
    """The configured pipeline depth (env override > tuned > static 2);
    warn-and-fallback on bad values."""
    from ..tuning import knobs

    return knobs.value("prefetch_depth")


def feed_stall_s() -> float:
    """The configured feed-stall deadline (<= 0 = disabled);
    warn-and-fallback on bad values."""
    from ..utils import env_number

    return env_number(FEED_STALL_ENV, DEFAULT_FEED_STALL_S, float)


def staging_depth(n_batches: int):
    """The pipeline depth for a pass of ``n_batches``: ``None`` (the
    configured depth) for multi-batch passes, ``0`` (inline, no feed
    thread) for a single-batch pass. Double-buffering a one-batch fold
    has nothing to overlap with, so the feed thread's spawn/teardown is
    pure fixed cost — measurable on the streaming plane, where every
    micro-batch fold is a one-batch pass (the ~50ms/fold knee diet). The
    inline path keeps the ``prefetch`` fault site and identical ordering,
    so semantics are unchanged — this is the documented "serial" mode
    applied exactly where serial is optimal."""
    return 0 if n_batches <= 1 else None


#: queue sentinel kinds
_ITEM, _DONE, _ERROR = 0, 1, 2


class PrefetchingBatchIterator:
    """Iterate ``produce()`` results through a bounded staging pipeline.

    ``produce`` is called repeatedly on the feed thread; it returns the
    next staged item or ``None`` at end of input (the engine's existing
    producer contract). Up to ``depth`` finished items wait in the stage
    queue while the consumer folds; ``depth=0`` degenerates to calling
    ``produce`` inline (no thread, bit-identical ordering).

    The iterator is a context manager; exiting (or ``close()``) tears the
    feed thread down even when the consumer stopped early."""

    def __init__(
        self,
        produce: Callable[[], Optional[Any]],
        *,
        depth: Optional[int] = None,
        stall_timeout_s: Optional[float] = None,
        name: str = "deequ-ingest-prefetch",
    ):
        self._produce = produce
        self.depth = prefetch_depth() if depth is None else max(0, int(depth))
        #: how long the consumer tolerates a SILENT feed thread before
        #: raising typed FeedStallError (<= 0 disables the deadline)
        self.stall_timeout_s = (
            feed_stall_s() if stall_timeout_s is None else float(stall_timeout_s)
        )
        self._closed = threading.Event()
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._index = 0
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._feed_loop, name=name, daemon=True
            )
            self._thread.start()

    # -- feed thread ---------------------------------------------------------

    def _feed_loop(self) -> None:
        from ..reliability.faults import fault_point

        index = 0
        while not self._closed.is_set():
            try:
                # chaos site: an injected feed_stall wedges/kills the feed
                # exactly where a real transfer thread would
                fault_point("prefetch", tag=str(index))
                item = self._produce()
            except BaseException as exc:  # noqa: BLE001 - propagate to
                # the consumer: KeyboardInterrupt-class injections must
                # ride out exactly like on the inline path
                self._put((_ERROR, exc))
                return
            if item is None:
                self._put((_DONE, None))
                return
            if not self._put((_ITEM, item)):
                return  # consumer closed while we were staging
            index += 1

    def _put(self, entry) -> bool:
        """Bounded put that aborts when the consumer closed the pipeline
        (a consumer that stopped early must not leave this thread parked
        on a full queue forever)."""
        while not self._closed.is_set():
            try:
                self._queue.put(entry, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer side -------------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self.depth == 0:
            from ..reliability.faults import fault_point

            fault_point("prefetch", tag=str(self._index))
            self._index += 1
            item = self._produce()
            if item is None:
                raise StopIteration
            return item
        if self._closed.is_set():
            raise StopIteration
        deadline = self.stall_timeout_s
        try:
            if deadline and deadline > 0:
                kind, value = self._queue.get(timeout=deadline)
            else:
                kind, value = self._queue.get()
        except queue.Empty:
            # the feed thread went SILENT past the stall deadline (a hung
            # device_put, a wedged source): declare it typed — a
            # DeviceFailureException, so the pass fails over to the host
            # tier, whose chunk iteration shares none of this machinery
            from ..exceptions import FeedStallError

            self.close()
            raise FeedStallError(
                "prefetch",
                f"feed thread produced nothing for {deadline:.0f}s",
            ) from None
        if kind == _ITEM:
            return value
        self._closed.set()
        if kind == _ERROR:
            raise value
        raise StopIteration

    def close(self) -> None:
        """Tear the pipeline down (idempotent): wakes a feed thread parked
        on a full queue and joins it. Staged-but-unconsumed items are
        dropped — the consumer abandoning a pass does exactly that."""
        self._closed.set()
        if self._thread is not None:
            # drain so a blocked put's retry loop sees closed immediately
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "PrefetchingBatchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
