"""In-process columnar ingestion: any columnar payload becomes a
:class:`~deequ_tpu.data.Dataset` WITHOUT a pandas hop.

The streaming service's documented input used to be a Dataset the caller
built themselves — and the path of least resistance was
``Dataset.from_pandas(df)``, which materializes every column through a
DataFrame even when the producer already holds numpy arrays or Arrow
record batches. This module is the single coercion point: dict-of-numpy
feeds go straight through ``pa.array`` (zero-copy for numeric dtypes),
Arrow tables/record batches wrap as-is (dictionary-encoded columns keep
their encoding, so string dict columns ride the cached distinct-value
hash path the engine already has), and only an actual DataFrame pays the
pandas conversion.
"""

from __future__ import annotations

import weakref
from typing import Any, Mapping

from ..data import Dataset

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is in the base image
    pa = None

#: table -> Dataset identity cache (weak: an entry lives exactly as long
#: as the caller's table object). A fleet fan-out feeds the SAME payload
#: object to many sessions — 1000 sessions ingesting one broadcast slice
#: built 1000 Datasets, re-running dictionary probes and re-deriving
#: per-column caches per session (measured as a top fold cost in the
#: streaming-knee soak). Arrow tables are immutable, so one Dataset per
#: table object is always valid.
_DATASET_CACHE: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def _cached_dataset(table: "pa.Table") -> Dataset:
    key = id(table)
    ds = _DATASET_CACHE.get(key)
    # the weak value keeps the mapping honest: a dead Dataset drops its
    # entry, and we pin the source table ON the Dataset (ds.arrow is the
    # probed/encoded table, not necessarily `table`) so a recycled id()
    # can never alias a different live table
    if ds is not None and getattr(ds, "_source_table", None) is table:
        return ds
    ds = Dataset(table)
    ds._source_table = table
    _DATASET_CACHE[key] = ds
    return ds


def as_dataset(data: Any) -> Dataset:
    """Coerce a columnar payload into a :class:`Dataset`.

    Accepted shapes, cheapest first:

    - ``Dataset`` — returned unchanged (no copy, derived caches kept);
    - ``pyarrow.Table`` / ``pyarrow.RecordBatch`` — wrapped directly
      (record batches become single-batch tables; zero-copy);
    - ``Mapping[str, numpy.ndarray | list]`` — each array passes through
      ``pa.array`` (zero-copy for numeric numpy dtypes), no pandas. Float
      columns follow the NUMPY missing-value convention: ``NaN`` marks a
      null (numpy has no validity mask), so a dict-fed session computes
      the same completeness/means a pandas- or Arrow-fed one does;
    - a pandas ``DataFrame`` — the legacy path, via ``Dataset.from_pandas``.
    """
    if isinstance(data, Dataset):
        return data
    if pa is not None:
        if isinstance(data, pa.Table):
            return _cached_dataset(data)
        if isinstance(data, pa.RecordBatch):
            return Dataset(pa.Table.from_batches([data]))
    if isinstance(data, Mapping):
        # from_pandas=True is pyarrow's "NaN means null" switch (it does
        # NOT involve pandas): without it a float NaN stays a VALUE and a
        # dict-fed session would silently disagree with every other feed
        # on completeness and every NaN-poisoned aggregate
        arrays = {
            name: pa.array(vals, from_pandas=True)
            for name, vals in data.items()
        }
        return Dataset(pa.table(arrays))
    # a DataFrame (or anything pandas-like exposing columns): the one
    # remaining path that pays object materialization
    if hasattr(data, "columns") and hasattr(data, "dtypes"):
        return Dataset.from_pandas(data)
    raise TypeError(
        "cannot ingest object of type "
        f"{type(data).__name__}: expected Dataset, pyarrow Table/"
        "RecordBatch, dict of arrays, or pandas DataFrame"
    )


def payload_bytes(data: Dataset) -> int:
    """Wire-equivalent size of a dataset's columnar buffers (what the
    ingest byte counters report for in-process feeds, so the export plane's
    MB/s means the same thing whether a batch arrived over HTTP or by
    reference). Memoized per Dataset: ``Table.nbytes`` on a sliced table
    walks every buffer (~0.4ms, measured as a per-fold cost on the
    streaming plane), and the table is immutable."""
    cached = getattr(data, "_payload_nbytes", None)
    if cached is not None:
        return cached
    try:
        n = int(data.arrow.nbytes)
    except Exception:  # noqa: BLE001 - accounting must never fail a fold
        n = 0
    data._payload_nbytes = n
    return n
