"""HTTP ingest endpoint: the Arrow IPC wire frontend on the export plane.

``POST /ingest/v1/<tenant>/<dataset>`` with an Arrow IPC stream body
folds each record batch into the named streaming session, one atomic
micro-batch merge per frame, and answers with the fold report as JSON.
The endpoint rides the existing :class:`~deequ_tpu.service.metrics.
MetricsExporter` HTTP plane (same server, same port as ``/metrics``), so
a service that exports metrics already has an ingest socket.

Contract:

- the session must already exist (created by the operator with its
  checks via ``service.session(...)``): an unknown session is 404 — the
  endpoint never auto-creates a zero-check session that would verify
  nothing and always report SUCCESS;
- ``X-Deequ-Checksum`` (optional) carries the xxhash64 hex digest of the
  raw body; a mismatch is 400 and nothing folds;
- bounded admission maps to 429 (``ServiceOverloaded`` — the scheduler
  shed the fold), schema drift to 409, a closed session to 410, a closed
  service to 503, malformed frames to 400;
- a client that disconnects mid-body tears the stream typed: complete
  leading frames stay committed, the torn tail never folds, and the
  disconnect is counted (no response can reach a dead client, so the
  counters + flight record ARE the observable). If the request DECLARED
  a checksum, a torn body can never verify it — nothing folds at all,
  because folding unverified frames would bypass the digest tripwire.
"""

from __future__ import annotations

import json
import logging
from typing import Optional, Tuple

_logger = logging.getLogger(__name__)

from ..exceptions import (
    FeedDisconnectError,
    MalformedFrameError,
    SchemaDriftError,
)
from .arrow_stream import (
    CHECKSUM_HEADER,
    describe_ingest_metrics,
    fold_stream,
)

#: route prefix the exporter dispatches to this endpoint
INGEST_PREFIX = "/ingest/v1/"


def _unquote(component: str) -> str:
    from urllib.parse import unquote

    return unquote(component)



def _typed_error_response(exc, metrics, labels, source) -> Tuple[int, dict]:
    """One shared mapping from typed fold errors to HTTP responses —
    the buffered and incremental paths must never diverge on status
    semantics."""
    if isinstance(exc, MalformedFrameError):
        return 400, {"error": "malformed_frame", "detail": str(exc)}
    if isinstance(exc, SchemaDriftError):
        return 409, {"error": "schema_drift", "detail": str(exc)}
    from .rowgate import FrameQuarantinedError

    if isinstance(exc, FrameQuarantinedError):
        # every row of the frame failed the tenant's row-level schema:
        # nothing folded, the frame sits in the quarantine sidecar —
        # 422 (the payload is well-FORMED Arrow, its CONTENT is
        # unprocessable), distinct from the 400 decode failures
        return 422, {"error": "frame_quarantined", "detail": str(exc)}
    from ..service.errors import (
        JobFailed,
        JobTimeout,
        QuotaExceeded,
        ServiceClosed,
        ServiceOverloaded,
        SessionClosed,
    )

    # QuotaExceeded BEFORE its ServiceOverloaded parent: both are 429,
    # but the body must tell the producer whether ITS budget or the
    # GLOBAL queue was the limit (the remedies differ: back off vs
    # retry-later)
    if isinstance(exc, QuotaExceeded):
        metrics.inc("deequ_service_ingest_shed_total", **labels)
        return 429, {"error": "quota_exceeded", "detail": str(exc),
                     "resource": exc.resource}
    if isinstance(exc, ServiceOverloaded):
        metrics.inc("deequ_service_ingest_shed_total", **labels)
        return 429, {"error": "overloaded", "detail": str(exc)}
    if isinstance(exc, SessionClosed):
        return 410, {"error": "session_closed"}
    if isinstance(exc, ServiceClosed):
        return 503, {"error": "service_closed"}
    if isinstance(exc, JobTimeout):
        return 504, {"error": "fold_timeout", "detail": str(exc)}
    if isinstance(exc, JobFailed):
        return 500, {"error": "fold_failed", "detail": str(exc)}
    _logger.warning("ingest %s: unexpected failure", source, exc_info=True)
    return 500, {"error": "internal", "detail": str(exc)}


class IngestEndpoint:
    """Stateless request handler bound to one VerificationService."""

    def __init__(self, service):
        self.service = service
        describe_ingest_metrics(service.metrics)

    # -- routing -------------------------------------------------------------

    def matches(self, path: str) -> bool:
        return path.startswith(INGEST_PREFIX)

    def parse_target(self, path: str) -> Optional[Tuple[str, str]]:
        rest = path[len(INGEST_PREFIX):]
        if "?" in rest:
            rest = rest.split("?", 1)[0]
        parts = [p for p in rest.split("/") if p]
        if len(parts) != 2:
            return None
        return _unquote(parts[0]), _unquote(parts[1])

    # -- request handling ----------------------------------------------------

    def handle_post(self, path: str, headers, rfile) -> Tuple[int, dict]:
        """Process one POST; returns ``(http_status, json_body)``. Never
        raises — every failure mode maps to a typed JSON error body (the
        transport layer decides whether a client is still there to read
        it).

        ``X-Deequ-Trace`` (optional) carries a serialized trace context
        from the producer: the request span — and every fold/decode span
        under it — parents into the REMOTE trace, so a cross-process
        ingest shows up as one trace_id end to end."""
        from ..observability import trace as _trace

        parent = _trace.extract(headers.get(_trace.TRACE_HEADER))
        sp = _trace.start_span(
            "ingest_request", kind="ingest", attrs={"path": path},
            parent=parent if parent is not None else "auto",
        )
        with _trace.attach(sp):
            try:
                status, body = self._handle_post_traced(
                    path, headers, rfile
                )
            except BaseException as exc:
                if sp is not _trace.NULL:
                    sp.set_attr("error", f"{type(exc).__name__}: {exc}")
                sp.finish("error")
                raise
        if sp is not _trace.NULL:
            sp.set_attr("status", status)
        sp.finish("ok" if status < 400 else "error")
        return status, body

    def _handle_post_traced(
        self, path: str, headers, rfile
    ) -> Tuple[int, dict]:
        target = self.parse_target(path)
        if target is None:
            return 404, {"error": "not_found", "detail": (
                f"expected {INGEST_PREFIX}<tenant>/<dataset>"
            )}
        tenant, dataset = target
        session = self.service.get_session(tenant, dataset,
                                           include_closed=True)
        plane = getattr(self.service, "catalog_plane", None)
        if session is None and plane is not None and plane.catalog.registered(
            tenant
        ):
            # catalog auto-open: a REGISTERED tenant's first POST
            # materializes its session from the catalog document (checks,
            # gate, quotas, watches all from the declarative suite) — the
            # cold->hot promotion of the tenant tiering. UNREGISTERED
            # tenants keep the 404 below: the endpoint still never
            # invents a zero-check session.
            from ..service.catalog import CatalogError

            try:
                session = plane.ensure_session(tenant, dataset)
            except CatalogError as exc:
                # registered but unservable (every version corrupt, no
                # last-good): the tenant EXISTS, the catalog is the sick
                # part — 503 so the producer retries after the operator
                # repairs the document, instead of a 404 baiting it into
                # re-registering
                return 503, {"error": "catalog_error", "tenant": tenant,
                             "detail": str(exc)}
        if session is None:
            return 404, {"error": "unknown_session", "tenant": tenant,
                         "dataset": dataset, "detail": (
                             "create the session (with its checks) via "
                             "service.session(), or register the tenant "
                             "in the catalog, before feeding it"
                         )}
        if session.closed:
            # "gone", not "never existed": the documented 410 contract —
            # a producer retrying on 404 by re-registering must NOT be
            # told to do that for a deliberately closed session
            return 410, {"error": "session_closed", "tenant": tenant,
                         "dataset": dataset}
        if plane is not None:
            # the fold-boundary hook: touch the tenant's hot-tier idle
            # clock and (debounced) poll its document version, hot-
            # reloading the session when the catalog was edited —
            # tolerant of sessions the plane did not open
            plane.on_fold_boundary(session)
        metrics = self.service.metrics
        labels = {"tenant": tenant, "dataset": dataset}
        try:
            declared = int(headers.get("Content-Length", "0"))
        except ValueError:
            return 411, {"error": "length_required"}
        if declared <= 0:
            return 411, {"error": "length_required"}
        source = f"http:{tenant}/{dataset}"
        checksum = headers.get(CHECKSUM_HEADER)
        if checksum is None:
            # INCREMENTAL decode: frames fold as they arrive off the
            # socket — a GB-scale stream holds one frame in memory, not
            # its whole body. Only possible WITHOUT a declared digest:
            # a checksum must verify over the complete payload before
            # anything folds (the tripwire contract), so checksummed
            # requests keep the buffered path below.
            return self._handle_incremental(
                session, rfile, declared, source, metrics, labels
            )
        try:
            body = rfile.read(declared)
        except OSError:
            # socket timeout/reset mid-body: whatever partial data the
            # buffered reader held is gone with the raise — a pure
            # disconnect, nothing decodable arrived
            metrics.inc("deequ_service_ingest_disconnects_total", **labels)
            from ..observability import record_failure

            record_failure(FeedDisconnectError(source, detail="socket error"))
            return 400, {"error": "feed_disconnect", "received_bytes": 0,
                         "declared_bytes": declared}
        if len(body) < declared:
            # the producer DECLARED a digest and a torn body can never
            # verify it: folding unverified leading frames would bypass
            # the exact tripwire the digest exists for (a flipped byte
            # decodes silently in Arrow IPC), so nothing folds. (Digest-
            # free requests never reach here — they ride the incremental
            # path, whose disconnect contract folds the whole leading
            # frames.)
            metrics.inc(
                "deequ_service_ingest_disconnects_total", **labels
            )
            from ..observability import record_failure

            record_failure(FeedDisconnectError(
                source, bytes_read=len(body),
                detail="checksummed stream torn; nothing folded",
            ))
            return 400, {
                "error": "feed_disconnect",
                "declared_bytes": declared,
                "received_bytes": len(body),
                "detail": "declared checksum cannot be verified on a "
                          "torn body; nothing folded",
            }
        try:
            report = fold_stream(
                session, body, checksum=checksum, complete=True,
                source=source,
            )
        except Exception as exc:  # noqa: BLE001 - typed service errors
            return _typed_error_response(exc, metrics, labels, source)
        return 200, {"ok": True, **report.to_dict()}

    def _handle_incremental(
        self, session, rfile, declared: int, source: str, metrics, labels
    ) -> Tuple[int, dict]:
        """Unbuffered body handling: Arrow frames decode straight off the
        socket and fold one by one — memory holds one frame, not the
        declared Content-Length. Torn-tail semantics are the documented
        disconnect contract (complete leading frames stay committed, the
        tail never folds, the tear is counted + flight-recorded by the
        fold machinery)."""
        from .arrow_stream import BoundedReader, fold_stream_reader

        reader = BoundedReader(rfile, declared)
        try:
            report = fold_stream_reader(session, reader, source=source)
        except FeedDisconnectError:
            return 400, {
                "error": "feed_disconnect",
                "declared_bytes": declared,
                "received_bytes": reader.bytes_read,
            }
        except Exception as exc:  # noqa: BLE001 - typed service errors
            # drain the remainder so a keep-alive connection stays framed
            # (the client may still be sending)
            reader.drain()
            return _typed_error_response(exc, metrics, labels, source)
        reader.drain()
        if reader.bytes_read < declared:
            # every frame decoded but the body came up short (the length
            # header lied high): still a disconnect for accounting —
            # the buffered path's exact contract
            metrics.inc("deequ_service_ingest_disconnects_total", **labels)
            return 400, {
                "error": "feed_disconnect",
                "declared_bytes": declared,
                "received_bytes": reader.bytes_read,
            }
        return 200, {"ok": True, **report.to_dict()}


def render_response(status: int, body: dict) -> bytes:
    return json.dumps(body, sort_keys=True).encode()
