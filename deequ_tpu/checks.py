"""Check DSL: a named, immutable group of constraints with ~40 fluent
factories (reference `checks/Check.scala:60-974`). Each factory returns a NEW
Check (or a CheckWithLastConstraintFilterable allowing ``.where(...)`` to
rebuild the last constraint with a row filter, reference
`checks/CheckWithLastConstraintFilterable.scala:22-54`).
"""

from __future__ import annotations

import enum
from typing import Any, Callable, List, Optional, Sequence, Set, Tuple

from . import constraints as C
from .analyzers import Analyzer, Patterns
from .constraints import (
    AnalysisBasedConstraint,
    ConstrainableDataTypes,
    Constraint,
    ConstraintDecorator,
    ConstraintResult,
    ConstraintStatus,
)


class CheckLevel(enum.Enum):
    ERROR = "Error"
    WARNING = "Warning"


class CheckStatus(enum.Enum):
    SUCCESS = "Success"
    WARNING = "Warning"
    ERROR = "Error"

    @property
    def severity(self) -> int:
        return {"Success": 0, "Warning": 1, "Error": 2}[self.value]


class CheckResult:
    def __init__(self, check: "Check", status: CheckStatus, constraint_results):
        self.check = check
        self.status = status
        self.constraint_results = list(constraint_results)


def is_one(value: float) -> bool:
    """The default assertion (reference `Check.IsOne`)."""
    return value == 1.0


def contained_in_predicate(column: str, allowed_values) -> str:
    """Null-tolerant membership predicate shared by ``is_contained_in`` and
    the categorical suggestion rules. Numeric literals stay numeric so
    numeric columns can match their allowed set."""
    literals = ", ".join(
        repr(v) if isinstance(v, str) else repr(float(v))
        if isinstance(v, float) else str(v)
        for v in allowed_values
    )
    return f"({column} is None) or ({column} in [{literals}])"


class Check:
    """(reference `checks/Check.scala:60-94`)."""

    def __init__(
        self,
        level: CheckLevel = CheckLevel.ERROR,
        description: str = "",
        constraints: Sequence[Constraint] = (),
    ):
        self.level = level
        self.description = description
        self.constraints: Tuple[Constraint, ...] = tuple(constraints)

    # -- plumbing -----------------------------------------------------------

    def add_constraint(self, constraint: Constraint) -> "Check":
        return Check(self.level, self.description, self.constraints + (constraint,))

    def _add_filterable(
        self, creation_func: Callable[[Optional[str]], Constraint]
    ) -> "CheckWithLastConstraintFilterable":
        return CheckWithLastConstraintFilterable(
            self.level,
            self.description,
            self.constraints + (creation_func(None),),
            creation_func,
        )

    def evaluate(self, context) -> CheckResult:
        """(reference `checks/Check.scala:950-962`)."""
        results = [c.evaluate(context.metric_map) for c in self.constraints]
        any_failures = any(r.status == ConstraintStatus.FAILURE for r in results)
        if any_failures:
            status = (
                CheckStatus.ERROR if self.level == CheckLevel.ERROR else CheckStatus.WARNING
            )
        else:
            status = CheckStatus.SUCCESS
        return CheckResult(self, status, results)

    def required_analyzers(self) -> Set[Analyzer]:
        """(reference `checks/Check.scala:964-973`)."""
        out: Set[Analyzer] = set()
        for c in self.constraints:
            inner = c.inner if isinstance(c, ConstraintDecorator) else c
            if isinstance(inner, AnalysisBasedConstraint):
                out.add(inner.analyzer)
        return out

    # -- factories ----------------------------------------------------------

    def has_size(self, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.size_constraint(assertion, where, hint)
        )

    def is_complete(self, column, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.completeness_constraint(column, is_one, where, hint)
        )

    def has_completeness(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.completeness_constraint(column, assertion, where, hint)
        )

    def is_unique(self, column, hint=None) -> "Check":
        return self.add_constraint(C.uniqueness_constraint([column], is_one, hint))

    def is_primary_key(self, column, *columns, hint=None) -> "Check":
        return self.add_constraint(
            C.uniqueness_constraint([column, *columns], is_one, hint)
        )

    def has_uniqueness(self, columns, assertion, hint=None) -> "Check":
        if isinstance(columns, str):
            columns = [columns]
        return self.add_constraint(C.uniqueness_constraint(columns, assertion, hint))

    def has_distinctness(self, columns, assertion, hint=None) -> "Check":
        if isinstance(columns, str):
            columns = [columns]
        return self.add_constraint(C.distinctness_constraint(columns, assertion, hint))

    def has_unique_value_ratio(self, columns, assertion, hint=None) -> "Check":
        if isinstance(columns, str):
            columns = [columns]
        return self.add_constraint(C.unique_value_ratio_constraint(columns, assertion, hint))

    def has_number_of_distinct_values(
        self, column, assertion, binning_func=None, max_bins=None, hint=None
    ) -> "Check":
        return self.add_constraint(
            C.histogram_bin_constraint(column, assertion, binning_func, max_bins, hint=hint)
        )

    def has_histogram_values(
        self, column, assertion, binning_func=None, max_bins=None, hint=None
    ) -> "Check":
        return self.add_constraint(
            C.histogram_constraint(column, assertion, binning_func, max_bins, hint=hint)
        )

    def kll_sketch_satisfies(self, column, assertion, kll_parameters=None, hint=None) -> "Check":
        return self.add_constraint(C.kll_constraint(column, assertion, kll_parameters, hint))

    def has_entropy(self, column, assertion, hint=None) -> "Check":
        return self.add_constraint(C.entropy_constraint(column, assertion, hint))

    def has_mutual_information(self, column_a, column_b, assertion, hint=None) -> "Check":
        return self.add_constraint(
            C.mutual_information_constraint(column_a, column_b, assertion, hint)
        )

    def has_approx_quantile(
        self, column, quantile, assertion, relative_error=0.01, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.approx_quantile_constraint(
                column, quantile, assertion, relative_error, where, hint
            )
        )

    def has_min_length(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.min_length_constraint(column, assertion, where, hint)
        )

    def has_max_length(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.max_length_constraint(column, assertion, where, hint)
        )

    def has_min(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.min_constraint(column, assertion, where, hint)
        )

    def has_max(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.max_constraint(column, assertion, where, hint)
        )

    def has_mean(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.mean_constraint(column, assertion, where, hint)
        )

    def has_sum(self, column, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.sum_constraint(column, assertion, where, hint)
        )

    def has_standard_deviation(
        self, column, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.standard_deviation_constraint(column, assertion, where, hint)
        )

    def has_approx_count_distinct(
        self, column, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.approx_count_distinct_constraint(column, assertion, where, hint)
        )

    def has_correlation(
        self, column_a, column_b, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.correlation_constraint(column_a, column_b, assertion, where, hint)
        )

    def satisfies(
        self, column_condition, constraint_name, assertion=is_one, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.compliance_constraint(
                constraint_name, column_condition, assertion, where, hint
            )
        )

    def has_pattern(
        self, column, pattern, assertion=is_one, name=None, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: C.pattern_match_constraint(
                column, pattern, assertion, where, name, hint
            )
        )

    def contains_credit_card_number(self, column, assertion=is_one, hint=None):
        return self.has_pattern(
            column, Patterns.CREDITCARD, assertion,
            name=f"containsCreditCardNumber({column})", hint=hint,
        )

    def contains_email(self, column, assertion=is_one, hint=None):
        return self.has_pattern(
            column, Patterns.EMAIL, assertion, name=f"containsEmail({column})", hint=hint
        )

    def contains_url(self, column, assertion=is_one, hint=None):
        return self.has_pattern(
            column, Patterns.URL, assertion, name=f"containsURL({column})", hint=hint
        )

    def contains_social_security_number(self, column, assertion=is_one, hint=None):
        return self.has_pattern(
            column,
            Patterns.SOCIAL_SECURITY_NUMBER_US,
            assertion,
            name=f"containsSocialSecurityNumber({column})",
            hint=hint,
        )

    def has_data_type(self, column, data_type: ConstrainableDataTypes, assertion=is_one, hint=None):
        return self._add_filterable(
            lambda where: C.data_type_constraint(column, data_type, assertion, where, hint)
        )

    def is_non_negative(self, column, assertion=is_one, hint=None):
        # nulls are compliant (reference coalesces nulls to 0.0,
        # `checks/Check.scala:787-799`)
        return self.satisfies(
            f"({column} is None) or ({column} >= 0)",
            f"{column} is non-negative",
            assertion,
            hint,
        )

    def is_positive(self, column, assertion=is_one, hint=None):
        return self.satisfies(
            f"({column} is None) or ({column} > 0)",
            f"{column} is positive",
            assertion,
            hint,
        )

    def is_less_than(self, column_a, column_b, assertion=is_one, hint=None):
        return self.satisfies(
            f"{column_a} < {column_b}", f"{column_a} is less than {column_b}", assertion, hint
        )

    def is_less_than_or_equal_to(self, column_a, column_b, assertion=is_one, hint=None):
        return self.satisfies(
            f"{column_a} <= {column_b}",
            f"{column_a} is less than or equal to {column_b}",
            assertion,
            hint,
        )

    def is_greater_than(self, column_a, column_b, assertion=is_one, hint=None):
        return self.satisfies(
            f"{column_a} > {column_b}",
            f"{column_a} is greater than {column_b}",
            assertion,
            hint,
        )

    def is_greater_than_or_equal_to(self, column_a, column_b, assertion=is_one, hint=None):
        return self.satisfies(
            f"{column_a} >= {column_b}",
            f"{column_a} is greater than or equal to {column_b}",
            assertion,
            hint,
        )

    def is_contained_in(
        self,
        column,
        allowed_values=None,
        lower_bound=None,
        upper_bound=None,
        include_lower_bound=True,
        include_upper_bound=True,
        assertion=is_one,
        hint=None,
    ):
        """Values version (allowed_values) or numeric-interval version
        (lower_bound/upper_bound); non-null values must comply
        (reference `checks/Check.scala:844-943`)."""
        if allowed_values is not None:
            predicate = contained_in_predicate(column, allowed_values)
            return self.satisfies(
                predicate,
                f"{column} contained in {','.join(str(v) for v in allowed_values)}",
                assertion,
                hint,
            )
        if lower_bound is None or upper_bound is None:
            raise ValueError(
                "is_contained_in needs either allowed_values or lower_bound+upper_bound"
            )
        left = ">=" if include_lower_bound else ">"
        right = "<=" if include_upper_bound else "<"
        predicate = (
            f"({column} is None) or "
            f"({column} {left} {lower_bound} and {column} {right} {upper_bound})"
        )
        return self.satisfies(
            predicate, f"{column} between {lower_bound} and {upper_bound}", assertion, hint
        )

    def is_newest_point_non_anomalous(
        self,
        metrics_repository,
        anomaly_detection_strategy,
        analyzer: Analyzer,
        with_tag_values=None,
        after_date=None,
        before_date=None,
        hint=None,
    ) -> "Check":
        """Anomaly check on the newest metric point given repository history
        (reference `checks/Check.scala:345-365,998-1055`)."""
        from .anomalydetection.wiring import is_newest_point_non_anomalous

        def assertion(value: float) -> bool:
            return is_newest_point_non_anomalous(
                metrics_repository,
                anomaly_detection_strategy,
                analyzer,
                with_tag_values or {},
                after_date,
                before_date,
                value,
            )

        return self.add_constraint(C.anomaly_constraint(analyzer, assertion, hint))


class CheckWithLastConstraintFilterable(Check):
    """Allows filtering the data for the last added constraint with
    ``.where(...)`` (reference `checks/CheckWithLastConstraintFilterable.scala`)."""

    def __init__(self, level, description, constraints, create_replacement):
        super().__init__(level, description, constraints)
        self._create_replacement = create_replacement

    def where(self, filter_: str) -> Check:
        adjusted = self.constraints[:-1] + (self._create_replacement(filter_),)
        return Check(self.level, self.description, adjusted)
