"""Consistent-hash ring: the front tier's session router.

Sessions are sticky — a streaming session's algebraic states live on ONE
worker between fold boundaries — so routing must be a pure function of
the session key that (a) every front-tier replica computes identically
and (b) moves as FEW keys as possible when the host set changes. A
consistent-hash ring with virtual nodes gives both: each host owns
``DEEQU_TPU_CLUSTER_VNODES`` pseudo-random points on a 64-bit circle,
and a key routes to the first point clockwise of its own hash. Adding or
removing one host re-homes only the ~1/N of keys whose clockwise arc
changed; everything else stays put (sessions legally move hosts only at
fold boundaries, via flush-on-A / re-open-on-B through the partition
store — the ring decides WHERE, :class:`~deequ_tpu.cluster.front
.FrontTier` performs the move).

Hashing is ``blake2b`` (stdlib, keyed by nothing, stable across
processes and Python runs — ``hash()`` is salted per process and
useless here). Ring mutations pass a ``ring_rebalance`` fault probe so
chaos plans can fail the re-hash mid-membership-change.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..reliability.faults import fault_point
from ..utils import env_number

#: virtual nodes per host — more points = smoother key distribution at
#: slightly larger rings; 64 keeps the worst-case host imbalance under a
#: few percent for small clusters
VNODES_ENV = "DEEQU_TPU_CLUSTER_VNODES"
DEFAULT_VNODES = 64


def ring_vnodes() -> int:
    return int(
        env_number(VNODES_ENV, DEFAULT_VNODES, int, minimum=1)
    )


def stable_hash(key: str) -> int:
    """Process-stable 64-bit hash of ``key`` (blake2b, first 8 bytes)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(),
        "big",
    )


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Not thread-safe by itself — the front tier serializes membership
    changes under its own lock; lookups between mutations are reads of
    immutable snapshots (``_points``/``_owners`` are rebuilt wholesale,
    never edited in place, so a racing ``route`` sees either the old or
    the new ring, both valid)."""

    def __init__(
        self,
        hosts: Sequence[str] = (),
        vnodes: Optional[int] = None,
    ) -> None:
        self._vnodes = ring_vnodes() if vnodes is None else max(1, int(vnodes))
        self._hosts: List[str] = []
        self._points: List[int] = []
        self._owners: List[str] = []
        for host in hosts:
            self.add_host(host)

    @property
    def hosts(self) -> Tuple[str, ...]:
        return tuple(self._hosts)

    @property
    def vnodes(self) -> int:
        return self._vnodes

    def __len__(self) -> int:
        return len(self._hosts)

    def __contains__(self, host: str) -> bool:
        return host in self._hosts

    def _rebuild(self) -> None:
        pairs: List[Tuple[int, str]] = []
        for host in self._hosts:
            for v in range(self._vnodes):
                pairs.append((stable_hash(f"{host}#{v}"), host))
        # ties broken by host id so every replica builds the same ring
        pairs.sort(key=lambda p: (p[0], p[1]))
        self._points = [p[0] for p in pairs]
        self._owners = [p[1] for p in pairs]

    def add_host(self, host: str) -> None:
        """Add ``host``; ~1/N of key space re-homes onto it."""
        if host in self._hosts:
            return
        fault_point("ring_rebalance", tag=host)
        self._hosts.append(host)
        self._hosts.sort()
        self._rebuild()

    def remove_host(self, host: str) -> None:
        """Remove ``host``; its arcs re-home to the clockwise survivors."""
        if host not in self._hosts:
            return
        fault_point("ring_rebalance", tag=host)
        self._hosts.remove(host)
        self._rebuild()

    def route(self, key: str) -> str:
        """Owner host for ``key``: first ring point clockwise of its hash.

        Raises ``LookupError`` on an empty ring — the caller (front tier)
        decides whether that is a 503 or a crash."""
        if not self._points:
            raise LookupError("hash ring has no hosts")
        idx = bisect.bisect_right(self._points, stable_hash(key))
        if idx == len(self._points):
            idx = 0
        return self._owners[idx]

    def moved_keys(
        self, keys: Sequence[str], before: "HashRing"
    ) -> Dict[str, Tuple[str, str]]:
        """Which of ``keys`` route differently on this ring vs ``before``:
        ``{key: (old_host, new_host)}`` — the migration work-list for a
        membership change (everything absent stayed put)."""
        moved: Dict[str, Tuple[str, str]] = {}
        for key in keys:
            try:
                old = before.route(key)
            except LookupError:
                old = ""
            new = self.route(key)
            if old != new:
                moved[key] = (old, new)
        return moved

    def snapshot(self) -> "HashRing":
        """Independent copy (for ``moved_keys`` before/after diffs)."""
        clone = HashRing(vnodes=self._vnodes)
        clone._hosts = list(self._hosts)
        clone._points = list(self._points)
        clone._owners = list(self._owners)
        return clone
