"""Horizontal scale-out tier: N worker processes, one service.

The single-host service plane (``deequ_tpu.service``) is complete per
host — scheduler, coalescer, placement, drift, export. This package
makes MANY of those hosts act as one service (ROADMAP item 3):

- :mod:`~deequ_tpu.cluster.ring` — consistent-hash routing of session
  keys with virtual nodes: membership changes move ~1/N of keys, and
  every front-tier replica routes identically;
- :mod:`~deequ_tpu.cluster.worker` — the per-host worker protocol
  (open / ingest / flush / release / adopt) over a whole
  VerificationService; adoption resumes a session from the shared
  partition store, contract and all;
- :mod:`~deequ_tpu.cluster.membership` — file-heartbeat liveness with a
  typed :class:`~deequ_tpu.cluster.membership.HostLossError`;
- :mod:`~deequ_tpu.cluster.front` — the routing/migration/recovery
  brain: sessions move hosts only at fold boundaries (flush-on-old /
  adopt-on-new through the partition store), and a lost host's sessions
  recover as salvage-from-store + journal replay, exactly;
- cross-host battery aggregation rides :mod:`deequ_tpu.parallel.dcn`
  (each worker's drained aggregate is one shard of a global stacked
  array; one log2(n) butterfly merge returns the cluster-wide state);
- the multi-writer partition store is fenced by the compaction lease
  (:mod:`deequ_tpu.repository.lease`): appends are lock-free atomic
  renames from any host, compaction is elected.
"""

from __future__ import annotations

from .front import (
    CLUSTER_JOURNAL_MAX_FOLDS_ENV,
    DEFAULT_CLUSTER_JOURNAL_MAX_FOLDS,
    FrontTier,
    cluster_journal_max_folds,
)
from .membership import (
    DEFAULT_HEARTBEAT_S,
    DEFAULT_HOST_TTL_S,
    HEARTBEAT_ENV,
    HOST_TTL_ENV,
    HeartbeatMembership,
    HostLossError,
    heartbeat_s,
    host_ttl_s,
)
from .ring import DEFAULT_VNODES, VNODES_ENV, HashRing, ring_vnodes
from .worker import LocalWorker, session_partition

__all__ = [
    "CLUSTER_JOURNAL_MAX_FOLDS_ENV",
    "DEFAULT_CLUSTER_JOURNAL_MAX_FOLDS",
    "DEFAULT_HEARTBEAT_S",
    "DEFAULT_HOST_TTL_S",
    "DEFAULT_VNODES",
    "HEARTBEAT_ENV",
    "HOST_TTL_ENV",
    "VNODES_ENV",
    "FrontTier",
    "HashRing",
    "HeartbeatMembership",
    "HostLossError",
    "LocalWorker",
    "cluster_journal_max_folds",
    "describe_cluster_series",
    "heartbeat_s",
    "host_ttl_s",
    "ring_vnodes",
    "session_partition",
]


def describe_cluster_series(metrics) -> None:
    """Register help text for the cluster tier's counter series on a
    :class:`~deequ_tpu.service.metrics.ServiceMetrics` (deliberately
    unrolled literal calls — the export-plane convention that keeps
    every exported name greppable and the invariant linter's
    export-help check satisfiable by inspection)."""
    metrics.describe(
        "deequ_service_cluster_routes_total",
        "Session-key routing decisions made by the front tier's hash ring.",
    )
    metrics.describe(
        "deequ_service_cluster_migrations_total",
        "Sessions legally moved between hosts at fold boundaries "
        "(flush-on-old / adopt-on-new through the partition store).",
    )
    metrics.describe(
        "deequ_service_cluster_host_losses_total",
        "Worker hosts declared lost (missed heartbeats past the TTL or "
        "an injected host_loss fault).",
    )
    metrics.describe(
        "deequ_service_cluster_ring_moves_total",
        "Session keys whose ring arc re-homed across membership changes.",
    )
    metrics.describe(
        "deequ_service_cluster_sessions_recovered_total",
        "Sessions re-opened on a survivor after a host loss (adopted "
        "from the partition store).",
    )
    metrics.describe(
        "deequ_service_cluster_replayed_folds_total",
        "Journaled folds replayed into recovered sessions (the window "
        "between the dead host's last flush and its loss).",
    )
    metrics.describe(
        "deequ_service_cluster_journal_flushes_total",
        "Force-flushes triggered by a session's replay journal reaching "
        "DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS payloads (bounds replay "
        "memory for producers that never flush).",
    )
