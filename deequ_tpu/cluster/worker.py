"""One cluster worker: a :class:`~deequ_tpu.service.VerificationService`
plus the cluster-facing session protocol the front tier drives.

A worker is a whole single-host service plane — its own FleetScheduler,
coalescer, placement router, metrics exporter and (optionally) HTTP
ingest endpoint — made clusterable by three capabilities layered here:

- **open/ingest/flush** against sessions addressed by (tenant, dataset)
  — what the front tier routes to the ring-chosen host;
- **release**: flush the session's cumulative algebraic states (and its
  checksummed schema contract) into the SHARED partition store, then
  close it — the first half of a legal migration (sessions move hosts
  only at fold boundaries);
- **adopt**: re-open a session AGAINST the flushed partition's state
  provider, so the new host resumes from the exact cumulative states +
  contract the old host committed — the second half of a migration, and
  the recovery path after a host loss (salvage from the store, then the
  front tier replays the folds the flush missed).

Workers also heartbeat the shared membership directory so the front
tier can tell a live host from a dead one.
"""

from __future__ import annotations

import logging
from contextlib import contextmanager
from typing import Any, Optional, Sequence

from ..observability import trace as _trace
from .membership import HeartbeatMembership

_logger = logging.getLogger(__name__)


def session_partition(tenant: str) -> str:
    """The partition a (tenant, dataset) session flushes into — must
    match ``StreamingSession._flush_to_partition_locked``'s default so
    adoption reads exactly what release wrote."""
    return f"session-{tenant}"


class LocalWorker:
    """In-process worker: wraps a service the front tier can route to.

    The same protocol an HTTP-fronted worker speaks (tools/cluster_soak
    drives remote workers through the ingest endpoint); in-process it is
    plain method calls, which is what unit tests and the chaos drills
    compose."""

    def __init__(
        self,
        host_id: str,
        service,
        membership: Optional[HeartbeatMembership] = None,
    ) -> None:
        self.host_id = str(host_id)
        self.service = service
        self.membership = membership
        if membership is not None and not membership.host_id:
            membership.host_id = self.host_id
        # a cluster-attached worker owns the truth about ITS half of the
        # cluster plane: overwrite the service's default detached
        # /statusz section with the per-host view (membership + owned
        # sessions) — honest per-host reporting; ring ownership lives on
        # the front tier
        statusz = getattr(service, "statusz", None)
        if statusz is not None:
            statusz.register("cluster", self._statusz_section)

    def _statusz_section(self) -> dict:
        section: dict = {"attached": True, "host": self.host_id}
        if self.membership is not None:
            try:
                section["members"] = sorted(self.membership.members())
            except Exception as exc:  # noqa: BLE001 - a torn membership
                # dir must not blank the section
                section["members_error"] = f"{type(exc).__name__}: {exc}"
        sessions = getattr(self.service, "_sessions", {})
        lock = getattr(self.service, "_sessions_lock", None)
        if lock is not None:
            with lock:
                section["sessions"] = sorted(
                    f"{t}/{d}" for (t, d), s in sessions.items()
                    if not s.closed
                )
        return section

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self.membership is not None:
            self.membership.start()

    def close(self, **kw) -> None:
        if self.membership is not None:
            self.membership.stop()
        self.service.close(**kw)

    # -- tracing ---------------------------------------------------------

    @contextmanager
    def _span(self, name: str, trace_ctx: Optional[str], **attrs: Any):
        """Worker-side span for one protocol call, attached for the body.
        ``trace_ctx`` is a serialized :data:`~deequ_tpu.observability.
        TRACE_HEADER` value from the front tier: extracting it parents
        this span under the FRONT's trace (one trace_id across the hop);
        without it the span joins this thread's context (in-process
        front) or starts a new root. These spans are what a SIGKILLed
        worker leaves behind in its journal/flight ring — a worker that
        emitted no spans had no post-mortem."""
        parent = "auto" if trace_ctx is None else _trace.extract(trace_ctx)
        sp = _trace.start_span(
            name, kind="cluster",
            attrs={"host": self.host_id, **attrs}, parent=parent,
        )
        with _trace.attach(sp):
            try:
                yield sp
            except BaseException as exc:
                if sp is not _trace.NULL:
                    sp.set_attr("error", f"{type(exc).__name__}: {exc}")
                sp.finish("error")
                raise
            else:
                sp.finish()

    # -- session protocol ------------------------------------------------

    def open_session(
        self, tenant: str, dataset: str, checks: Sequence[Any] = (),
        trace_ctx: Optional[str] = None, **kw
    ):
        with self._span(
            "worker_open", trace_ctx, tenant=tenant, dataset=dataset
        ):
            return self.service.session(tenant, dataset, checks, **kw)

    def ingest(
        self, tenant: str, dataset: str, data,
        trace_ctx: Optional[str] = None, **kw
    ):
        with self._span(
            "worker_ingest", trace_ctx, tenant=tenant, dataset=dataset
        ):
            session = self.service.get_session(tenant, dataset)
            if session is None:
                raise KeyError(
                    f"no live session {tenant}/{dataset} on host "
                    f"{self.host_id}"
                )
            return session.ingest(data, **kw)

    def flush(
        self, tenant: str, dataset: str, partition: Optional[str] = None,
        trace_ctx: Optional[str] = None,
    ) -> Optional[str]:
        """Flush the session's cumulative states + contract into the
        shared partition store (fold boundary). Returns the partition
        name, or None when the session never folded."""
        with self._span(
            "worker_flush", trace_ctx, tenant=tenant, dataset=dataset
        ):
            session = self.service.get_session(tenant, dataset)
            if session is None:
                return None
            return session.flush_to_partition(partition=partition)

    def release(
        self, tenant: str, dataset: str, trace_ctx: Optional[str] = None
    ) -> Optional[str]:
        """Flush then CLOSE the session — the outbound half of a
        migration. After release the states live in the partition store
        and this host serves 410 for the session."""
        with self._span(
            "worker_release", trace_ctx, tenant=tenant, dataset=dataset
        ):
            session = self.service.get_session(tenant, dataset)
            if session is None:
                return None
            name = session.flush_to_partition()
            session.close()
            return name

    def adopt_session(
        self,
        tenant: str,
        dataset: str,
        checks: Sequence[Any] = (),
        partition: Optional[str] = None,
        trace_ctx: Optional[str] = None,
        **kw,
    ):
        """Re-open a migrated/lost session from the shared partition
        store: the new session's state provider IS the flushed
        partition's provider, so it resumes from the committed
        cumulative states and re-loads the checksummed schema contract
        beside them (drift policies fire identically post-migration).
        A session that never flushed adopts an EMPTY provider — correct,
        because the front tier then replays every journaled fold."""
        with self._span(
            "worker_adopt", trace_ctx, tenant=tenant, dataset=dataset,
            partition=partition or session_partition(tenant),
        ):
            store = getattr(self.service, "partition_store", None)
            if store is None:
                raise ValueError(
                    f"host {self.host_id} has no partition store to "
                    f"adopt from"
                )
            name = partition or session_partition(tenant)
            kw.setdefault("state_provider", store.provider(dataset, name))
            session = self.service.session(tenant, dataset, checks, **kw)
            if session._schema is None:
                manifest = store.get(dataset, name)
                if manifest is not None and manifest.schema:
                    from ..data import ColumnKind, ColumnSchema, Schema

                    # the flushed manifest carries the schema the states
                    # were folded under: restoring it lets the adopted
                    # session serve state-only queries (current()) BEFORE
                    # its first post-adoption fold, and keeps the
                    # committed row total cumulative across the migration
                    session._schema = Schema([
                        ColumnSchema(n, ColumnKind(k))
                        for n, k in manifest.schema
                    ])
                    session.rows_ingested = int(manifest.num_rows)
            return session

    def session_stats(self, tenant: str, dataset: str) -> dict:
        session = self.service.get_session(tenant, dataset)
        if session is None:
            return {}
        return {
            "host": self.host_id,
            "batches": session.batches_ingested,
            "rows": session.rows_ingested,
            "bytes": session.bytes_ingested,
        }
