"""Membership + health: who is in the cluster, and who just died.

Workers prove liveness by heartbeating a shared directory: each writes
``host-<id>.json`` (atomic tmp + rename — readers never see a torn
beat) carrying its host id, a monotonically increasing beat sequence,
and a wall-clock stamp. The front tier scans the directory: a host
whose last beat is older than ``DEEQU_TPU_CLUSTER_HOST_TTL_S`` is
declared LOST and surfaces as a typed :class:`HostLossError` — the
signal that drives ring re-hash + session recovery in
:class:`~deequ_tpu.cluster.front.FrontTier`. Files, not sockets,
deliberately: the partition store and the compaction lease already live
on the shared filesystem, so membership rides the same substrate with
the same failure domain (a worker that cannot reach the share cannot
beat — and also cannot commit, so declaring it lost is safe).

Each membership scan passes a ``host_heartbeat`` fault probe per host
(tag = host id), so chaos plans can declare an arbitrary host dead
without killing anything.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..exceptions import ShardLossError
from ..reliability.faults import fault_point
from ..utils import env_number

_logger = logging.getLogger(__name__)

#: seconds between heartbeat writes from a live worker
HEARTBEAT_ENV = "DEEQU_TPU_CLUSTER_HEARTBEAT_S"
DEFAULT_HEARTBEAT_S = 0.5

#: seconds without a beat before a host is declared lost (should be a
#: few multiples of the heartbeat period to ride out scheduler hiccups)
HOST_TTL_ENV = "DEEQU_TPU_CLUSTER_HOST_TTL_S"
DEFAULT_HOST_TTL_S = 3.0


def heartbeat_s() -> float:
    return float(
        env_number(HEARTBEAT_ENV, DEFAULT_HEARTBEAT_S, float, minimum=0.05)
    )


def host_ttl_s() -> float:
    return float(
        env_number(HOST_TTL_ENV, DEFAULT_HOST_TTL_S, float, minimum=0.1)
    )


class HostLossError(ShardLossError):
    """A cluster WORKER HOST died (missed heartbeats past the TTL, or a
    cross-host collective hung past its deadline). The cluster-tier
    sibling of a mesh shard loss — and deliberately a
    :class:`~deequ_tpu.exceptions.ShardLossError` subclass so anything
    routing on the mesh-recoverable family treats it identically — but
    it names a HOST ID, not mesh positions: recovery is the front
    tier's job (re-hash the ring range to survivors, re-open the dead
    host's sessions from the partition store, replay unflushed folds),
    not the elastic mesh ladder's."""

    def __init__(self, host: str, site: str = "", detail: str = "",
                 survivors=None):
        self.host = str(host)
        self.lost = ()
        self.site = site
        self.survivors = None if survivors is None else list(survivors)
        # bypass ShardLossError's shard-index message: a host loss names
        # a host id, and "shard(s) [] lost" would read as a no-op
        Exception.__init__(
            self,
            f"cluster host {self.host or '<host>'} lost"
            + (f" at {site}" if site else "")
            + (f": {detail}" if detail else ""),
        )


class HeartbeatMembership:
    """File-based heartbeat membership over a shared directory.

    One instance per participant: workers call :meth:`beat` (or run
    :meth:`start` for a background beater), the front tier calls
    :meth:`scan` to partition the membership into (alive, lost). A
    lost host's beat file is retired by whoever recovers it
    (:meth:`retire`), so one loss is reported once."""

    def __init__(
        self,
        root: str,
        host_id: str = "",
        heartbeat_period_s: Optional[float] = None,
        ttl_s: Optional[float] = None,
    ) -> None:
        self.root = root
        self.host_id = host_id
        self.period_s = (
            heartbeat_s() if heartbeat_period_s is None
            else float(heartbeat_period_s)
        )
        self.ttl_s = host_ttl_s() if ttl_s is None else float(ttl_s)
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        os.makedirs(self.root, exist_ok=True)

    def _path(self, host: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "-_." else "_" for c in host
        )
        return os.path.join(self.root, f"host-{safe}.json")

    # -- writer side (workers) -------------------------------------------

    def beat(self) -> None:
        """Write one heartbeat (atomic rename; readers never see torn
        JSON). Failures log-and-continue: a missed beat is exactly the
        condition the TTL already tolerates."""
        if not self.host_id:
            raise ValueError("beat() requires a host_id")
        self._seq += 1
        payload = json.dumps(
            {"host": self.host_id, "seq": self._seq, "ts": time.time()}
        )
        path = self._path(self.host_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(payload)
            os.replace(tmp, path)
        except OSError as exc:  # pragma: no cover - fs-dependent
            _logger.warning("heartbeat write failed for %s: %s",
                            self.host_id, exc)

    def start(self) -> None:
        """Background beater at ``period_s`` until :meth:`stop`."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.period_s):
                self.beat()

        self.beat()
        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"heartbeat-{self.host_id}"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=self.period_s * 4)
        self._thread = None

    # -- reader side (front tier) ----------------------------------------

    def members(self) -> Dict[str, dict]:
        """Last beat per host (torn/alien files skipped)."""
        out: Dict[str, dict] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("host-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, name), encoding="utf-8") as fh:
                    rec = json.load(fh)
            except (OSError, ValueError):
                continue
            host = rec.get("host")
            if isinstance(host, str) and host:
                out[host] = rec
        return out

    def scan(self) -> Tuple[List[str], List[str]]:
        """Partition the membership into ``(alive, lost)`` by beat age.
        Each host passes a ``host_heartbeat`` fault probe (tag = host
        id); an injected ``host_loss`` fault declares that host dead —
        the chaos path that exercises recovery without killing a
        process."""
        now = time.time()
        alive: List[str] = []
        lost: List[str] = []
        for host, rec in sorted(self.members().items()):
            try:
                fault_point("host_heartbeat", tag=host)
            except HostLossError:
                lost.append(host)
                continue
            age = now - float(rec.get("ts", 0.0))
            (alive if age <= self.ttl_s else lost).append(host)
        return alive, lost

    def retire(self, host: str) -> None:
        """Drop ``host``'s beat file after its loss has been handled, so
        subsequent scans stop reporting it."""
        try:
            os.unlink(self._path(host))
        except OSError:
            pass
