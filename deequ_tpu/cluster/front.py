"""Front tier: consistent-hash session routing, legal migration, and
host-loss recovery over a set of workers.

The ring (:class:`~deequ_tpu.cluster.ring.HashRing`) is the single
routing authority: a session key ``tenant/dataset`` always belongs to
``ring.route(key)``. When membership changes move a key's arc, the
front tier performs the LEGAL move — sessions migrate only at fold
boundaries, as flush-on-old / adopt-on-new through the shared partition
store, carrying the cumulative algebraic states AND the checksummed
schema contract. Two flavors:

- **graceful** (:meth:`migrate`, triggered by ring changes): the old
  host flushes + closes first, so the partition store holds everything
  and nothing needs replaying;
- **loss** (:meth:`handle_host_loss`, triggered by missed heartbeats or
  a typed :class:`~deequ_tpu.cluster.membership.HostLossError`): the
  dead host flushed LAST at some earlier boundary, so the survivor
  adopts the store's states and the front tier replays its per-session
  fold journal — every payload accepted since the last flush — into
  the adopted session. Algebraic states make replay exact: salvage +
  replay equals the lost session, fold for fold, which is what the
  chaos drill's parity gate asserts.

Every routing decision, migration, loss and replay bumps a typed
``deequ_service_cluster_*`` counter (described in
:func:`~deequ_tpu.cluster.describe_cluster_series`), so the drill can
PROVE recovery happened rather than infer it from timing.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..observability import trace as _trace
from .membership import HeartbeatMembership, HostLossError
from .ring import HashRing
from .worker import LocalWorker, session_partition

_logger = logging.getLogger(__name__)

#: folds a session's replay journal may hold before the front tier
#: FORCE-FLUSHES the session to the partition store (clearing the
#: journal): the journal exists to replay the window since the last
#: flush, and a producer that never calls flush() would otherwise grow
#: it one payload per fold for the session's whole life. Warn-once
#: parser; minimum 1 (a bound of 0 would force-flush every fold).
CLUSTER_JOURNAL_MAX_FOLDS_ENV = "DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS"
DEFAULT_CLUSTER_JOURNAL_MAX_FOLDS = 256


def cluster_journal_max_folds() -> int:
    from ..utils import env_number

    return int(env_number(
        CLUSTER_JOURNAL_MAX_FOLDS_ENV, DEFAULT_CLUSTER_JOURNAL_MAX_FOLDS,
        int, minimum=1,
    ))


def _key(tenant: str, dataset: str) -> Tuple[str, str]:
    return (str(tenant), str(dataset))


def _ring_key(key: Tuple[str, str]) -> str:
    return f"{key[0]}/{key[1]}"


class FrontTier:
    """Routes session traffic to ring-chosen workers; owns migration and
    recovery. Thread-safe: one re-entrant lock serializes membership
    changes, placements and journals (ingest forwarding itself happens
    outside the lock — the target session serializes its own folds)."""

    def __init__(
        self,
        metrics=None,
        membership: Optional[HeartbeatMembership] = None,
        vnodes: Optional[int] = None,
    ) -> None:
        from ..service.metrics import ServiceMetrics
        from . import describe_cluster_series

        self.ring = HashRing(vnodes=vnodes)
        self.workers: Dict[str, LocalWorker] = {}
        self.membership = membership
        self.metrics = metrics if metrics is not None else ServiceMetrics()
        describe_cluster_series(self.metrics)
        self._lock = threading.RLock()
        #: key -> (checks, session kwargs): what re-creates the session
        #: anywhere (the schema contract travels via the store, not here)
        self._specs: Dict[Tuple[str, str], Tuple[tuple, dict]] = {}
        #: key -> host currently holding the live session
        self._placements: Dict[Tuple[str, str], str] = {}
        #: key -> payloads accepted since the last flush — the replay
        #: log that makes loss recovery exact (cleared at every flush,
        #: so it holds one fold window, not the session's life; bounded
        #: by a force-flush at DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS)
        self._journal: Dict[Tuple[str, str], List[Any]] = {}
        self._journal_max_folds = cluster_journal_max_folds()

    # -- membership ------------------------------------------------------

    def add_worker(self, worker: LocalWorker) -> None:
        """Join ``worker``; keys whose arc re-homes onto it migrate
        gracefully (flush-on-old / adopt-on-new)."""
        with self._lock:
            before = self.ring.snapshot()
            self.ring.add_host(worker.host_id)
            self.workers[worker.host_id] = worker
            worker.start()
            moved = self.ring.moved_keys(
                [_ring_key(k) for k in self._placements], before
            )
            if moved:
                self.metrics.inc(
                    "deequ_service_cluster_ring_moves_total", len(moved)
                )
            for key in list(self._placements):
                if _ring_key(key) in moved:
                    self._migrate_locked(key, self.ring.route(_ring_key(key)))

    def remove_worker(self, host: str) -> None:
        """Gracefully drain ``host``: its sessions migrate to the ring's
        survivors (flush first, nothing replays), then it leaves."""
        with self._lock:
            worker = self.workers.get(host)
            if worker is None:
                return
            before = self.ring.snapshot()
            self.ring.remove_host(host)
            moved = self.ring.moved_keys(
                [_ring_key(k) for k in self._placements], before
            )
            if moved:
                self.metrics.inc(
                    "deequ_service_cluster_ring_moves_total", len(moved)
                )
            for key, placed in list(self._placements.items()):
                if placed == host:
                    self._migrate_locked(key, self.ring.route(_ring_key(key)))
            del self.workers[host]
            worker.close()

    def check_membership(self) -> List[str]:
        """One health sweep: scan heartbeats, recover every host the TTL
        (or an injected ``host_loss`` fault) declares dead. Returns the
        hosts recovered this sweep."""
        if self.membership is None:
            return []
        _alive, lost = self.membership.scan()
        handled = []
        for host in lost:
            if host in self.workers:
                self.handle_host_loss(host)
                handled.append(host)
            self.membership.retire(host)
        return handled

    # -- session plane ---------------------------------------------------

    def route(self, tenant: str, dataset: str) -> str:
        """The ring-chosen host for a session key."""
        host = self.ring.route(_ring_key(_key(tenant, dataset)))
        self.metrics.inc("deequ_service_cluster_routes_total")
        return host

    def open_session(
        self, tenant: str, dataset: str, checks: Sequence[Any] = (), **kw
    ) -> str:
        """Create the session on its ring-chosen host; remembers the
        spec so migration/recovery can re-create it elsewhere. Returns
        the placed host id."""
        key = _key(tenant, dataset)
        with self._lock, _trace.span(
            "cluster_open", kind="cluster", session=_ring_key(key)
        ) as sp:
            host = self.route(tenant, dataset)
            if sp is not _trace.NULL:
                sp.set_attr("target", host)
            self._specs[key] = (tuple(checks), dict(kw))
            self.workers[host].open_session(
                tenant, dataset, checks, trace_ctx=_trace.inject(), **kw
            )
            self._placements[key] = host
            self._journal.setdefault(key, [])
            return host

    def ingest(self, tenant: str, dataset: str, data, **kw):
        """Forward one micro-batch to the session's host (migrating
        first if the ring re-homed the key) and journal the payload for
        loss replay. A journal that reaches
        ``DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS`` payloads force-flushes
        the session AFTER this fold commits — bounding replay memory for
        producers that never reach a natural flush boundary."""
        key = _key(tenant, dataset)
        with _trace.span(
            "cluster_ingest", kind="cluster", session=_ring_key(key)
        ) as sp:
            with self._lock:
                if key not in self._placements:
                    raise KeyError(
                        f"unknown session {tenant}/{dataset}: open it via "
                        "the front tier first"
                    )
                owner = self.route(tenant, dataset)
                if owner != self._placements[key]:
                    self._migrate_locked(key, owner)
                worker = self.workers[self._placements[key]]
                if sp is not _trace.NULL:
                    sp.set_attr("target", self._placements[key])
                journal = self._journal.setdefault(key, [])
                journal.append(data)
                force_flush = len(journal) >= self._journal_max_folds
            result = worker.ingest(
                tenant, dataset, data, trace_ctx=_trace.inject(sp), **kw
            )
        if force_flush:
            # flush only AFTER the worker committed this fold: flushing
            # first would clear a journal entry whose fold has not
            # reached the session yet — a host loss in that window would
            # replay nothing and lose the payload
            self.flush(tenant, dataset)
            self.metrics.inc(
                "deequ_service_cluster_journal_flushes_total"
            )
        return result

    def flush(self, tenant: str, dataset: str) -> Optional[str]:
        """Fold boundary: flush the session's cumulative states (+
        contract) to the partition store and clear its replay journal —
        everything journaled is now durably committed."""
        key = _key(tenant, dataset)
        with self._lock, _trace.span(
            "cluster_flush", kind="cluster", session=_ring_key(key)
        ):
            host = self._placements.get(key)
            if host is None:
                return None
            name = self.workers[host].flush(
                tenant, dataset, trace_ctx=_trace.inject()
            )
            if name is not None:
                self._journal[key] = []
            return name

    def flush_all(self) -> None:
        with self._lock:
            for tenant, dataset in list(self._placements):
                self.flush(tenant, dataset)

    # -- migration + recovery --------------------------------------------

    def _migrate_locked(self, key: Tuple[str, str], new_host: str) -> None:
        """Graceful move at a fold boundary: release (flush + close) on
        the old host, adopt from the store on the new one. The flush
        captures every journaled fold, so the journal clears."""
        tenant, dataset = key
        old_host = self._placements.get(key)
        if old_host == new_host:
            return
        checks, kw = self._specs.get(key, ((), {}))
        with _trace.span(
            "cluster_migrate", kind="cluster", session=_ring_key(key),
            source=old_host or "", target=new_host,
        ):
            partition = None
            if old_host is not None and old_host in self.workers:
                partition = self.workers[old_host].release(
                    tenant, dataset, trace_ctx=_trace.inject()
                )
            self.workers[new_host].adopt_session(
                tenant, dataset, checks,
                partition=partition or session_partition(tenant),
                trace_ctx=_trace.inject(), **dict(kw),
            )
            self._placements[key] = new_host
            if partition is not None:
                self._journal[key] = []
            self.metrics.inc("deequ_service_cluster_migrations_total")

    def handle_host_loss(self, host: str) -> List[Tuple[str, str]]:
        """Recover every session placed on a DEAD host: re-hash its ring
        range to the survivors, adopt each session from its last flushed
        partition, and replay the journaled folds the flush missed.
        Returns the recovered keys. Raises
        :class:`~deequ_tpu.cluster.membership.HostLossError` when no
        survivor remains to adopt onto."""
        with self._lock:
            with _trace.span("cluster_host_loss", kind="cluster", host=host):
                self.metrics.inc("deequ_service_cluster_host_losses_total")
                before = self.ring.snapshot()
                self.ring.remove_host(host)
                self.workers.pop(host, None)
                if not self.workers:
                    raise HostLossError(
                        host, site="cluster_front",
                        detail="no surviving workers to recover onto",
                    )
                moved = self.ring.moved_keys(
                    [_ring_key(k) for k in self._placements], before
                )
                if moved:
                    self.metrics.inc(
                        "deequ_service_cluster_ring_moves_total", len(moved)
                    )
                recovered = []
                for key, placed in list(self._placements.items()):
                    if placed != host:
                        continue
                    tenant, dataset = key
                    new_host = self.ring.route(_ring_key(key))
                    checks, kw = self._specs.get(key, ((), {}))
                    # adopt the LAST FLUSHED states (+ contract) from the
                    # shared store — the dead host cannot flush again, so
                    # no fold can double-commit...
                    self.workers[new_host].adopt_session(
                        tenant, dataset, checks,
                        trace_ctx=_trace.inject(), **dict(kw)
                    )
                    # ...and replay the journal — every payload accepted
                    # since that flush — so no fold is lost either
                    replayed = 0
                    for payload in self._journal.get(key, []):
                        self.workers[new_host].ingest(
                            tenant, dataset, payload,
                            trace_ctx=_trace.inject(),
                        )
                        replayed += 1
                    self._placements[key] = new_host
                    self.metrics.inc(
                        "deequ_service_cluster_sessions_recovered_total"
                    )
                    if replayed:
                        self.metrics.inc(
                            "deequ_service_cluster_replayed_folds_total",
                            replayed,
                        )
                    _trace.add_event(
                        "cluster_session_recovered", session=_ring_key(key),
                        source=host, target=new_host, replayed=replayed,
                    )
                    recovered.append(key)
                if self.membership is not None:
                    self.membership.retire(host)
                return recovered

    def placement(self, tenant: str, dataset: str) -> Optional[str]:
        return self._placements.get(_key(tenant, dataset))

    def close(self) -> None:
        with self._lock:
            for worker in self.workers.values():
                worker.close()
            self.workers.clear()
