"""Anomaly detection strategies (reference `anomalydetection/*.scala`)."""

from __future__ import annotations

import math
import sys
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import Anomaly, AnomalyDetectionStrategy

# finite sentinels (the reference uses Double.MinValue/MaxValue): a factor
# of MAX times stdDev 0 must stay 0, never NaN as inf*0 would be
_NEG_INF = -sys.float_info.max
_POS_INF = sys.float_info.max


def normalize_intervals(n_series: int, search_interval, message: str):
    """Per-series (starts, ends) int64 arrays from either ONE shared
    ``(start, end)`` tuple or a sequence of N per-series tuples — the
    fleet-watch shape, where every tenant's "newest point" sits at its own
    ragged index. Validates each interval with the caller's exact serial
    error ``message`` so batched and serial paths fail identically."""
    seq = list(search_interval)
    if len(seq) == 2 and not hasattr(seq[0], "__len__"):
        starts = np.full(n_series, int(seq[0]), dtype=np.int64)
        ends = np.full(n_series, int(seq[1]), dtype=np.int64)
    else:
        if len(seq) != n_series:
            raise ValueError(
                f"need one search interval or one per series "
                f"({n_series}), got {len(seq)}"
            )
        starts = np.array([int(s) for s, _ in seq], dtype=np.int64)
        ends = np.array([int(e) for _, e in seq], dtype=np.int64)
    if np.any(starts > ends):
        raise ValueError(message)
    return starts, ends


def pad_series_matrix(series_list):
    """Right-pad N ragged series into a float64 ``[N, T]`` matrix plus the
    per-series lengths (the mask). Padding is zeros; every batched core
    masks it out via the lengths."""
    arrays = [np.asarray(s, dtype=np.float64) for s in series_list]
    lengths = np.array([len(a) for a in arrays], dtype=np.int64)
    t = int(lengths.max()) if len(arrays) else 0
    m = np.zeros((len(arrays), t))
    for i, a in enumerate(arrays):
        m[i, : len(a)] = a
    return m, lengths


@dataclass(frozen=True)
class SimpleThresholdStrategy(AnomalyDetectionStrategy):
    """Flags values outside [lower_bound, upper_bound]
    (reference `anomalydetection/SimpleThresholdStrategy.scala`)."""

    upper_bound: float
    lower_bound: float = _NEG_INF

    def __post_init__(self):
        if self.lower_bound > self.upper_bound:
            raise ValueError("The lower bound must be smaller or equal to the upper bound.")

    def detect(self, data_series, search_interval):
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval can't be larger than the end.")
        out = []
        for index in range(start, min(end, len(data_series))):
            value = data_series[index]
            if value < self.lower_bound or value > self.upper_bound:
                out.append(
                    (
                        index,
                        Anomaly(
                            value,
                            1.0,
                            f"[SimpleThresholdStrategy]: Value {value} is not in bounds "
                            f"[{self.lower_bound}, {self.upper_bound}]",
                        ),
                    )
                )
        return out

    def detect_batch(self, series_list, search_interval):
        """Batched :meth:`detect`: N ragged series flag through ONE
        vectorized bounds compare (``search_interval``: one shared tuple
        or one per series) — element-for-element identical to serial."""
        if not len(series_list):
            return []
        starts, ends = normalize_intervals(
            len(series_list), search_interval,
            "The start of the interval can't be larger than the end.",
        )
        m, lengths = pad_series_matrix(series_list)
        idx = np.arange(m.shape[1], dtype=np.int64)
        in_window = (
            (idx[None, :] >= starts[:, None])
            & (idx[None, :] < np.minimum(ends, lengths)[:, None])
        )
        flags = in_window & ((m < self.lower_bound) | (m > self.upper_bound))
        out = []
        for i, series in enumerate(series_list):
            rows = []
            for index in np.nonzero(flags[i])[0]:
                value = series[int(index)]
                rows.append(
                    (
                        int(index),
                        Anomaly(
                            value,
                            1.0,
                            f"[SimpleThresholdStrategy]: Value {value} is not in bounds "
                            f"[{self.lower_bound}, {self.upper_bound}]",
                        ),
                    )
                )
            out.append(rows)
        return out


@dataclass(frozen=True)
class _BaseChangeStrategy(AnomalyDetectionStrategy):
    """Nth-order discrete change detection
    (reference `anomalydetection/BaseChangeStrategy.scala:30-95`)."""

    max_rate_decrease: Optional[float] = None
    max_rate_increase: Optional[float] = None
    order: int = 1

    def __post_init__(self):
        if self.max_rate_decrease is None and self.max_rate_increase is None:
            raise ValueError(
                "At least one of the two limits (max_rate_decrease or max_rate_increase) "
                "has to be specified."
            )
        lo = self.max_rate_decrease if self.max_rate_decrease is not None else _NEG_INF
        hi = self.max_rate_increase if self.max_rate_increase is not None else _POS_INF
        if lo > hi:
            raise ValueError(
                "The maximal rate of increase has to be bigger than the maximal rate of decrease."
            )
        if self.order < 0:
            raise ValueError("Order of derivative cannot be negative.")

    def diff(self, series: np.ndarray, order: int) -> np.ndarray:
        if order == 0 or len(series) == 0:
            return series
        return self.diff(series[1:] - series[:-1], order - 1)

    def diff_matrix(self, m: np.ndarray, order: int) -> np.ndarray:
        """The series-axis twin of :meth:`diff` over an ``[N, T]`` matrix
        (same recursive pairwise subtraction, columns instead of scalars).
        Each output column j holds the order-``order`` change ending at
        input column ``j + order`` — window-start independent, which is
        what lets ONE matrix diff serve every per-series interval."""
        if order == 0 or m.shape[1] == 0:
            return m
        return self.diff_matrix(m[:, 1:] - m[:, :-1], order - 1)

    def detect_batch(self, series_list, search_interval):
        """Batched :meth:`detect`: N ragged series' nth-order changes
        compute in ONE matrix diff (``search_interval``: one shared tuple
        or one per series) — element-for-element identical to serial,
        because ``diff`` of a window equals the full-series diff
        restricted to the window's columns."""
        if not len(series_list):
            return []
        starts, ends = normalize_intervals(
            len(series_list), search_interval,
            "The start of the interval cannot be larger than the end.",
        )
        m, lengths = pad_series_matrix(series_list)
        lo = self.max_rate_decrease if self.max_rate_decrease is not None else _NEG_INF
        hi = self.max_rate_increase if self.max_rate_increase is not None else _POS_INF
        changes = self.diff_matrix(m, self.order)
        # diff column j = change ending at index j + order; the serial
        # window [max(start-order,0) : min(end,len)] maps to diff columns
        # [max(start-order,0), min(end,len)-order)
        j = np.arange(changes.shape[1], dtype=np.int64)
        start_points = np.maximum(starts - self.order, 0)
        stop = np.minimum(ends, lengths) - self.order
        in_window = (
            (j[None, :] >= start_points[:, None])
            & (j[None, :] < stop[:, None])
        )
        flags = in_window & ((changes < lo) | (changes > hi))
        out = []
        for i, series in enumerate(series_list):
            rows = []
            for col in np.nonzero(flags[i])[0]:
                index = int(col) + self.order
                change = changes[i, int(col)]
                rows.append(
                    (
                        index,
                        Anomaly(
                            series[index],
                            1.0,
                            f"[AbsoluteChangeStrategy]: Change of {change} is not in bounds "
                            f"[{lo}, {hi}]. Order={self.order}",
                        ),
                    )
                )
            out.append(rows)
        return out

    def detect(self, data_series, search_interval):
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval cannot be larger than the end.")
        start_point = max(start - self.order, 0)
        window = np.asarray(data_series[start_point:end], dtype=np.float64)
        data = self.diff(window, self.order)
        lo = self.max_rate_decrease if self.max_rate_decrease is not None else _NEG_INF
        hi = self.max_rate_increase if self.max_rate_increase is not None else _POS_INF
        out = []
        for i, change in enumerate(data):
            if change < lo or change > hi:
                index = i + start_point + self.order
                out.append(
                    (
                        index,
                        Anomaly(
                            data_series[index],
                            1.0,
                            f"[AbsoluteChangeStrategy]: Change of {change} is not in bounds "
                            f"[{lo}, {hi}]. Order={self.order}",
                        ),
                    )
                )
        return out


@dataclass(frozen=True)
class AbsoluteChangeStrategy(_BaseChangeStrategy):
    """(reference `anomalydetection/AbsoluteChangeStrategy.scala`)."""


@dataclass(frozen=True)
class RateOfChangeStrategy(_BaseChangeStrategy):
    """Deprecated alias of AbsoluteChangeStrategy
    (reference `anomalydetection/RateOfChangeStrategy.scala`)."""


@dataclass(frozen=True)
class RelativeRateOfChangeStrategy(_BaseChangeStrategy):
    """Ratio (current / order-steps-back) change detection
    (reference `anomalydetection/RelativeRateOfChangeStrategy.scala`)."""

    def diff(self, series: np.ndarray, order: int) -> np.ndarray:
        if order <= 0:
            raise ValueError("Order of diff cannot be zero or negative")
        if len(series) == 0:
            return series
        with np.errstate(divide="ignore", invalid="ignore"):
            return series[order:] / series[:-order]

    def diff_matrix(self, m: np.ndarray, order: int) -> np.ndarray:
        if order <= 0:
            raise ValueError("Order of diff cannot be zero or negative")
        if m.shape[1] == 0:
            return m
        with np.errstate(divide="ignore", invalid="ignore"):
            return m[:, order:] / m[:, :-order]


@dataclass(frozen=True)
class OnlineNormalStrategy(AnomalyDetectionStrategy):
    """Incremental mean/variance bounds with optional anomaly exclusion
    (reference `anomalydetection/OnlineNormalStrategy.scala:39-45`)."""

    lower_deviation_factor: Optional[float] = 3.0
    upper_deviation_factor: Optional[float] = 3.0
    ignore_start_percentage: float = 0.1
    ignore_anomalies: bool = True

    def __post_init__(self):
        if self.lower_deviation_factor is None and self.upper_deviation_factor is None:
            raise ValueError("At least one factor has to be specified.")
        if (self.lower_deviation_factor or 1.0) < 0 or (self.upper_deviation_factor or 1.0) < 0:
            raise ValueError("Factors cannot be smaller than zero.")
        if not 0.0 <= self.ignore_start_percentage <= 1.0:
            raise ValueError("Percentage of start values to ignore must be in interval [0, 1].")

    def compute_stats_and_anomalies(self, data_series, search_interval=(0, 2**63 - 1)):
        results = []
        current_mean = 0.0
        current_variance = 0.0
        sn = 0.0
        num_skip = len(data_series) * self.ignore_start_percentage
        search_start, search_end = search_interval
        upper_factor = (
            self.upper_deviation_factor if self.upper_deviation_factor is not None else _POS_INF
        )
        lower_factor = (
            self.lower_deviation_factor if self.lower_deviation_factor is not None else _POS_INF
        )
        for index, value in enumerate(data_series):
            last_mean, last_variance, last_sn = current_mean, current_variance, sn
            if index == 0:
                current_mean = value
            else:
                current_mean = last_mean + (value - last_mean) / (index + 1)
            sn += (value - last_mean) * (value - current_mean)
            current_variance = sn / (index + 1)
            std_dev = math.sqrt(current_variance)
            upper = current_mean + upper_factor * std_dev
            lower = current_mean - lower_factor * std_dev
            if (
                index < num_skip
                or index < search_start
                or index >= search_end
                or lower <= value <= upper
            ):
                results.append((current_mean, std_dev, False))
            else:
                if self.ignore_anomalies:
                    current_mean, current_variance, sn = last_mean, last_variance, last_sn
                results.append((current_mean, std_dev, True))
        return results

    def detect(self, data_series, search_interval):
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval can't be larger than the end.")
        stats = self.compute_stats_and_anomalies(data_series, search_interval)
        upper_factor = (
            self.upper_deviation_factor if self.upper_deviation_factor is not None else _POS_INF
        )
        lower_factor = (
            self.lower_deviation_factor if self.lower_deviation_factor is not None else _POS_INF
        )
        out = []
        for index in range(start, min(end, len(data_series))):
            mean, std_dev, is_anomaly = stats[index]
            if not is_anomaly:
                continue
            lower = mean - lower_factor * std_dev
            upper = mean + upper_factor * std_dev
            out.append(
                (
                    index,
                    Anomaly(
                        data_series[index],
                        1.0,
                        f"[OnlineNormalStrategy]: Value {data_series[index]} is not in "
                        f"bounds [{lower}, {upper}].",
                    ),
                )
            )
        return out

    # -- batched scoring core (fleet watch: ROADMAP item 5) ------------------

    def compute_stats_batch(
        self, series_matrix, lengths=None, search_interval=(0, 2**63 - 1)
    ):
        """The scoring core vectorized over a SERIES axis: one array-shaped
        call scores N metric series at once — the per-timestep recurrences
        (incremental mean, Welford ``sn``, the anomaly-exclusion rollback)
        run as elementwise numpy ops over all N series, so a fleet of
        thousands of tenants' metric histories scores in O(T) vector steps
        instead of N python loops. Per-element arithmetic is IDENTICAL to
        the one-series :meth:`compute_stats_and_anomalies` (same formula,
        same order, same IEEE ops), pinned by parity tests.

        ``series_matrix``: float64 ``[N, T]``, ragged series padded on the
        right (padding is ignored via ``lengths``). ``search_interval``:
        one shared ``(start, end)`` tuple, or a sequence of N per-series
        tuples (the fleet-watch shape — each tenant's newest point sits at
        its own ragged index). Returns ``(means, std_devs, is_anomaly)``
        each ``[N, T]``; entries past a series' length are zeros/False."""
        m = np.asarray(series_matrix, dtype=np.float64)
        if m.ndim != 2:
            raise ValueError("series_matrix must be [n_series, n_points]")
        n, t = m.shape
        lengths = (
            np.full(n, t, dtype=np.int64) if lengths is None
            else np.asarray(lengths, dtype=np.int64)
        )
        upper_factor = (
            self.upper_deviation_factor
            if self.upper_deviation_factor is not None else _POS_INF
        )
        lower_factor = (
            self.lower_deviation_factor
            if self.lower_deviation_factor is not None else _POS_INF
        )
        seq = list(search_interval)
        if len(seq) == 2 and not hasattr(seq[0], "__len__"):
            search_start, search_end = int(seq[0]), int(seq[1])
        else:
            # per-series intervals: the comparisons below are elementwise,
            # so arrays slot straight in (no validation here — the scalar
            # compute_stats_and_anomalies performs none either)
            search_start = np.array([int(s) for s, _ in seq], dtype=np.int64)
            search_end = np.array([int(e) for _, e in seq], dtype=np.int64)
        num_skip = lengths * self.ignore_start_percentage
        means = np.zeros((n, t))
        std_devs = np.zeros((n, t))
        flags = np.zeros((n, t), dtype=bool)
        current_mean = np.zeros(n)
        sn = np.zeros(n)
        for index in range(t):
            active = index < lengths
            value = np.where(active, m[:, index], 0.0)
            last_mean = current_mean
            last_sn = sn
            if index == 0:
                current_mean = value.copy()
            else:
                current_mean = last_mean + (value - last_mean) / (index + 1)
            sn = last_sn + (value - last_mean) * (value - current_mean)
            std_dev = np.sqrt(sn / (index + 1))
            upper = current_mean + upper_factor * std_dev
            lower = current_mean - lower_factor * std_dev
            # points outside the search interval are never FLAGGED — and,
            # exactly like the scalar path, never rolled back either
            anomaly = active & ~(
                (index < num_skip)
                | (index < search_start)
                | (index >= search_end)
                | ((lower <= value) & (value <= upper))
            )
            if self.ignore_anomalies:
                # the scalar path RESTORES the running stats for anomalous
                # points (and records the restored mean with the
                # pre-restore std) — replicated elementwise
                current_mean = np.where(anomaly, last_mean, current_mean)
                sn = np.where(anomaly, last_sn, sn)
            inactive = ~active
            current_mean = np.where(inactive, last_mean, current_mean)
            sn = np.where(inactive, last_sn, sn)
            means[:, index] = np.where(active, current_mean, 0.0)
            std_devs[:, index] = np.where(active, std_dev, 0.0)
            flags[:, index] = anomaly
        return means, std_devs, flags

    def detect_batch(self, series_list, search_interval):
        """Batched :meth:`detect`: N series score through ONE
        ``compute_stats_batch`` call (``search_interval``: one shared
        tuple or one per series); returns a list over series of the same
        ``[(index, Anomaly), ...]`` the one-series path produces (bounds,
        messages and indices identical — parity-pinned)."""
        if not len(series_list):
            return []
        starts, ends = normalize_intervals(
            len(series_list), search_interval,
            "The start of the interval can't be larger than the end.",
        )
        series_list = [np.asarray(s, dtype=np.float64) for s in series_list]
        m, lengths = pad_series_matrix(series_list)
        means, std_devs, flags = self.compute_stats_batch(
            m, lengths, list(zip(starts.tolist(), ends.tolist()))
        )
        upper_factor = (
            self.upper_deviation_factor
            if self.upper_deviation_factor is not None else _POS_INF
        )
        lower_factor = (
            self.lower_deviation_factor
            if self.lower_deviation_factor is not None else _POS_INF
        )
        out = []
        for i, series in enumerate(series_list):
            rows = []
            for index in range(int(starts[i]), min(int(ends[i]), len(series))):
                if not flags[i, index]:
                    continue
                mean = means[i, index]
                std_dev = std_devs[i, index]
                lower = mean - lower_factor * std_dev
                upper = mean + upper_factor * std_dev
                value = series[index]
                rows.append(
                    (
                        index,
                        Anomaly(
                            value,
                            1.0,
                            f"[OnlineNormalStrategy]: Value {value} is not "
                            f"in bounds [{lower}, {upper}].",
                        ),
                    )
                )
            out.append(rows)
        return out


@dataclass(frozen=True)
class BatchNormalStrategy(AnomalyDetectionStrategy):
    """Mean/stdDev bounds estimated from values outside the search interval
    (reference `anomalydetection/BatchNormalStrategy.scala:33-36`)."""

    lower_deviation_factor: Optional[float] = 3.0
    upper_deviation_factor: Optional[float] = 3.0
    include_interval: bool = False

    def __post_init__(self):
        if self.lower_deviation_factor is None and self.upper_deviation_factor is None:
            raise ValueError("At least one factor has to be specified.")
        if (self.lower_deviation_factor or 1.0) < 0 or (self.upper_deviation_factor or 1.0) < 0:
            raise ValueError("Factors cannot be smaller than zero.")

    def detect(self, data_series, search_interval):
        start, end = search_interval
        if start > end:
            raise ValueError("The start of the interval can't be larger than the end.")
        if len(data_series) == 0:
            raise ValueError("Data series is empty. Can't calculate mean/ stdDev.")
        series = np.asarray(data_series, dtype=np.float64)
        end_capped = min(end, len(series))
        if self.include_interval:
            basis = series
        else:
            basis = np.concatenate([series[:start], series[end_capped:]])
            if len(basis) == 0:
                raise ValueError(
                    "Excluding values in searchInterval from calculation but not enough values "
                    "remain to calculate mean and stdDev."
                )
        mean = float(np.mean(basis))
        # sample stddev like breeze meanAndVariance (ddof=1)
        std_dev = float(np.std(basis, ddof=1)) if len(basis) > 1 else 0.0
        upper_factor = (
            self.upper_deviation_factor if self.upper_deviation_factor is not None else _POS_INF
        )
        lower_factor = (
            self.lower_deviation_factor if self.lower_deviation_factor is not None else _POS_INF
        )
        upper = mean + upper_factor * std_dev
        lower = mean - lower_factor * std_dev
        out = []
        for index in range(start, end_capped):
            value = series[index]
            if value > upper or value < lower:
                out.append(
                    (
                        index,
                        Anomaly(
                            float(value),
                            1.0,
                            f"[BatchNormalStrategy]: Value {value} is not in "
                            f"bounds [{lower}, {upper}].",
                        ),
                    )
                )
        return out

    def detect_batch(self, series_list, search_interval):
        """Batched :meth:`detect` over N ragged series (``search_interval``:
        one shared tuple or one per series). The per-series mean/stdDev
        reductions run on each row's exact basis slice (identical
        reduction order — a masked full-width sum would round differently
        under numpy's pairwise summation); the bounds compare is one
        vectorized pass."""
        if not len(series_list):
            return []
        starts, ends = normalize_intervals(
            len(series_list), search_interval,
            "The start of the interval can't be larger than the end.",
        )
        upper_factor = (
            self.upper_deviation_factor if self.upper_deviation_factor is not None else _POS_INF
        )
        lower_factor = (
            self.lower_deviation_factor if self.lower_deviation_factor is not None else _POS_INF
        )
        m, lengths = pad_series_matrix(series_list)
        n = len(series_list)
        uppers = np.zeros(n)
        lowers = np.zeros(n)
        for i in range(n):
            if lengths[i] == 0:
                raise ValueError("Data series is empty. Can't calculate mean/ stdDev.")
            series = m[i, : lengths[i]]
            end_capped = min(int(ends[i]), int(lengths[i]))
            if self.include_interval:
                basis = series
            else:
                basis = np.concatenate(
                    [series[: int(starts[i])], series[end_capped:]]
                )
                if len(basis) == 0:
                    raise ValueError(
                        "Excluding values in searchInterval from calculation but not enough values "
                        "remain to calculate mean and stdDev."
                    )
            mean = float(np.mean(basis))
            std_dev = float(np.std(basis, ddof=1)) if len(basis) > 1 else 0.0
            uppers[i] = mean + upper_factor * std_dev
            lowers[i] = mean - lower_factor * std_dev
        idx = np.arange(m.shape[1], dtype=np.int64)
        in_window = (
            (idx[None, :] >= starts[:, None])
            & (idx[None, :] < np.minimum(ends, lengths)[:, None])
        )
        flags = in_window & ((m > uppers[:, None]) | (m < lowers[:, None]))
        out = []
        for i in range(n):
            rows = []
            for index in np.nonzero(flags[i])[0]:
                value = m[i, int(index)]
                rows.append(
                    (
                        int(index),
                        Anomaly(
                            float(value),
                            1.0,
                            f"[BatchNormalStrategy]: Value {value} is not in "
                            f"bounds [{lowers[i]}, {uppers[i]}].",
                        ),
                    )
                )
            out.append(rows)
        return out
