"""Wiring between checks and anomaly detection: the assertion behind
``Check.is_newest_point_non_anomalous`` (reference `checks/Check.scala:
998-1055` and `HistoryUtils.scala`)."""

from __future__ import annotations

from typing import Dict, Optional

from . import AnomalyDetector, DataPoint


def extract_metric_values(repository_results, analyzer):
    """AnalysisResults -> DataPoints for one analyzer
    (reference `HistoryUtils.extractMetricValues`)."""
    points = []
    for result in repository_results:
        metric = result.analyzer_context.metric_map.get(analyzer)
        value = None
        if metric is not None and metric.value.is_success:
            raw = metric.value.get()
            if isinstance(raw, (int, float)):
                value = float(raw)
        points.append(DataPoint(result.result_key.data_set_date, value))
    return points


def is_newest_point_non_anomalous(
    metrics_repository,
    anomaly_detection_strategy,
    analyzer,
    with_tag_values: Dict[str, str],
    after_date: Optional[int],
    before_date: Optional[int],
    current_metric_value: float,
) -> bool:
    loader = metrics_repository.load().for_analyzers([analyzer])
    if with_tag_values:
        loader = loader.with_tag_values(with_tag_values)
    if after_date is not None:
        loader = loader.after(after_date)
    if before_date is not None:
        loader = loader.before(before_date)
    repository_results = loader.get()
    history = extract_metric_values(repository_results, analyzer)
    if not history:
        raise ValueError(
            "There have to be previous results in the MetricsRepository!"
        )
    test_time = max(p.time for p in history) + 1
    detector = AnomalyDetector(anomaly_detection_strategy)
    result = detector.is_new_point_anomalous(
        history, DataPoint(test_time, float(current_metric_value))
    )
    return len(result.anomalies) == 0
