"""Anomaly detection over metric time series
(reference `anomalydetection/*.scala`).

An :class:`AnomalyDetectionStrategy` finds anomalies in a value series within
a search interval; :class:`AnomalyDetector` handles the
sort/filter/new-point protocol. Series here are metric histories (length
<< 1e5), so everything is plain numpy on host — same as the reference, where
this is driver-side breeze code.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class Anomaly:
    """(reference `anomalydetection/DetectionResult.scala`)."""

    value: Optional[float]
    confidence: float
    detail: Optional[str] = None

    def __eq__(self, other):
        if not isinstance(other, Anomaly):
            return NotImplemented
        return self.value == other.value and self.confidence == other.confidence


@dataclass(frozen=True)
class DetectionResult:
    anomalies: Tuple[Tuple[int, Anomaly], ...] = ()


@dataclass(frozen=True)
class DataPoint:
    """(reference `anomalydetection/AnomalyDetector.scala:19`)."""

    time: int
    metric_value: Optional[float]


class AnomalyDetectionStrategy(abc.ABC):
    @abc.abstractmethod
    def detect(
        self, data_series: Sequence[float], search_interval: Tuple[int, int]
    ) -> List[Tuple[int, Anomaly]]:
        """Find anomalies at indices within [start, end) of the series."""

    def detect_batch(self, series_list, search_interval):
        """Score N series at once; returns one ``[(index, Anomaly), ...]``
        list per series. ``search_interval``: one shared ``(start, end)``
        tuple, or a sequence of N per-series tuples (the fleet-watch
        shape). This default simply loops :meth:`detect` — every strategy
        is batchable by contract; the vectorizable strategies override it
        with array-shaped cores that are element-for-element identical
        to serial (parity-pinned by tests/test_anomaly_reference.py)."""
        from .strategies import normalize_intervals

        if not len(series_list):
            return []
        starts, ends = normalize_intervals(
            len(series_list), search_interval,
            "The start of the interval can't be larger than the end.",
        )
        return [
            self.detect(series, (int(starts[i]), int(ends[i])))
            for i, series in enumerate(series_list)
        ]


@dataclass(frozen=True)
class AnomalyDetector:
    """(reference `anomalydetection/AnomalyDetector.scala:21-90`)."""

    strategy: AnomalyDetectionStrategy

    def is_new_point_anomalous(
        self, historical_data_points: Sequence[DataPoint], new_point: DataPoint
    ) -> DetectionResult:
        if not historical_data_points:
            raise ValueError("historicalDataPoints must not be empty!")
        sorted_points = sorted(historical_data_points, key=lambda p: p.time)
        last_time = sorted_points[-1].time
        if last_time >= new_point.time:
            raise ValueError(
                "Can't decide which range to use for anomaly detection. New data point with "
                f"time {new_point.time} is in history range "
                f"({sorted_points[0].time} - {last_time})!"
            )
        all_points = list(sorted_points) + [new_point]
        result = self.detect_anomalies_in_history(
            all_points, (new_point.time, np.iinfo(np.int64).max)
        )
        return DetectionResult(result.anomalies)

    def detect_anomalies_in_history(
        self,
        data_series: Sequence[DataPoint],
        search_interval: Tuple[int, int] = (np.iinfo(np.int64).min, np.iinfo(np.int64).max),
    ) -> DetectionResult:
        search_start, search_end = search_interval
        if search_start > search_end:
            raise ValueError("The first interval element has to be smaller or equal to the last.")
        present = [p for p in data_series if p.metric_value is not None]
        sorted_series = sorted(present, key=lambda p: p.time)
        timestamps = [p.time for p in sorted_series]
        lower = int(np.searchsorted(timestamps, search_start, side="left"))
        upper = int(np.searchsorted(timestamps, search_end, side="left"))
        values = [p.metric_value for p in sorted_series]
        anomalies = self.strategy.detect(values, (lower, upper))
        return DetectionResult(
            tuple((timestamps[idx], anomaly) for idx, anomaly in anomalies)
        )


from .strategies import (  # noqa: E402
    AbsoluteChangeStrategy,
    BatchNormalStrategy,
    OnlineNormalStrategy,
    RateOfChangeStrategy,
    RelativeRateOfChangeStrategy,
    SimpleThresholdStrategy,
)
from .seasonal import HoltWinters, MetricInterval, SeriesSeasonality  # noqa: E402

__all__ = [
    "AbsoluteChangeStrategy",
    "Anomaly",
    "AnomalyDetectionStrategy",
    "AnomalyDetector",
    "BatchNormalStrategy",
    "DataPoint",
    "DetectionResult",
    "HoltWinters",
    "MetricInterval",
    "OnlineNormalStrategy",
    "RateOfChangeStrategy",
    "RelativeRateOfChangeStrategy",
    "SeriesSeasonality",
    "SimpleThresholdStrategy",
]
