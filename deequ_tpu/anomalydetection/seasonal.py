"""Additive Holt-Winters seasonal anomaly detection with L-BFGS-B parameter
fitting (reference `anomalydetection/seasonal/HoltWinters.scala:63-249`,
which uses breeze's LBFGSB; here scipy's)."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from . import Anomaly, AnomalyDetectionStrategy


class SeriesSeasonality(enum.Enum):
    WEEKLY = "Weekly"
    YEARLY = "Yearly"


class MetricInterval(enum.Enum):
    DAILY = "Daily"
    MONTHLY = "Monthly"


@dataclass(frozen=True)
class ModelResults:
    forecasts: List[float]
    level: List[float]
    trend: List[float]
    seasonality: List[float]
    residuals: List[float]


def additive_holt_winters(
    series: Sequence[float],
    periodicity: int,
    number_of_points_to_forecast: int,
    alpha: float,
    beta: float,
    gamma: float,
) -> ModelResults:
    """(reference `HoltWinters.scala:76-124` — same recurrences)."""
    series = list(series)
    m = periodicity
    first_period_sum = sum(series[:m])
    second_period_sum = sum(series[m : 2 * m])
    level = [first_period_sum / m]
    trend = [(second_period_sum - first_period_sum) / (m * m)]
    seasonality = [x - level[0] for x in series[:m]]
    y = [level[0] + trend[0] + seasonality[0]]
    big_y = list(series)
    for t in range(len(series) + number_of_points_to_forecast):
        if t >= len(series):
            big_y.append(level[-1] + trend[-1] + seasonality[len(seasonality) - m])
        level.append(alpha * (big_y[t] - seasonality[t]) + (1 - alpha) * (level[t] + trend[t]))
        trend.append(beta * (level[t + 1] - level[t]) + (1 - beta) * trend[t])
        seasonality.append(
            gamma * (big_y[t] - level[t] - trend[t]) + (1 - gamma) * seasonality[t]
        )
        y.append(level[t + 1] + trend[t + 1] + seasonality[t + 1])
    residuals = [series_value - forecast for forecast, series_value in zip(y, series)]
    forecasted = big_y[len(series) :]
    return ModelResults(forecasted, level, trend, seasonality, residuals)


@dataclass(frozen=True)
class BatchModelResults:
    """Per-series forecasts/residuals of one batched Holt-Winters pass.
    ``forecasts[i, :n_forecasts[i]]`` and ``residuals[i, :train_lengths[i]]``
    are meaningful; the padding is zeros."""

    forecasts: np.ndarray  # [N, max(n_forecasts)]
    residuals: np.ndarray  # [N, max(train_lengths)]
    train_lengths: np.ndarray
    n_forecasts: np.ndarray


def additive_holt_winters_batch(
    matrix: np.ndarray,
    train_lengths: np.ndarray,
    periodicity: int,
    n_forecasts: np.ndarray,
    alphas: np.ndarray,
    betas: np.ndarray,
    gammas: np.ndarray,
) -> BatchModelResults:
    """The series-axis twin of :func:`additive_holt_winters`: N training
    series (right-padded rows of ``matrix``, per-series ``train_lengths``)
    run the level/trend/seasonality recurrences as ONE elementwise vector
    pass over timesteps — a fleet of tenants' seasonal models evaluates in
    O(T) array steps instead of N python loops. Per-element arithmetic is
    IDENTICAL to the scalar recurrence (same formula, same op order, same
    IEEE doubles; the initial period sums accumulate left-to-right exactly
    like python's ``sum``), pinned by parity tests.

    Requires every ``train_lengths[i] >= periodicity`` (the scalar path's
    seasonal-list layout only aligns with the shared buffer then — callers
    route shorter histories through the scalar code)."""
    m = int(periodicity)
    mat = np.asarray(matrix, dtype=np.float64)
    tl = np.asarray(train_lengths, dtype=np.int64)
    nf = np.asarray(n_forecasts, dtype=np.int64)
    alphas = np.asarray(alphas, dtype=np.float64)
    betas = np.asarray(betas, dtype=np.float64)
    gammas = np.asarray(gammas, dtype=np.float64)
    if np.any(tl < m):
        raise ValueError(
            "additive_holt_winters_batch requires at least one full cycle "
            "of training per series (route shorter series through the "
            "scalar path)"
        )
    n, width = mat.shape
    total = tl + nf
    steps = int(total.max()) if n else 0
    zeros = np.zeros(n)

    def col(j: int) -> np.ndarray:
        return mat[:, j] if j < width else zeros

    # initial level/trend: the scalar path's python `sum` is a
    # left-to-right fold — replicate it column by column (m is 7 or 12)
    first = np.zeros(n)
    second = np.zeros(n)
    for j in range(m):
        first = first + np.where(j < tl, col(j), 0.0)
    for j in range(m, 2 * m):
        second = second + np.where(j < tl, col(j), 0.0)
    level = first / m
    trend = (second - first) / (m * m)
    # seasonality buffer: index j<m holds the init entries, index m+t the
    # entry appended at step t — the same layout as the scalar list, so
    # every scalar list read seasonality[k] is exactly S[:, k]
    seas = np.zeros((n, m + steps + 1))
    for j in range(m):
        seas[:, j] = col(j) - level
    residuals = np.zeros((n, int(tl.max()) if n else 0))
    forecasts = np.zeros((n, int(nf.max()) if n else 0))
    if residuals.shape[1] > 0:
        residuals[:, 0] = np.where(
            0 < tl, col(0) - (level + trend + seas[:, 0]), 0.0
        )
    rows = np.arange(n)
    for t in range(steps):
        active = t < total
        in_train = t < tl
        observed = col(t)
        # big_y[t]: the observed training value, or (in the forecast
        # phase) level[-1] + trend[-1] + seasonality[len - m] == S[:, t]
        big_y = np.where(in_train, observed, level + trend + seas[:, t])
        fc_mask = active & ~in_train
        if fc_mask.any():
            forecasts[rows[fc_mask], (t - tl)[fc_mask]] = big_y[fc_mask]
        level_next = alphas * (big_y - seas[:, t]) + (1 - alphas) * (level + trend)
        trend_next = betas * (level_next - level) + (1 - betas) * trend
        seas[:, m + t] = gammas * (big_y - level - trend) + (1 - gammas) * seas[:, t]
        # y[t+1] = level[t+1] + trend[t+1] + seasonality[t+1] (list index
        # t+1 — an INIT entry while t+1 < m, the step-(t+1-m) append after)
        y_next = level_next + trend_next + seas[:, t + 1]
        r_mask = active & (t + 1 < tl)
        if r_mask.any():
            residuals[r_mask, t + 1] = (col(t + 1) - y_next)[r_mask]
        # freeze finished lanes so a short series' state cannot drift (its
        # outputs are already recorded; this only guards against overflow
        # in dead lanes)
        level = np.where(active, level_next, level)
        trend = np.where(active, trend_next, trend)
    return BatchModelResults(forecasts, residuals, tl, nf)


class HoltWinters(AnomalyDetectionStrategy):
    """(reference `HoltWinters.scala:63-249`; periodicity table `:70-73`)."""

    def __init__(self, metrics_interval: MetricInterval, seasonality: SeriesSeasonality):
        table = {
            (SeriesSeasonality.WEEKLY, MetricInterval.DAILY): 7,
            (SeriesSeasonality.YEARLY, MetricInterval.MONTHLY): 12,
        }
        key = (seasonality, metrics_interval)
        if key not in table:
            raise ValueError(
                "Only (Weekly seasonality, Daily interval) and (Yearly, Monthly) are supported"
            )
        self.series_periodicity = table[key]

    def _fit(self, series: Sequence[float], num_forecast: int) -> Tuple[float, float, float]:
        from scipy.optimize import minimize

        m = self.series_periodicity

        def objective(x: np.ndarray) -> float:
            results = additive_holt_winters(series, m, num_forecast, x[0], x[1], x[2])
            return float(sum(r * r for r in results.residuals))

        res = minimize(
            objective,
            x0=np.array([0.3, 0.1, 0.1]),
            method="L-BFGS-B",
            bounds=[(0.0, 1.0)] * 3,
        )
        return float(res.x[0]), float(res.x[1]), float(res.x[2])

    def _validate(self, data_series, start: int, end: int) -> int:
        """The scalar path's validations, shared with the batched twin so
        both fail identically; returns the forecast count."""
        if len(data_series) == 0:
            raise ValueError("Provided data series is empty")
        if start >= end:
            raise ValueError("Start must be before end")
        if start < 0 or end < 0:
            raise ValueError("The search interval needs to be strictly positive")
        if start < self.series_periodicity * 2:
            raise ValueError("Need at least two full cycles of data to estimate model")
        if start >= len(data_series):
            return 1
        return min(end, len(data_series)) - start

    @staticmethod
    def _flag(data_series, start, forecasts, residuals):
        """Residual-threshold flagging shared by scalar and batched paths
        (same 1.96-sigma rule, same message)."""
        abs_residuals = np.abs(np.asarray(residuals))
        residual_sd = float(np.std(abs_residuals, ddof=1)) if len(abs_residuals) > 1 else 0.0
        out = []
        test_series = data_series[start:]
        for detection_index, (observed, forecast) in enumerate(
            zip(test_series, forecasts)
        ):
            if abs(observed - forecast) > 1.96 * residual_sd:
                out.append(
                    (
                        detection_index + start,
                        Anomaly(
                            observed,
                            1.0,
                            f"Forecasted {forecast} for observed value {observed}",
                        ),
                    )
                )
        return out

    def detect(self, data_series, search_interval=(0, 2**31 - 1)):
        start, end = search_interval
        num_forecast = self._validate(data_series, start, end)
        training = list(data_series[:start])
        alpha, beta, gamma = self._fit(training, num_forecast)
        results = additive_holt_winters(
            training, self.series_periodicity, num_forecast, alpha, beta, gamma
        )
        return self._flag(data_series, start, results.forecasts, results.residuals)

    # -- batched scoring (fleet watch: ROADMAP item 5) -----------------------

    def fit_batch(self, series_list, search_interval=(0, 2**31 - 1)):
        """Per-series L-BFGS-B parameter fits for a fleet, via the SAME
        scalar objective ``detect`` uses (parameters are therefore
        bit-identical to serial — the optimizer is inherently per-series;
        it is the model-evaluation recurrences that batch). Returns a list
        of (alpha, beta, gamma). Callers scoring the same histories every
        harvest can cache these and pass them to :meth:`detect_batch`."""
        from .strategies import normalize_intervals

        if not len(series_list):
            return []
        starts, ends = normalize_intervals(
            len(series_list), search_interval, "Start must be before end"
        )
        out = []
        for i, series in enumerate(series_list):
            nf = self._validate(series, int(starts[i]), int(ends[i]))
            out.append(self._fit(list(series[: int(starts[i])]), nf))
        return out

    def detect_batch(self, series_list, search_interval=(0, 2**31 - 1), params=None):
        """Batched :meth:`detect`: every series' seasonal model evaluates
        in ONE :func:`additive_holt_winters_batch` vector pass (parameters
        from ``params`` — e.g. a cached :meth:`fit_batch` — or fitted
        per series exactly like serial), element-for-element identical to
        the scalar path. ``search_interval``: one shared tuple or one per
        series. Series whose training span is shorter than one full cycle
        (possible only when the series itself is shorter than the
        validated ``2 * periodicity`` start) route through the scalar
        recurrence — the shared seasonal buffer only aligns with the
        scalar list layout from one cycle up."""
        from .strategies import normalize_intervals

        if not len(series_list):
            return []
        starts, ends = normalize_intervals(
            len(series_list), search_interval, "Start must be before end"
        )
        m = self.series_periodicity
        n = len(series_list)
        n_forecasts = np.zeros(n, dtype=np.int64)
        train_lengths = np.zeros(n, dtype=np.int64)
        for i, series in enumerate(series_list):
            n_forecasts[i] = self._validate(series, int(starts[i]), int(ends[i]))
            train_lengths[i] = min(int(starts[i]), len(series))
        if params is None:
            params = [
                self._fit(list(series[: int(starts[i])]), int(n_forecasts[i]))
                for i, series in enumerate(series_list)
            ]
        out: List = [None] * n
        batched = [i for i in range(n) if train_lengths[i] >= m]
        batched_set = set(batched)
        for i in range(n):
            if i in batched_set:
                continue
            results = additive_holt_winters(
                list(series_list[i][: int(starts[i])]), m,
                int(n_forecasts[i]), *params[i]
            )
            out[i] = self._flag(
                series_list[i], int(starts[i]),
                results.forecasts, results.residuals,
            )
        if batched:
            width = int(train_lengths[batched].max())
            mat = np.zeros((len(batched), width))
            for row, i in enumerate(batched):
                tl = int(train_lengths[i])
                mat[row, :tl] = np.asarray(
                    series_list[i][:tl], dtype=np.float64
                )
            res = additive_holt_winters_batch(
                mat, train_lengths[batched], m, n_forecasts[batched],
                np.array([params[i][0] for i in batched]),
                np.array([params[i][1] for i in batched]),
                np.array([params[i][2] for i in batched]),
            )
            for row, i in enumerate(batched):
                tl = int(train_lengths[i])
                nf = int(n_forecasts[i])
                out[i] = self._flag(
                    series_list[i], int(starts[i]),
                    res.forecasts[row, :nf], res.residuals[row, :tl],
                )
        return out
