"""Additive Holt-Winters seasonal anomaly detection with L-BFGS-B parameter
fitting (reference `anomalydetection/seasonal/HoltWinters.scala:63-249`,
which uses breeze's LBFGSB; here scipy's)."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from . import Anomaly, AnomalyDetectionStrategy


class SeriesSeasonality(enum.Enum):
    WEEKLY = "Weekly"
    YEARLY = "Yearly"


class MetricInterval(enum.Enum):
    DAILY = "Daily"
    MONTHLY = "Monthly"


@dataclass(frozen=True)
class ModelResults:
    forecasts: List[float]
    level: List[float]
    trend: List[float]
    seasonality: List[float]
    residuals: List[float]


def additive_holt_winters(
    series: Sequence[float],
    periodicity: int,
    number_of_points_to_forecast: int,
    alpha: float,
    beta: float,
    gamma: float,
) -> ModelResults:
    """(reference `HoltWinters.scala:76-124` — same recurrences)."""
    series = list(series)
    m = periodicity
    first_period_sum = sum(series[:m])
    second_period_sum = sum(series[m : 2 * m])
    level = [first_period_sum / m]
    trend = [(second_period_sum - first_period_sum) / (m * m)]
    seasonality = [x - level[0] for x in series[:m]]
    y = [level[0] + trend[0] + seasonality[0]]
    big_y = list(series)
    for t in range(len(series) + number_of_points_to_forecast):
        if t >= len(series):
            big_y.append(level[-1] + trend[-1] + seasonality[len(seasonality) - m])
        level.append(alpha * (big_y[t] - seasonality[t]) + (1 - alpha) * (level[t] + trend[t]))
        trend.append(beta * (level[t + 1] - level[t]) + (1 - beta) * trend[t])
        seasonality.append(
            gamma * (big_y[t] - level[t] - trend[t]) + (1 - gamma) * seasonality[t]
        )
        y.append(level[t + 1] + trend[t + 1] + seasonality[t + 1])
    residuals = [series_value - forecast for forecast, series_value in zip(y, series)]
    forecasted = big_y[len(series) :]
    return ModelResults(forecasted, level, trend, seasonality, residuals)


class HoltWinters(AnomalyDetectionStrategy):
    """(reference `HoltWinters.scala:63-249`; periodicity table `:70-73`)."""

    def __init__(self, metrics_interval: MetricInterval, seasonality: SeriesSeasonality):
        table = {
            (SeriesSeasonality.WEEKLY, MetricInterval.DAILY): 7,
            (SeriesSeasonality.YEARLY, MetricInterval.MONTHLY): 12,
        }
        key = (seasonality, metrics_interval)
        if key not in table:
            raise ValueError(
                "Only (Weekly seasonality, Daily interval) and (Yearly, Monthly) are supported"
            )
        self.series_periodicity = table[key]

    def _fit(self, series: Sequence[float], num_forecast: int) -> Tuple[float, float, float]:
        from scipy.optimize import minimize

        m = self.series_periodicity

        def objective(x: np.ndarray) -> float:
            results = additive_holt_winters(series, m, num_forecast, x[0], x[1], x[2])
            return float(sum(r * r for r in results.residuals))

        res = minimize(
            objective,
            x0=np.array([0.3, 0.1, 0.1]),
            method="L-BFGS-B",
            bounds=[(0.0, 1.0)] * 3,
        )
        return float(res.x[0]), float(res.x[1]), float(res.x[2])

    def detect(self, data_series, search_interval=(0, 2**31 - 1)):
        if len(data_series) == 0:
            raise ValueError("Provided data series is empty")
        start, end = search_interval
        if start >= end:
            raise ValueError("Start must be before end")
        if start < 0 or end < 0:
            raise ValueError("The search interval needs to be strictly positive")
        if start < self.series_periodicity * 2:
            raise ValueError("Need at least two full cycles of data to estimate model")

        if start >= len(data_series):
            num_forecast = 1
        else:
            num_forecast = min(end, len(data_series)) - start

        training = list(data_series[:start])
        alpha, beta, gamma = self._fit(training, num_forecast)
        results = additive_holt_winters(
            training, self.series_periodicity, num_forecast, alpha, beta, gamma
        )
        abs_residuals = np.abs(np.asarray(results.residuals))
        residual_sd = float(np.std(abs_residuals, ddof=1)) if len(abs_residuals) > 1 else 0.0

        out = []
        test_series = data_series[start:]
        for detection_index, (observed, forecast) in enumerate(
            zip(test_series, results.forecasts)
        ):
            if abs(observed - forecast) > 1.96 * residual_sd:
                out.append(
                    (
                        detection_index + start,
                        Anomaly(
                            observed,
                            1.0,
                            f"Forecasted {forecast} for observed value {observed}",
                        ),
                    )
                )
        return out
