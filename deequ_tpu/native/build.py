"""Build the native host-kernel shared library.

Usage: ``python -m deequ_tpu.native.build``; `lib.py` also invokes this
automatically on first use (set DEEQU_TPU_NO_NATIVE=1 to disable).
"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_DIR, "src", "host_kernels.cpp")
LIBRARY = os.path.join(_DIR, "_host_kernels.so")


def build(force: bool = False) -> str:
    """Compile the shared library if missing/stale; returns its path."""
    if (
        not force
        and os.path.exists(LIBRARY)
        and os.path.getmtime(LIBRARY) >= os.path.getmtime(SOURCE)
    ):
        return LIBRARY
    # compile to a temp path and rename into place so concurrent importers
    # never dlopen a half-written library
    tmp = f"{LIBRARY}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
        "-o", tmp, SOURCE, "-ldl",
    ]
    try:
        result = subprocess.run(cmd, capture_output=True, text=True)
        if result.returncode != 0:
            raise RuntimeError(f"native build failed:\n{result.stderr}")
        os.replace(tmp, LIBRARY)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return LIBRARY


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(f"built {path}")
