"""Native (C++) runtime kernels for host-side hot loops.

The reference's "native tier" is its set of Catalyst ImperativeAggregate /
UDAF kernels injected into Spark internals (reference
`analyzers/catalyst/*.scala`). Here the device tier is XLA/Pallas; this
package holds the *host* native tier: batch string hashing, regex/type
classification, HLL ingest packing and group-by keying over Arrow buffers,
compiled from C++ (`deequ_tpu/native/src/`) and loaded via ctypes.

Falls back to pure Python (exports = None) when the shared library has not
been built; build with `python -m deequ_tpu.native.build`.
"""

from __future__ import annotations

native_xxhash64_strings = None
native_classify_types = None
native_string_lengths = None
native_hll_pack_numeric = None
native_hll_pack_strings = None
native_block_stats = None
native_block_comoments = None
native_block_hll = None
native_block_hll_strings = None
native_block_kll_sample = None
native_dict_masked_bincount = None
native_block_kll_pick = None
native_pattern_match = None
native_u64_value_counts = None

try:  # pragma: no cover - exercised when the native lib is built
    from .lib import (  # noqa: F401
        native_block_comoments,
        native_block_hll,
        native_block_hll_strings,
        native_block_kll_pick,
        native_block_kll_sample,
        native_dict_masked_bincount,
        native_block_stats,
        native_classify_types,
        native_hll_pack_numeric,
        native_hll_pack_strings,
        native_pattern_match,
        native_string_lengths,
        native_u64_value_counts,
        native_xxhash64_strings,
    )
except Exception:  # noqa: BLE001
    pass
