"""Native (C++) runtime kernels for host-side hot loops.

The reference's "native tier" is its set of Catalyst ImperativeAggregate /
UDAF kernels injected into Spark internals (reference
`analyzers/catalyst/*.scala`). Here the device tier is XLA/Pallas; this
package holds the *host* native tier: batch string hashing, regex/type
classification and group-by keying over Arrow buffers, compiled from C++
(`deequ_tpu/native/src/`) and loaded via ctypes.

Falls back to pure Python (exports = None) when the shared library has not
been built; build with `python -m deequ_tpu.native.build`.
"""

from __future__ import annotations

native_xxhash64_strings = None
native_classify_types = None
native_string_lengths = None

try:  # pragma: no cover - exercised when the native lib is built
    from .lib import (  # noqa: F401
        native_classify_types,
        native_string_lengths,
        native_xxhash64_strings,
    )
except Exception:  # noqa: BLE001
    pass
