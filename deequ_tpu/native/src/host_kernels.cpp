// Native host kernels for the string-typed hot loops of the scan frontend.
//
// The reference's native tier is its set of Catalyst ImperativeAggregate /
// UDAF kernels doing per-row buffer updates inside Spark executors
// (reference `analyzers/catalyst/StatefulHyperloglogPlus.scala:89-115`,
// `StatefulDataType.scala:26-83`). Here the device tier is XLA; this C++
// tier covers the host-side per-value string work the device cannot do:
// xxHash64 batch hashing (HLL ingest), type classification (DataType
// analyzer) and UTF-8 length counting (Min/MaxLength), all operating on
// Arrow-layout buffers (concatenated UTF-8 bytes + offsets) in one pass.
//
// Build: python -m deequ_tpu.native.build  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// xxHash64 (public algorithm; must match deequ_tpu/ops/hashing.py and
// Spark's XxHash64Function bit-for-bit)
// ---------------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / aarch64)
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static uint64_t xxh64(const uint8_t* data, int64_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = rotl64(v1 + read64(p) * P2, 31) * P1; p += 8;
      v2 = rotl64(v2 + read64(p) * P2, 31) * P1; p += 8;
      v3 = rotl64(v3 + read64(p) * P2, 31) * P1; p += 8;
      v4 = rotl64(v4 + read64(p) * P2, 31) * P1; p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = (h ^ (rotl64(v1 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v2 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v3 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v4 * P2, 31) * P1)) * P1 + P4;
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h = rotl64(h ^ (rotl64(read64(p) * P2, 31) * P1), 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h = rotl64(h ^ ((uint64_t)read32(p) * P1), 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h = rotl64(h ^ ((uint64_t)(*p) * P5), 11) * P1;
    ++p;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// hash n strings given arrow large-string layout; null/invalid -> seed
void xxhash64_batch(const uint8_t* data, const int64_t* offsets,
                    const uint8_t* valid, int64_t n, uint64_t seed,
                    uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = seed;
      continue;
    }
    out[i] = xxh64(data + offsets[i], offsets[i + 1] - offsets[i], seed);
  }
}

// ---------------------------------------------------------------------------
// HLL ingest: hash value -> (register index, leading-zero count), packed as
// uint16 = (idx << 6) | pw. One pass per column, so the device feed is 2
// bytes/row instead of 8 (mirrors the per-row math of the reference
// `StatefulHyperloglogPlus.update`, `StatefulHyperloglogPlus.scala:93-114`:
// idx = top P bits, pw = clz((hash << P) | 1 << (P-1)) + 1, P = 9).
// Nulls pack as 0 (idx 0, pw 0), which never wins a register max.
// ---------------------------------------------------------------------------

static const int HLL_P = 9;

static inline uint16_t hll_pack_hash(uint64_t h) {
  uint32_t idx = (uint32_t)(h >> (64 - HLL_P));
  uint64_t w = (h << HLL_P) | (1ULL << (HLL_P - 1));
  // w always has a bit set (the padding bit), so clzll is defined
  uint32_t pw = (uint32_t)__builtin_clzll(w) + 1;
  return (uint16_t)((idx << 6) | pw);
}

static inline uint64_t xxh64_fixed8(uint64_t value, uint64_t seed) {
  // xxh64 specialized to an 8-byte input (Spark hashes fixed-width values
  // as one little-endian long)
  uint64_t h = seed + P5 + 8;
  uint64_t k = rotl64(value * P2, 31) * P1;
  h ^= k;
  h = rotl64(h, 27) * P1 + P4;
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// doubles: IEEE754 bits with -0.0 normalized to 0.0 (Spark semantics)
void hll_pack_f64(const double* vals, const uint8_t* valid, int64_t n,
                  uint64_t seed, uint16_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    double d = vals[i] == 0.0 ? 0.0 : vals[i];  // collapses -0.0
    uint64_t bits;
    std::memcpy(&bits, &d, 8);
    out[i] = hll_pack_hash(xxh64_fixed8(bits, seed));
  }
}

void hll_pack_i64(const int64_t* vals, const uint8_t* valid, int64_t n,
                  uint64_t seed, uint16_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    out[i] = hll_pack_hash(xxh64_fixed8((uint64_t)vals[i], seed));
  }
}

// strings in arrow large-string layout
void hll_pack_strings(const uint8_t* data, const int64_t* offsets,
                      const uint8_t* valid, int64_t n, uint64_t seed,
                      uint16_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    out[i] = hll_pack_hash(
        xxh64(data + offsets[i], offsets[i + 1] - offsets[i], seed));
  }
}

// ---------------------------------------------------------------------------
// type classification (reference regexes,
// `analyzers/catalyst/StatefulDataType.scala:36-38`):
//   FRACTIONAL: ^(-|\+)? ?\d*\.\d*$
//   INTEGRAL:   ^(-|\+)? ?\d*$
//   BOOLEAN:    ^(true|false)$
// decision order: null -> fractional -> integral -> boolean -> string
// codes: 0=null/unknown 1=fractional 2=integral 3=boolean 4=string
// ---------------------------------------------------------------------------

static inline bool match_numericish(const uint8_t* s, int64_t len, bool* fractional) {
  int64_t i = 0;
  if (i < len && (s[i] == '-' || s[i] == '+')) ++i;
  if (i < len && s[i] == ' ') ++i;  // the reference regex admits one space
  int64_t digits_before = 0;
  while (i < len && s[i] >= '0' && s[i] <= '9') { ++i; ++digits_before; }
  if (i == len) {           // integral (digits may be empty, as in the regex)
    *fractional = false;
    return true;
  }
  if (s[i] != '.') return false;
  ++i;
  while (i < len && s[i] >= '0' && s[i] <= '9') ++i;
  if (i != len) return false;
  *fractional = true;       // digits on either side of '.' may be empty
  return true;
}

static inline bool match_boolean(const uint8_t* s, int64_t len) {
  return (len == 4 && std::memcmp(s, "true", 4) == 0) ||
         (len == 5 && std::memcmp(s, "false", 5) == 0);
}

void classify_types_batch(const uint8_t* data, const int64_t* offsets,
                          const uint8_t* valid, int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    const uint8_t* s = data + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    bool fractional = false;
    if (match_numericish(s, len, &fractional)) {
      out[i] = fractional ? 1 : 2;
    } else if (match_boolean(s, len)) {
      out[i] = 3;
    } else {
      out[i] = 4;
    }
  }
}

// ---------------------------------------------------------------------------
// UTF-8 codepoint lengths (matches python len(str)); null -> 0
// ---------------------------------------------------------------------------

void string_lengths_batch(const uint8_t* data, const int64_t* offsets,
                          const uint8_t* valid, int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    const uint8_t* s = data + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int32_t count = 0;
    for (int64_t j = 0; j < len; ++j) {
      if ((s[j] & 0xC0) != 0x80) ++count;  // count non-continuation bytes
    }
    out[i] = count;
  }
}

// ---------------------------------------------------------------------------
// Block-partial reduction kernels (the ingest tier).
//
// When the accelerator feed link cannot sustain raw column streaming (the
// engine probes this), per-batch partial states are computed here — one
// C-speed pass over the block — and the device folds the tiny states with
// the same semigroup `merge` algebra it uses across shards (SURVEY.md §2.9:
// partial aggregation near the data + algebraic merge IS the reference's
// execution model; Spark's partial-agg runs executor-side for the same
// reason). Two-pass moments match the batch formulas of the device update
// (`analyzers/simple.py` StandardDeviation/Correlation.update).
// ---------------------------------------------------------------------------

// NaN semantics (uniform with the device update and the numpy fallback in
// HostBatchContext.block_stats — Spark's NaN-largest total order): NaN never
// wins the min (min is NaN only when NO non-NaN value exists, which is also
// the MinState identity); ANY nonnull NaN wins the max; sum/m2 propagate NaN.
//
// The loops are branchless with LANES independent accumulators so -O3
// -march=native auto-vectorizes them (blend + fma); masked-out slots blend
// to the identity BEFORE any arithmetic, so garbage bytes in Arrow null
// slots (possibly NaN/inf) never poison a lane. Lane-wise summation
// reassociates the additions; the resulting sums are at least as accurate
// as the sequential order and well inside the engine's 1e-9 cross-path
// tolerance.
#define BLOCK_STATS_LANES 8
#define BLOCK_STATS_IMPL(NAME, T)                                            \
  void NAME(const T* v, const uint8_t* m, int64_t n, double* out) {          \
    /* out: [count, sum, min, max, m2] */                                    \
    double inf = __builtin_inf(), qnan = __builtin_nan("");                  \
    double sum_l[BLOCK_STATS_LANES], mn_l[BLOCK_STATS_LANES],                \
        mx_l[BLOCK_STATS_LANES];                                             \
    int64_t cnt_l[BLOCK_STATS_LANES], nan_l[BLOCK_STATS_LANES];              \
    for (int j = 0; j < BLOCK_STATS_LANES; ++j) {                            \
      sum_l[j] = 0.0; mn_l[j] = inf; mx_l[j] = -inf;                         \
      cnt_l[j] = 0; nan_l[j] = 0;                                            \
    }                                                                        \
    int64_t main_n = n - (n % BLOCK_STATS_LANES);                            \
    for (int64_t i = 0; i < main_n; i += BLOCK_STATS_LANES) {                \
      for (int j = 0; j < BLOCK_STATS_LANES; ++j) {                          \
        int64_t live = (m == nullptr) || m[i + j];          \
        double x = (double)v[i + j];                                         \
        int64_t isnan_ = x != x;                                             \
        sum_l[j] += live ? x : 0.0;                                          \
        cnt_l[j] += live;                                                    \
        nan_l[j] += live & isnan_;                                           \
        double xo = (live && !isnan_) ? x : inf;                             \
        mn_l[j] = xo < mn_l[j] ? xo : mn_l[j];                               \
        double xh = (live && !isnan_) ? x : -inf;                            \
        mx_l[j] = xh > mx_l[j] ? xh : mx_l[j];                               \
      }                                                                      \
    }                                                                        \
    for (int64_t i = main_n; i < n; ++i) {                                   \
      int64_t live = (m == nullptr) || m[i];                                 \
      double x = (double)v[i];                                               \
      int64_t isnan_ = x != x;                                               \
      sum_l[0] += live ? x : 0.0;                                            \
      cnt_l[0] += live;                                                      \
      nan_l[0] += live & isnan_;                                             \
      double xo = (live && !isnan_) ? x : inf;                               \
      mn_l[0] = xo < mn_l[0] ? xo : mn_l[0];                                 \
      double xh = (live && !isnan_) ? x : -inf;                              \
      mx_l[0] = xh > mx_l[0] ? xh : mx_l[0];                                 \
    }                                                                        \
    double sum = 0.0, mn = inf, mx = -inf;                                   \
    int64_t count = 0, nans = 0;                                             \
    for (int j = 0; j < BLOCK_STATS_LANES; ++j) {                            \
      sum += sum_l[j];                                                       \
      count += cnt_l[j];                                                     \
      nans += nan_l[j];                                                      \
      mn = mn_l[j] < mn ? mn_l[j] : mn;                                      \
      mx = mx_l[j] > mx ? mx_l[j] : mx;                                      \
    }                                                                        \
    double m2 = 0.0;                                                         \
    if (count > 0) {                                                         \
      double mean = sum / (double)count;                                     \
      double m2_l[BLOCK_STATS_LANES];                                        \
      for (int j = 0; j < BLOCK_STATS_LANES; ++j) m2_l[j] = 0.0;             \
      for (int64_t i = 0; i < main_n; i += BLOCK_STATS_LANES) {              \
        for (int j = 0; j < BLOCK_STATS_LANES; ++j) {                        \
          int64_t live = (m == nullptr) || m[i + j];        \
          double d = live ? (double)v[i + j] - mean : 0.0;                   \
          m2_l[j] += d * d;                                                  \
        }                                                                    \
      }                                                                      \
      for (int64_t i = main_n; i < n; ++i) {                                 \
        int64_t live = (m == nullptr) || m[i];                               \
        double d = live ? (double)v[i] - mean : 0.0;                         \
        m2_l[0] += d * d;                                                    \
      }                                                                      \
      for (int j = 0; j < BLOCK_STATS_LANES; ++j) m2 += m2_l[j];             \
    }                                                                        \
    int64_t nonnan = count - nans;                                           \
    out[0] = (double)count;                                                  \
    out[1] = sum;                                                            \
    out[2] = nonnan > 0 ? mn : qnan;                                         \
    out[3] = nans > 0 ? qnan : (nonnan > 0 ? mx : qnan);                     \
    out[4] = m2;                                                             \
    out[5] = (double)nonnan;                                                 \
    out[6] = nonnan > 0 ? mx : qnan; /* NaN-excluded max (KLL g_max) */      \
  }

BLOCK_STATS_IMPL(block_stats_f64, double)
BLOCK_STATS_IMPL(block_stats_f32, float)
BLOCK_STATS_IMPL(block_stats_i64, int64_t)
BLOCK_STATS_IMPL(block_stats_i32, int32_t)

// Pearson co-moments for Correlation: out = [n, xsum, ysum, ck, xmk, ymk]
// (branchless multi-lane like BLOCK_STATS_IMPL)
void block_comoments_f64(const double* x, const double* y, const uint8_t* m,
                         int64_t n, double* out) {
  double xs_l[BLOCK_STATS_LANES] = {0}, ys_l[BLOCK_STATS_LANES] = {0};
  int64_t cnt_l[BLOCK_STATS_LANES] = {0};
  int64_t main_n = n - (n % BLOCK_STATS_LANES);
  for (int64_t i = 0; i < main_n; i += BLOCK_STATS_LANES) {
    for (int j = 0; j < BLOCK_STATS_LANES; ++j) {
      int64_t live = (m == nullptr) || m[i + j];
      xs_l[j] += live ? x[i + j] : 0.0;
      ys_l[j] += live ? y[i + j] : 0.0;
      cnt_l[j] += live;
    }
  }
  for (int64_t i = main_n; i < n; ++i) {
    int64_t live = (m == nullptr) || m[i];
    xs_l[0] += live ? x[i] : 0.0;
    ys_l[0] += live ? y[i] : 0.0;
    cnt_l[0] += live;
  }
  double xs = 0.0, ys = 0.0;
  int64_t count = 0;
  for (int j = 0; j < BLOCK_STATS_LANES; ++j) {
    xs += xs_l[j]; ys += ys_l[j]; count += cnt_l[j];
  }
  double ck = 0.0, xmk = 0.0, ymk = 0.0;
  if (count > 0) {
    double xa = xs / (double)count, ya = ys / (double)count;
    double ck_l[BLOCK_STATS_LANES] = {0}, xmk_l[BLOCK_STATS_LANES] = {0},
        ymk_l[BLOCK_STATS_LANES] = {0};
    for (int64_t i = 0; i < main_n; i += BLOCK_STATS_LANES) {
      for (int j = 0; j < BLOCK_STATS_LANES; ++j) {
        int64_t live = (m == nullptr) || m[i + j];
        double dx = live ? x[i + j] - xa : 0.0;
        double dy = live ? y[i + j] - ya : 0.0;
        ck_l[j] += dx * dy;
        xmk_l[j] += dx * dx;
        ymk_l[j] += dy * dy;
      }
    }
    for (int64_t i = main_n; i < n; ++i) {
      int64_t live = (m == nullptr) || m[i];
      double dx = live ? x[i] - xa : 0.0;
      double dy = live ? y[i] - ya : 0.0;
      ck_l[0] += dx * dy;
      xmk_l[0] += dx * dx;
      ymk_l[0] += dy * dy;
    }
    for (int j = 0; j < BLOCK_STATS_LANES; ++j) {
      ck += ck_l[j]; xmk += xmk_l[j]; ymk += ymk_l[j];
    }
  }
  out[0] = (double)count;
  out[1] = xs;
  out[2] = ys;
  out[3] = ck;
  out[4] = xmk;
  out[5] = ymk;
}

// HLL register update in place: regs[512] must be zero- or prior-initialized.
// Hashes are computed 8 rows at a time into a local block first (independent
// chains -> instruction-level parallelism); the register max-scatter stays
// scalar (data-dependent indices). Masked-out garbage hashes harmlessly and
// is discarded at scatter time.
#define BLOCK_HLL_IMPL(NAME, T, TOBITS)                                      \
  void NAME(const T* v, const uint8_t* m, int64_t n, uint64_t seed,          \
            uint8_t* regs) {                                                 \
    uint64_t h[8];                                                           \
    int64_t main_n = n - (n % 8);                                            \
    for (int64_t i = 0; i < main_n; i += 8) {                                \
      for (int j = 0; j < 8; ++j) h[j] = xxh64_fixed8(TOBITS(v[i + j]), seed); \
      for (int j = 0; j < 8; ++j) {                                          \
        if (m != nullptr && !m[i + j]) continue;                             \
        uint32_t idx = (uint32_t)(h[j] >> (64 - HLL_P));                     \
        uint64_t w = (h[j] << HLL_P) | (1ULL << (HLL_P - 1));                \
        uint8_t pw = (uint8_t)(__builtin_clzll(w) + 1);                      \
        if (pw > regs[idx]) regs[idx] = pw;                                  \
      }                                                                      \
    }                                                                        \
    for (int64_t i = main_n; i < n; ++i) {                                   \
      if (m != nullptr && !m[i]) continue;                                   \
      uint64_t hh = xxh64_fixed8(TOBITS(v[i]), seed);                        \
      uint32_t idx = (uint32_t)(hh >> (64 - HLL_P));                         \
      uint64_t w = (hh << HLL_P) | (1ULL << (HLL_P - 1));                    \
      uint8_t pw = (uint8_t)(__builtin_clzll(w) + 1);                        \
      if (pw > regs[idx]) regs[idx] = pw;                                    \
    }                                                                        \
  }

static inline uint64_t bits_of_double(double d) {
  double z = d == 0.0 ? 0.0 : d;  // collapse -0.0 (Spark semantics)
  uint64_t b;
  std::memcpy(&b, &z, 8);
  return b;
}
static inline uint64_t bits_of_i64(int64_t v) { return (uint64_t)v; }

BLOCK_HLL_IMPL(block_hll_f64, double, bits_of_double)
BLOCK_HLL_IMPL(block_hll_i64, int64_t, bits_of_i64)

void block_hll_strings(const uint8_t* data, const int64_t* offsets,
                       const uint8_t* valid, int64_t n, uint64_t seed,
                       uint8_t* regs) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    uint64_t h = xxh64(data + offsets[i], offsets[i + 1] - offsets[i], seed);
    uint32_t idx = (uint32_t)(h >> (64 - HLL_P));
    uint64_t w = (h << HLL_P) | (1ULL << (HLL_P - 1));
    uint8_t pw = (uint8_t)(__builtin_clzll(w) + 1);
    if (pw > regs[idx]) regs[idx] = pw;
  }
}

// KLL block pre-sample: take <= k valid values at stride 2^h (h minimal so
// the sample fits), sort them, report (m, h, min, max, count). Stride
// sampling over the unsorted block + per-call offset rotation is the
// classical KLL bottom-sampler (items enter level h with weight 2^h); the
// device-side kll_update uses sorted-stride order statistics instead —
// both satisfy the KLL rank-error bound, and a run uses exactly one path.
static int cmp_f64(const void* a, const void* b) {
  double x = *(const double*)a, y = *(const double*)b;
  return (x > y) - (x < y);
}

// KLL pick-only variant: the caller already knows the valid (non-NaN) value
// count from a shared block_stats pass over the same column+mask, so the
// counting pass is skipped — one less memory sweep per column per batch.
// Shared stride policy for the host samplers: pick up to TWO levels denser
// than the stride that fits k items, then (when two levels denser) compact
// the sorted sample once in-kernel — every 2nd item, parity from the batch
// randomness — emitting <= 2k items one level up. The emitted items carry
// the rank accuracy of the 4x-denser sample (compaction error is
// deterministic and tiny vs sampling variance), which a plain k-item pick
// lacks (~2x the rank error of the device path's sorted order statistics;
// validated by the host-tier rank-error tests). The <= 2k emission also
// preserves the state-buffer occupancy invariant: a level may hold up to k
// uncompacted residuals, and 2k + k <= the 4k buffer.
static inline void kll_stride_policy(int32_t k, int64_t nv, int64_t* out_h,
                                     int64_t* out_stride, int64_t* out_cap,
                                     int* out_dense) {
  int64_t h = 0;
  int64_t stride = 1;
  while (stride * (int64_t)k < nv) { stride <<= 1; ++h; }
  int dense = h >= 2 ? 2 : (int)h;
  h -= dense;
  stride >>= dense;
  *out_h = h;
  *out_stride = stride;
  *out_cap = (int64_t)k << dense;
  *out_dense = dense;
}

// In-place compaction of the sorted pick when it was two levels dense:
// emit every 2nd item (parity from r), halving the count and raising the
// weight one level. Returns the new item count; *h is incremented.
static inline int64_t kll_compact_pick(double* items, int64_t taken,
                                       int dense, uint32_t r, int64_t* h) {
  if (dense < 2 || taken <= 1) return taken;
  int64_t parity = (int64_t)((r >> 8) & 1u);
  int64_t m_out = (taken - parity + 1) / 2;
  for (int64_t j = 0; j < m_out; ++j) items[j] = items[parity + 2 * j];
  *h += 1;
  return m_out;
}

// The strided pick over the valid values, selection-identical to numpy's
// vv[offset::stride][:cap]. When every row is a valid non-NaN value
// (nv == n — the common case for clean numeric columns) the pick is a
// DIRECT gather of <= cap elements: O(cap) instead of a full O(n) row walk.
// The general path keeps a countdown to the next pick index instead of the
// old per-valid-row 64-bit modulo (~3x on masked columns).
static inline int64_t kll_strided_pick(const double* v, const uint8_t* m,
                                       int64_t n, int64_t nv, int64_t offset,
                                       int64_t stride, int64_t cap,
                                       double* items) {
  int64_t taken = 0;
  if (nv == n) {
    for (int64_t i = offset; i < n && taken < cap; i += stride) {
      items[taken++] = v[i];
    }
    return taken;
  }
  int64_t next = offset, seen = 0;
  for (int64_t i = 0; i < n && taken < cap; ++i) {
    if (m != nullptr && !m[i]) continue;
    double x = v[i];
    if (x != x) continue;
    if (seen == next) {
      items[taken++] = x;
      next += stride;
    }
    ++seen;
  }
  return taken;
}

void block_kll_pick_f64(const double* v, const uint8_t* m, int64_t n,
                        int32_t k, uint32_t tick, int64_t nv, double* items,
                        int64_t* out_meta) {
  if (k < 1) k = 1;  // a non-positive sketch size must not hang the loop
  int64_t h, stride, cap;
  int dense;
  kll_stride_policy(k, nv, &h, &stride, &cap, &dense);
  uint32_t r = ((tick * 2654435761u) ^ ((uint32_t)nv * 2246822519u)) >> 7;
  int64_t offset = (int64_t)(r % (uint32_t)stride);
  int64_t taken = kll_strided_pick(v, m, n, nv, offset, stride, cap, items);
  qsort(items, (size_t)taken, sizeof(double), cmp_f64);
  taken = kll_compact_pick(items, taken, dense, r, &h);
  out_meta[0] = taken;
  out_meta[1] = h;
}

// Integer-column variant: picks directly from the int64 buffer (values are
// converted to double per PICKED item), so callers skip the full-column
// f64 conversion copy the f64 kernel would require. Integers have no NaN,
// so `nv` is simply the masked-valid count; selection order is identical
// to converting first (int -> double is monotone), keeping the result
// bit-identical to the f64 path for |v| < 2^53.
void block_kll_pick_i64(const int64_t* v, const uint8_t* m, int64_t n,
                        int32_t k, uint32_t tick, int64_t nv, double* items,
                        int64_t* out_meta) {
  if (k < 1) k = 1;
  int64_t h, stride, cap;
  int dense;
  kll_stride_policy(k, nv, &h, &stride, &cap, &dense);
  uint32_t r = ((tick * 2654435761u) ^ ((uint32_t)nv * 2246822519u)) >> 7;
  int64_t offset = (int64_t)(r % (uint32_t)stride);
  int64_t taken = 0;
  if (nv == n) {
    for (int64_t i = offset; i < n && taken < cap; i += stride) {
      items[taken++] = (double)v[i];
    }
  } else {
    int64_t next = offset, seen = 0;
    for (int64_t i = 0; i < n && taken < cap; ++i) {
      if (m != nullptr && !m[i]) continue;
      if (seen == next) {
        items[taken++] = (double)v[i];
        next += stride;
      }
      ++seen;
    }
  }
  qsort(items, (size_t)taken, sizeof(double), cmp_f64);
  taken = kll_compact_pick(items, taken, dense, r, &h);
  out_meta[0] = taken;
  out_meta[1] = h;
}

void block_kll_sample_f64(const double* v, const uint8_t* m, int64_t n,
                          int32_t k, uint32_t tick, double* items,
                          int64_t* out_meta, double* out_minmax) {
  // pass 1: count valid (NaN excluded, like the device path) — branchless
  // multi-lane like BLOCK_STATS_IMPL so it auto-vectorizes
  double inf = __builtin_inf();
  double mn_l[BLOCK_STATS_LANES], mx_l[BLOCK_STATS_LANES];
  int64_t nv_l[BLOCK_STATS_LANES];
  for (int j = 0; j < BLOCK_STATS_LANES; ++j) {
    mn_l[j] = inf; mx_l[j] = -inf; nv_l[j] = 0;
  }
  int64_t main_n = n - (n % BLOCK_STATS_LANES);
  for (int64_t i = 0; i < main_n; i += BLOCK_STATS_LANES) {
    for (int j = 0; j < BLOCK_STATS_LANES; ++j) {
      int64_t live = (m == nullptr) || m[i + j];
      double x = v[i + j];
      int64_t ok = live & (x == x);
      nv_l[j] += ok;
      double xo = ok ? x : inf;
      mn_l[j] = xo < mn_l[j] ? xo : mn_l[j];
      double xh = ok ? x : -inf;
      mx_l[j] = xh > mx_l[j] ? xh : mx_l[j];
    }
  }
  for (int64_t i = main_n; i < n; ++i) {
    int64_t live = (m == nullptr) || m[i];
    double x = v[i];
    int64_t ok = live & (x == x);
    nv_l[0] += ok;
    double xo = ok ? x : inf;
    mn_l[0] = xo < mn_l[0] ? xo : mn_l[0];
    double xh = ok ? x : -inf;
    mx_l[0] = xh > mx_l[0] ? xh : mx_l[0];
  }
  int64_t nv = 0;
  double mn = inf, mx = -inf;
  for (int j = 0; j < BLOCK_STATS_LANES; ++j) {
    nv += nv_l[j];
    mn = mn_l[j] < mn ? mn_l[j] : mn;
    mx = mx_l[j] > mx ? mx_l[j] : mx;
  }
  if (nv == 0) { mn = 0.0; mx = 0.0; }
  if (k < 1) k = 1;  // a non-positive sketch size must not hang the loop
  int64_t h, stride, cap;
  int dense;
  kll_stride_policy(k, nv, &h, &stride, &cap, &dense);
  // offset mixes the batch index AND the valid-value count so a stream
  // whose structure is periodic in the batch size cannot stay phase-locked
  // with the sampler (must match _np_kll_sample in analyzers/sketches.py
  // bit-for-bit)
  uint32_t r = ((tick * 2654435761u) ^ ((uint32_t)nv * 2246822519u)) >> 7;
  int64_t offset = (int64_t)(r % (uint32_t)stride);
  int64_t taken = kll_strided_pick(v, m, n, nv, offset, stride, cap, items);
  qsort(items, (size_t)taken, sizeof(double), cmp_f64);
  taken = kll_compact_pick(items, taken, dense, r, &h);
  out_meta[0] = taken;  // m
  out_meta[1] = h;
  out_meta[2] = nv;     // exact valid count
  out_minmax[0] = mn;
  out_minmax[1] = mx;
}

// ---------------------------------------------------------------------------
// dict_masked_bincount — one pass over a dictionary column's codes shared by
// every per-batch consumer (type-class histogram, HLL present-entry fold,
// frequency counts): out[c] += 1 for each masked row, rows with mask=0 or
// code out of [0, num_cats) land in out[num_cats]. Replaces 3-4 numpy
// passes (where + fancy-index copy + bincount) per consumer per column.
// ---------------------------------------------------------------------------

void dict_masked_bincount(const int32_t* codes, const uint8_t* mask,
                          int64_t n, int64_t num_cats, int64_t* out) {
  for (int64_t i = 0; i <= num_cats; ++i) out[i] = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t c = codes[i];
    int64_t slot = (mask[i] && c >= 0 && c < num_cats) ? c : num_cats;
    ++out[slot];
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// u64_value_counts — exact (key -> summed weight) aggregation of hashed
// group keys: the host-side drain of the device frequency engine (buffer
// tail + table entries fold through this in one call). Keys are xxhash64
// outputs (uniformly distributed), so a radix partition on the TOP bits
// splits the input into runs whose open-addressing tables stay
// cache-resident — a straight 2x-sized global table thrashes LLC above a
// few million distinct keys (~100ns/probe); partitioned probing stays at
// memory-bandwidth speeds. All three phases (histogram, scatter, probe)
// parallelize over std::thread — the caller holds no GIL here.
// weights == nullptr means all-ones. Returns the number of distinct keys
// written to out_keys/out_weights (caller sizes both at n, the worst
// case). -1 on allocation failure.
// ---------------------------------------------------------------------------

#include <thread>
#include <vector>

namespace {

inline int64_t next_pow2_i64(int64_t v) {
  int64_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

// probe one partitioned run [lo, hi) into a zeroed table of tcap slots;
// the slot seed re-mixes the key (Fibonacci multiply) rather than taking
// raw key bits: engine keys are avalanched hashes, but low-entropy keys
// from any other caller (or adversarial preimages of the public
// splitmix64 mixer) would otherwise all seed one slot and turn linear
// probing O(distinct^2). pw == nullptr counts each key once (the
// all-ones fast path skips an entire 8-byte-per-key weight stream).
// Emits at out positions starting at `at`; returns entries emitted.
int64_t count_run(const uint64_t* pk, const int64_t* pw, int64_t lo,
                  int64_t hi, uint64_t* tk, int64_t* tw, int64_t tcap,
                  uint64_t* out_keys, int64_t* out_weights, int64_t at) {
  uint64_t tmsk = (uint64_t)(tcap - 1);
  std::memset(tw, 0, (size_t)tcap * 8);
  for (int64_t i = lo; i < hi; ++i) {
    uint64_t k = pk[i];
    int64_t w = pw != nullptr ? pw[i] : 1;
    uint64_t s = (k * 0x9E3779B97F4A7C15ULL >> 16) & tmsk;
    while (true) {
      if (tw[s] == 0) { tk[s] = k; tw[s] = w; break; }
      if (tk[s] == k) { tw[s] += w; break; }
      s = (s + 1) & tmsk;
    }
  }
  int64_t m = 0;
  for (int64_t s = 0; s < tcap; ++s) {
    if (tw[s] != 0) {
      out_keys[at + m] = tk[s];
      out_weights[at + m] = tw[s];
      ++m;
    }
  }
  return m;
}

}  // namespace

extern "C" {

int64_t u64_value_counts(const uint64_t* keys, const int64_t* weights,
                         int64_t n, uint64_t* out_keys, int64_t* out_weights) {
  if (n <= 0) return 0;
  // partition count keeping each partition's table ~L2-resident
  int64_t parts = 1;
  while (parts < (1 << 12) && n / parts > (1 << 14)) parts <<= 1;
  int shift = 64;
  for (int64_t p = parts; p > 1; p >>= 1) --shift;

  if (parts == 1) {
    int64_t cap = next_pow2_i64(2 * n);
    uint64_t* tk = (uint64_t*)std::malloc((size_t)cap * 8);
    int64_t* tw = (int64_t*)std::malloc((size_t)cap * 8);
    if (tk == nullptr || tw == nullptr) {
      std::free(tk); std::free(tw);
      return -1;
    }
    // identity layout: the inputs ARE the single run
    int64_t m = count_run(keys, weights, 0, n, tk, tw, cap,
                          out_keys, out_weights, 0);
    std::free(tk); std::free(tw);
    return m;
  }

  unsigned hw = std::thread::hardware_concurrency();
  int64_t T = hw == 0 ? 1 : (int64_t)(hw < 8 ? hw : 8);
  if (T > n / (1 << 16)) T = n / (1 << 16) > 0 ? n / (1 << 16) : 1;

  int64_t* hist = (int64_t*)std::calloc((size_t)(T * parts), 8);
  int64_t* counts = (int64_t*)std::calloc((size_t)parts + 1, 8);
  uint64_t* pk = (uint64_t*)std::malloc((size_t)n * 8);
  int64_t* pw =
      weights != nullptr ? (int64_t*)std::malloc((size_t)n * 8) : nullptr;
  if (hist == nullptr || counts == nullptr || pk == nullptr ||
      (weights != nullptr && pw == nullptr)) {
    std::free(hist); std::free(counts); std::free(pk); std::free(pw);
    return -1;
  }
  auto slice = [&](int64_t t) -> std::pair<int64_t, int64_t> {
    return {n * t / T, n * (t + 1) / T};
  };
  // phase 1: per-slice histograms
  {
    std::vector<std::thread> threads;
    for (int64_t t = 0; t < T; ++t) {
      threads.emplace_back([&, t] {
        auto [lo, hi] = slice(t);
        int64_t* h = hist + t * parts;
        for (int64_t i = lo; i < hi; ++i) ++h[keys[i] >> shift];
      });
    }
    for (auto& th : threads) th.join();
  }
  // exclusive prefix: counts[p] = start of partition p; per-(thread,
  // partition) cursors so slices scatter into disjoint ranges
  for (int64_t p = 0; p < parts; ++p) {
    int64_t total = 0;
    for (int64_t t = 0; t < T; ++t) {
      int64_t c = hist[t * parts + p];
      hist[t * parts + p] = total;  // becomes the thread's local offset
      total += c;
    }
    counts[p + 1] = counts[p] + total;
  }
  // phase 2: parallel scatter into partitioned order
  {
    std::vector<std::thread> threads;
    for (int64_t t = 0; t < T; ++t) {
      threads.emplace_back([&, t] {
        auto [lo, hi] = slice(t);
        int64_t* cur = hist + t * parts;
        if (weights != nullptr) {
          for (int64_t i = lo; i < hi; ++i) {
            int64_t p = (int64_t)(keys[i] >> shift);
            int64_t at = counts[p] + cur[p]++;
            pk[at] = keys[i];
            pw[at] = weights[i];
          }
        } else {
          for (int64_t i = lo; i < hi; ++i) {
            int64_t p = (int64_t)(keys[i] >> shift);
            pk[counts[p] + cur[p]++] = keys[i];
          }
        }
      });
    }
    for (auto& th : threads) th.join();
  }
  // phase 3: probe partitions in parallel (p % T == t assignment keeps the
  // load uniform — the hash spreads keys evenly), each thread with one
  // reusable table sized for the largest partition. Uniques land inside
  // each partition's own input range (distinct <= run length), recorded in
  // `emitted`, then compact single-threaded (<= 16 bytes per distinct).
  int64_t max_part = 0;
  for (int64_t p = 0; p < parts; ++p) {
    int64_t len = counts[p + 1] - counts[p];
    if (len > max_part) max_part = len;
  }
  int64_t cap = next_pow2_i64(2 * (max_part > 0 ? max_part : 1));
  int64_t* emitted = (int64_t*)std::calloc((size_t)parts, 8);
  bool failed = false;
  if (emitted == nullptr) failed = true;
  if (!failed) {
    std::vector<std::thread> threads;
    std::vector<int> alloc_failed((size_t)T, 0);
    for (int64_t t = 0; t < T; ++t) {
      threads.emplace_back([&, t] {
        uint64_t* tk = (uint64_t*)std::malloc((size_t)cap * 8);
        int64_t* tw = (int64_t*)std::malloc((size_t)cap * 8);
        if (tk == nullptr || tw == nullptr) {
          std::free(tk); std::free(tw);
          alloc_failed[(size_t)t] = 1;
          return;
        }
        for (int64_t p = t; p < parts; p += T) {
          int64_t lo = counts[p], hi = counts[p + 1];
          if (lo == hi) continue;
          int64_t tcap = next_pow2_i64(2 * (hi - lo));
          emitted[p] = count_run(pk, pw, lo, hi, tk, tw, tcap,
                                 out_keys, out_weights, lo);
        }
        std::free(tk); std::free(tw);
      });
    }
    for (auto& th : threads) th.join();
    for (int64_t t = 0; t < T; ++t) failed = failed || alloc_failed[(size_t)t];
  }
  int64_t m = -1;
  if (!failed) {
    m = 0;
    for (int64_t p = 0; p < parts; ++p) {
      int64_t lo = counts[p], e = emitted[p];
      if (e && lo != m) {
        std::memmove(out_keys + m, out_keys + lo, (size_t)e * 8);
        std::memmove(out_weights + m, out_weights + lo, (size_t)e * 8);
      }
      m += e;
    }
  }
  std::free(hist); std::free(counts); std::free(pk); std::free(pw);
  std::free(emitted);
  return m;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// pattern_match_batch — unanchored regex search per row over the Arrow
// string buffers, GIL-free, via the system PCRE2 library (dlopen'd so the
// build carries no header/link dependency). PCRE2 is Perl-compatible like
// Python `re` — the built-in Patterns use (?:...), (?!...), backreferences
// and \b, all with identical semantics — and PCRE2_UTF|PCRE2_UCP makes
// \d/\w Unicode-aware exactly like Python's default str patterns. A match
// only counts when non-empty (reference `regexp_extract(col, p, 0) != ""`,
// `analyzers/PatternMatch.scala:46-52`). Rows PCRE2 cannot judge (e.g.
// invalid UTF-8) get sentinel 2 so the caller can re-check them under
// Python `re`. Replaces the per-row Python loop flagged by VERDICT r4 #4.
// ---------------------------------------------------------------------------

#include <dlfcn.h>

namespace {

typedef void pcre2_code8;
typedef void pcre2_match_data8;

struct Pcre2Api {
  pcre2_code8* (*compile)(const uint8_t*, size_t, uint32_t, int*, size_t*, void*);
  int (*jit_compile)(pcre2_code8*, uint32_t);
  pcre2_match_data8* (*mdata_create)(const pcre2_code8*, void*);
  int (*match)(const pcre2_code8*, const uint8_t*, size_t, size_t, uint32_t,
               pcre2_match_data8*, void*);
  size_t* (*ovector)(pcre2_match_data8*);
  void (*code_free)(pcre2_code8*);
  void (*mdata_free)(pcre2_match_data8*);
  bool ok = false;
};

const uint32_t kPcre2Utf = 0x00080000u;
const uint32_t kPcre2Ucp = 0x00020000u;
const uint32_t kPcre2JitComplete = 0x00000001u;
const size_t kPcre2ZeroTerminated = ~(size_t)0;

const Pcre2Api& pcre2_api() {
  static Pcre2Api api = [] {
    Pcre2Api a;
    void* lib = dlopen("libpcre2-8.so.0", RTLD_NOW | RTLD_GLOBAL);
    if (lib == nullptr) lib = dlopen("libpcre2-8.so", RTLD_NOW | RTLD_GLOBAL);
    if (lib == nullptr) return a;
    a.compile = reinterpret_cast<decltype(a.compile)>(dlsym(lib, "pcre2_compile_8"));
    a.jit_compile = reinterpret_cast<decltype(a.jit_compile)>(
        dlsym(lib, "pcre2_jit_compile_8"));
    a.mdata_create = reinterpret_cast<decltype(a.mdata_create)>(
        dlsym(lib, "pcre2_match_data_create_from_pattern_8"));
    a.match = reinterpret_cast<decltype(a.match)>(dlsym(lib, "pcre2_match_8"));
    a.ovector = reinterpret_cast<decltype(a.ovector)>(
        dlsym(lib, "pcre2_get_ovector_pointer_8"));
    a.code_free = reinterpret_cast<decltype(a.code_free)>(dlsym(lib, "pcre2_code_free_8"));
    a.mdata_free = reinterpret_cast<decltype(a.mdata_free)>(
        dlsym(lib, "pcre2_match_data_free_8"));
    a.ok = a.compile && a.mdata_create && a.match && a.ovector && a.code_free &&
           a.mdata_free;
    return a;
  }();
  return api;
}

}  // namespace

extern "C" {

// returns 0 on success, -1 if the pattern failed to compile, -2 if PCRE2 is
// unavailable. out[i]: 1 = non-empty match, 0 = no match, 2 = row
// undecidable (caller re-checks under Python re).
int pattern_match_batch(const uint8_t* data, const int64_t* offsets,
                        const uint8_t* valid, int64_t n, const char* pattern,
                        uint8_t* out) {
  const Pcre2Api& api = pcre2_api();
  if (!api.ok) return -2;
  int err = 0;
  size_t err_off = 0;
  pcre2_code8* code = api.compile(reinterpret_cast<const uint8_t*>(pattern),
                                  kPcre2ZeroTerminated, kPcre2Utf | kPcre2Ucp,
                                  &err, &err_off, nullptr);
  if (code == nullptr) return -1;
  if (api.jit_compile != nullptr) {
    api.jit_compile(code, kPcre2JitComplete);  // best-effort; interp fallback
  }
  pcre2_match_data8* md = api.mdata_create(code, nullptr);
  if (md == nullptr) {
    api.code_free(code);
    return -1;
  }
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    const uint8_t* s = data + offsets[i];
    size_t len = (size_t)(offsets[i + 1] - offsets[i]);
    int rc = api.match(code, s, len, 0, 0, md, nullptr);
    if (rc >= 0) {
      size_t* ov = api.ovector(md);
      out[i] = ov[1] > ov[0] ? 1 : 0;  // empty first match counts as no match
    } else if (rc == -1 /* PCRE2_ERROR_NOMATCH */) {
      out[i] = 0;
    } else {
      out[i] = 2;  // bad UTF etc.: let the caller decide under Python re
    }
  }
  api.mdata_free(md);
  api.code_free(code);
  return 0;
}

}  // extern "C"
