// Native host kernels for the string-typed hot loops of the scan frontend.
//
// The reference's native tier is its set of Catalyst ImperativeAggregate /
// UDAF kernels doing per-row buffer updates inside Spark executors
// (reference `analyzers/catalyst/StatefulHyperloglogPlus.scala:89-115`,
// `StatefulDataType.scala:26-83`). Here the device tier is XLA; this C++
// tier covers the host-side per-value string work the device cannot do:
// xxHash64 batch hashing (HLL ingest), type classification (DataType
// analyzer) and UTF-8 length counting (Min/MaxLength), all operating on
// Arrow-layout buffers (concatenated UTF-8 bytes + offsets) in one pass.
//
// Build: python -m deequ_tpu.native.build  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// xxHash64 (public algorithm; must match deequ_tpu/ops/hashing.py and
// Spark's XxHash64Function bit-for-bit)
// ---------------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / aarch64)
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static uint64_t xxh64(const uint8_t* data, int64_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = rotl64(v1 + read64(p) * P2, 31) * P1; p += 8;
      v2 = rotl64(v2 + read64(p) * P2, 31) * P1; p += 8;
      v3 = rotl64(v3 + read64(p) * P2, 31) * P1; p += 8;
      v4 = rotl64(v4 + read64(p) * P2, 31) * P1; p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = (h ^ (rotl64(v1 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v2 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v3 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v4 * P2, 31) * P1)) * P1 + P4;
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h = rotl64(h ^ (rotl64(read64(p) * P2, 31) * P1), 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h = rotl64(h ^ ((uint64_t)read32(p) * P1), 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h = rotl64(h ^ ((uint64_t)(*p) * P5), 11) * P1;
    ++p;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// hash n strings given arrow large-string layout; null/invalid -> seed
void xxhash64_batch(const uint8_t* data, const int64_t* offsets,
                    const uint8_t* valid, int64_t n, uint64_t seed,
                    uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = seed;
      continue;
    }
    out[i] = xxh64(data + offsets[i], offsets[i + 1] - offsets[i], seed);
  }
}

// ---------------------------------------------------------------------------
// type classification (reference regexes,
// `analyzers/catalyst/StatefulDataType.scala:36-38`):
//   FRACTIONAL: ^(-|\+)? ?\d*\.\d*$
//   INTEGRAL:   ^(-|\+)? ?\d*$
//   BOOLEAN:    ^(true|false)$
// decision order: null -> fractional -> integral -> boolean -> string
// codes: 0=null/unknown 1=fractional 2=integral 3=boolean 4=string
// ---------------------------------------------------------------------------

static inline bool match_numericish(const uint8_t* s, int64_t len, bool* fractional) {
  int64_t i = 0;
  if (i < len && (s[i] == '-' || s[i] == '+')) ++i;
  if (i < len && s[i] == ' ') ++i;  // the reference regex admits one space
  int64_t digits_before = 0;
  while (i < len && s[i] >= '0' && s[i] <= '9') { ++i; ++digits_before; }
  if (i == len) {           // integral (digits may be empty, as in the regex)
    *fractional = false;
    return true;
  }
  if (s[i] != '.') return false;
  ++i;
  while (i < len && s[i] >= '0' && s[i] <= '9') ++i;
  if (i != len) return false;
  *fractional = true;       // digits on either side of '.' may be empty
  return true;
}

static inline bool match_boolean(const uint8_t* s, int64_t len) {
  return (len == 4 && std::memcmp(s, "true", 4) == 0) ||
         (len == 5 && std::memcmp(s, "false", 5) == 0);
}

void classify_types_batch(const uint8_t* data, const int64_t* offsets,
                          const uint8_t* valid, int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    const uint8_t* s = data + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    bool fractional = false;
    if (match_numericish(s, len, &fractional)) {
      out[i] = fractional ? 1 : 2;
    } else if (match_boolean(s, len)) {
      out[i] = 3;
    } else {
      out[i] = 4;
    }
  }
}

// ---------------------------------------------------------------------------
// UTF-8 codepoint lengths (matches python len(str)); null -> 0
// ---------------------------------------------------------------------------

void string_lengths_batch(const uint8_t* data, const int64_t* offsets,
                          const uint8_t* valid, int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) {
      out[i] = 0;
      continue;
    }
    const uint8_t* s = data + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int32_t count = 0;
    for (int64_t j = 0; j < len; ++j) {
      if ((s[j] & 0xC0) != 0x80) ++count;  // count non-continuation bytes
    }
    out[i] = count;
  }
}

}  // extern "C"
