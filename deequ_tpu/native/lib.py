"""ctypes bindings for the native host kernels.

Object arrays of python strings are converted ONCE per batch to Arrow
large-string layout (concatenated UTF-8 buffer + int64 offsets — a C-speed
conversion via pyarrow), then each kernel runs a single C++ pass over the
buffers. Falls back to pure Python upstream if anything here fails to load.
"""

from __future__ import annotations

import ctypes

import numpy as np
import pyarrow as pa

from ..utils import env_flag
from .build import build

#: env var: set to 1 to disable the native kernels (pure-Python fallback)
NO_NATIVE_ENV = "DEEQU_TPU_NO_NATIVE"

if env_flag(NO_NATIVE_ENV, False):
    raise ImportError("native kernels disabled via DEEQU_TPU_NO_NATIVE")

_lib = ctypes.CDLL(build())

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i32p = ctypes.POINTER(ctypes.c_int32)

_u16p = ctypes.POINTER(ctypes.c_uint16)
_f64p = ctypes.POINTER(ctypes.c_double)

_lib.xxhash64_batch.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u64p]
_lib.classify_types_batch.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, _i32p]
_lib.string_lengths_batch.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, _i32p]
_lib.hll_pack_f64.argtypes = [_f64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u16p]
_lib.hll_pack_i64.argtypes = [_i64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u16p]
_lib.hll_pack_strings.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u16p]
_f32p = ctypes.POINTER(ctypes.c_float)
for _name, _vp in (
    ("block_stats_f64", _f64p), ("block_stats_f32", _f32p),
    ("block_stats_i64", _i64p), ("block_stats_i32", _i32p),
):
    getattr(_lib, _name).argtypes = [_vp, _u8p, ctypes.c_int64, _f64p]
_lib.block_comoments_f64.argtypes = [_f64p, _f64p, _u8p, ctypes.c_int64, _f64p]
_lib.block_hll_f64.argtypes = [_f64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u8p]
_lib.block_hll_i64.argtypes = [_i64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u8p]
_lib.block_hll_strings.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u8p]
_lib.block_kll_sample_f64.argtypes = [
    _f64p, _u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint32,
    _f64p, _i64p, _f64p,
]
_lib.dict_masked_bincount.argtypes = [
    _i32p, _u8p, ctypes.c_int64, ctypes.c_int64, _i64p,
]
_lib.block_kll_pick_f64.argtypes = [
    _f64p, _u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint32,
    ctypes.c_int64, _f64p, _i64p,
]
_lib.block_kll_pick_i64.argtypes = [
    _i64p, _u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint32,
    ctypes.c_int64, _f64p, _i64p,
]
_lib.pattern_match_batch.argtypes = [
    _u8p, _i64p, _u8p, ctypes.c_int64, ctypes.c_char_p, _u8p,
]
_lib.pattern_match_batch.restype = ctypes.c_int
_lib.u64_value_counts.argtypes = [
    _u64p, _i64p, ctypes.c_int64, _u64p, _i64p,
]
_lib.u64_value_counts.restype = ctypes.c_int64


def _arrow_layout(values):
    """(data u8[:], offsets i64[n+1], valid u8[n]) from an object array of
    str/None OR directly from a pyarrow string array (no per-value python
    object materialization — the fast path for lazy string columns)."""
    if isinstance(values, pa.Array):
        arr = values
        if not pa.types.is_large_string(arr.type):
            arr = arr.cast(pa.large_string())  # widens offsets only
    else:
        arr = pa.array(values, type=pa.large_string(), from_pandas=True)
    buffers = arr.buffers()  # [validity, offsets, data]
    n = len(arr)
    offsets = np.frombuffer(buffers[1], dtype=np.int64, count=n + 1 + arr.offset)
    if arr.offset:
        offsets = offsets[arr.offset:]
    data_buf = buffers[2]
    data = (
        np.frombuffer(data_buf, dtype=np.uint8)
        if data_buf is not None and len(data_buf) > 0
        else np.zeros(1, dtype=np.uint8)
    )
    if arr.null_count:
        valid = np.asarray(arr.is_valid()).astype(np.uint8)
    else:
        valid = np.ones(n, dtype=np.uint8)
    return data, np.ascontiguousarray(offsets), valid


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctype)


def native_xxhash64_strings(values: np.ndarray, seed: int) -> np.ndarray:
    data, offsets, valid = _arrow_layout(values)
    n = len(values)
    out = np.empty(n, dtype=np.uint64)
    _lib.xxhash64_batch(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p),
        n, ctypes.c_uint64(seed), _ptr(out, _u64p),
    )
    return out


def native_classify_types(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    data, offsets, valid = _arrow_layout(values)
    valid = valid & np.asarray(mask, dtype=np.uint8)
    n = len(values)
    out = np.empty(n, dtype=np.int32)
    _lib.classify_types_batch(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p), n, _ptr(out, _i32p)
    )
    return out


def native_string_lengths(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    data, offsets, valid = _arrow_layout(values)
    valid = valid & np.asarray(mask, dtype=np.uint8)
    n = len(values)
    out = np.empty(n, dtype=np.int32)
    _lib.string_lengths_batch(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p), n, _ptr(out, _i32p)
    )
    return out


def native_hll_pack_numeric(values: np.ndarray, mask: np.ndarray, seed: int) -> np.ndarray:
    """uint16 (idx<<6)|pw HLL feature per row from a numeric array; nulls -> 0.
    Doubles hash as IEEE754 bits (-0.0 normalized), integrals/booleans as
    int64 — matching Spark's per-type layout (see ops/hashing.hash_column)."""
    n = len(values)
    out = np.empty(n, dtype=np.uint16)
    valid = None if mask is None else np.ascontiguousarray(mask, dtype=np.uint8)
    vp = _ptr(valid, _u8p) if valid is not None else None
    if np.issubdtype(values.dtype, np.floating):
        vals = np.ascontiguousarray(values, dtype=np.float64)
        _lib.hll_pack_f64(_ptr(vals, _f64p), vp, n, ctypes.c_uint64(seed), _ptr(out, _u16p))
    else:
        vals = np.ascontiguousarray(values, dtype=np.int64)
        _lib.hll_pack_i64(_ptr(vals, _i64p), vp, n, ctypes.c_uint64(seed), _ptr(out, _u16p))
    return out


def native_hll_pack_strings(values: np.ndarray, mask: np.ndarray, seed: int) -> np.ndarray:
    data, offsets, valid = _arrow_layout(values)
    if mask is not None:
        valid = valid & np.asarray(mask, dtype=np.uint8)
    n = len(values)
    out = np.empty(n, dtype=np.uint16)
    _lib.hll_pack_strings(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p),
        n, ctypes.c_uint64(seed), _ptr(out, _u16p),
    )
    return out


# -- block-partial reduction kernels (ingest tier) ---------------------------

_BLOCK_STATS = {
    np.dtype(np.float64): ("block_stats_f64", _f64p),
    np.dtype(np.float32): ("block_stats_f32", _f32p),
    np.dtype(np.int64): ("block_stats_i64", _i64p),
    np.dtype(np.int32): ("block_stats_i32", _i32p),
}


def _mask_u8(mask):
    if mask is None:
        return None, None
    m = np.ascontiguousarray(mask, dtype=np.uint8)
    return m, _ptr(m, _u8p)


def native_block_stats(values: np.ndarray, mask) -> np.ndarray:
    """One C pass -> [count, sum, min, max, m2, nonnan, max_nonnan] over the
    masked block (min/max follow the NaN-largest order; slots 5-6 let the
    KLL sampler skip its counting pass)."""
    entry = _BLOCK_STATS.get(values.dtype)
    if entry is None:
        values = np.ascontiguousarray(values, dtype=np.float64)
        entry = _BLOCK_STATS[values.dtype]
    else:
        values = np.ascontiguousarray(values)
    name, vp = entry
    out = np.empty(7, dtype=np.float64)
    _m, mp = _mask_u8(mask)
    getattr(_lib, name)(_ptr(values, vp), mp, len(values), _ptr(out, _f64p))
    return out


def native_block_kll_pick(values: np.ndarray, mask, k: int, tick: int, nv: int):
    """(items f64[k] sorted asc with +inf padding, m, h) — the pick-only KLL
    sampler for callers that already know the non-NaN valid count ``nv``
    from a shared block_stats pass (one less memory sweep). int64 columns
    dispatch to the i64 kernel, which converts per PICKED item instead of
    paying a full-column f64 conversion copy."""
    k = max(int(k), 1)  # keep the buffer in step with the kernel's k clamp
    # 4k wide: the kernel's stride policy picks up to two levels denser
    items = np.full(4 * k, np.inf, dtype=np.float64)
    meta = np.zeros(2, dtype=np.int64)
    _m, mp = _mask_u8(mask)
    if values.dtype == np.int64 and values.flags.c_contiguous:
        _lib.block_kll_pick_i64(
            _ptr(values, _i64p), mp, len(values), ctypes.c_int32(k),
            ctypes.c_uint32(tick & 0xFFFFFFFF), ctypes.c_int64(nv),
            _ptr(items, _f64p), _ptr(meta, _i64p),
        )
    else:
        vals = np.ascontiguousarray(values, dtype=np.float64)
        _lib.block_kll_pick_f64(
            _ptr(vals, _f64p), mp, len(vals), ctypes.c_int32(k),
            ctypes.c_uint32(tick & 0xFFFFFFFF), ctypes.c_int64(nv),
            _ptr(items, _f64p), _ptr(meta, _i64p),
        )
    m = int(meta[0])
    items[m:] = np.inf
    return items, m, int(meta[1])


def native_block_comoments(x: np.ndarray, y: np.ndarray, mask) -> np.ndarray:
    """[n, xsum, ysum, ck, xmk, ymk] co-moments over the jointly-masked block."""
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    out = np.empty(6, dtype=np.float64)
    _m, mp = _mask_u8(mask)
    _lib.block_comoments_f64(_ptr(x, _f64p), _ptr(y, _f64p), mp, len(x), _ptr(out, _f64p))
    return out


def native_block_hll(values: np.ndarray, mask, seed: int,
                     regs: np.ndarray | None = None) -> np.ndarray:
    """Update (or create) a uint8[512] HLL register block from numeric values."""
    if regs is None:
        regs = np.zeros(512, dtype=np.uint8)
    _m, mp = _mask_u8(mask)
    if np.issubdtype(values.dtype, np.floating):
        vals = np.ascontiguousarray(values, dtype=np.float64)
        _lib.block_hll_f64(_ptr(vals, _f64p), mp, len(vals), ctypes.c_uint64(seed), _ptr(regs, _u8p))
    else:
        vals = np.ascontiguousarray(values, dtype=np.int64)
        _lib.block_hll_i64(_ptr(vals, _i64p), mp, len(vals), ctypes.c_uint64(seed), _ptr(regs, _u8p))
    return regs


def native_block_hll_strings(values: np.ndarray, mask, seed: int,
                             regs: np.ndarray | None = None) -> np.ndarray:
    if regs is None:
        regs = np.zeros(512, dtype=np.uint8)
    data, offsets, valid = _arrow_layout(values)
    if mask is not None:
        valid = valid & np.asarray(mask, dtype=np.uint8)
    _lib.block_hll_strings(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p),
        len(values), ctypes.c_uint64(seed), _ptr(regs, _u8p),
    )
    return regs


def native_block_kll_sample(values: np.ndarray, mask, k: int, tick: int):
    """(items f64[4k] sorted asc with +inf padding beyond m, m, h, nv,
    min, max) — m <= 2k after the in-kernel dense-pick compaction."""
    k = max(int(k), 1)  # keep the buffer in step with the kernel's k clamp
    vals = np.ascontiguousarray(values, dtype=np.float64)
    # 4k wide: the kernel's stride policy picks up to two levels denser
    items = np.full(4 * k, np.inf, dtype=np.float64)
    meta = np.zeros(3, dtype=np.int64)
    minmax = np.zeros(2, dtype=np.float64)
    _m, mp = _mask_u8(mask)
    _lib.block_kll_sample_f64(
        _ptr(vals, _f64p), mp, len(vals), ctypes.c_int32(k),
        ctypes.c_uint32(tick & 0xFFFFFFFF),
        _ptr(items, _f64p), _ptr(meta, _i64p), _ptr(minmax, _f64p),
    )
    m, h, nv = int(meta[0]), int(meta[1]), int(meta[2])
    items[m:] = np.inf
    if nv == 0:
        # identity element: no items, min/max at the fold identities
        return items, 0, 0, 0, np.inf, -np.inf
    return items, m, h, nv, float(minmax[0]), float(minmax[1])


def native_pattern_match(values, mask, pattern: str):
    """bool[n] unanchored non-empty regex match per row, computed GIL-free
    by PCRE2 over the Arrow string buffers. Returns None when PCRE2 is
    unavailable or refuses the pattern (caller falls back to Python `re`).
    Rows PCRE2 cannot judge (sentinel 2, e.g. invalid UTF-8) are re-checked
    under Python `re` so the result is always `re`-exact."""
    data, offsets, valid = _arrow_layout(values)
    if mask is not None:
        valid = valid & np.asarray(mask, dtype=np.uint8)
    n = len(valid)
    out = np.zeros(n, dtype=np.uint8)
    rc = _lib.pattern_match_batch(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p),
        ctypes.c_int64(n), pattern.encode("utf-8"), _ptr(out, _u8p),
    )
    if rc != 0:
        return None
    result = out == 1
    undecided = np.flatnonzero(out == 2)
    if undecided.size:
        import re as _re

        compiled = _re.compile(pattern)
        for i in undecided:
            s = int(offsets[i])
            e = int(offsets[i + 1])
            try:
                text = bytes(data[s:e]).decode("utf-8", errors="surrogateescape")
            except Exception:  # noqa: BLE001
                result[i] = False
                continue
            m = compiled.search(text)
            result[i] = bool(m) and m.group(0) != ""
    return result


def native_u64_value_counts(keys: np.ndarray, weights=None):
    """(unique_keys u64[m], summed_weights i64[m]) over hashed group keys —
    the cache-partitioned C aggregation the device frequency engine's host
    drain uses (25M keys fold in a few hundred ms where np.unique pays a
    full 2s sort). ``weights=None`` counts each key once; explicit weights
    must be POSITIVE (zero weights are treated as absent — the empty-slot
    marker of the open tables). Returns None on allocation failure (caller
    falls back to the numpy sort path)."""
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    n = len(keys)
    out_keys = np.empty(n, dtype=np.uint64)
    out_weights = np.empty(n, dtype=np.int64)
    wp = None
    if weights is not None:
        weights = np.ascontiguousarray(weights, dtype=np.int64)
        wp = _ptr(weights, _i64p)
    m = _lib.u64_value_counts(
        _ptr(keys, _u64p), wp, ctypes.c_int64(n),
        _ptr(out_keys, _u64p), _ptr(out_weights, _i64p),
    )
    if m < 0:
        return None
    return out_keys[:m].copy(), out_weights[:m].copy()


def native_dict_masked_bincount(
    codes: np.ndarray, mask, num_cats: int
) -> np.ndarray:
    """int64[num_cats + 1] counts of each dictionary code among masked rows;
    masked-out or out-of-range rows land in the final sentinel slot. ONE
    memory pass shared by every per-batch dictionary consumer (type-class
    histogram, HLL present-entry fold, frequency counts)."""
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    out = np.zeros(int(num_cats) + 1, dtype=np.int64)
    _m, mp = _mask_u8(mask)
    _lib.dict_masked_bincount(
        _ptr(codes, _i32p), mp, len(codes), ctypes.c_int64(int(num_cats)),
        _ptr(out, _i64p),
    )
    return out
