"""ctypes bindings for the native host kernels.

Object arrays of python strings are converted ONCE per batch to Arrow
large-string layout (concatenated UTF-8 buffer + int64 offsets — a C-speed
conversion via pyarrow), then each kernel runs a single C++ pass over the
buffers. Falls back to pure Python upstream if anything here fails to load.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np
import pyarrow as pa

from .build import build

if os.environ.get("DEEQU_TPU_NO_NATIVE"):
    raise ImportError("native kernels disabled via DEEQU_TPU_NO_NATIVE")

_lib = ctypes.CDLL(build())

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i32p = ctypes.POINTER(ctypes.c_int32)

_lib.xxhash64_batch.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, ctypes.c_uint64, _u64p]
_lib.classify_types_batch.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, _i32p]
_lib.string_lengths_batch.argtypes = [_u8p, _i64p, _u8p, ctypes.c_int64, _i32p]


def _arrow_layout(values: np.ndarray):
    """(data u8[:], offsets i64[n+1], valid u8[n]) from an object array of
    str/None."""
    arr = pa.array(values, type=pa.large_string(), from_pandas=True)
    buffers = arr.buffers()  # [validity, offsets, data]
    n = len(arr)
    offsets = np.frombuffer(buffers[1], dtype=np.int64, count=n + 1 + arr.offset)
    if arr.offset:
        offsets = offsets[arr.offset:]
    data_buf = buffers[2]
    data = (
        np.frombuffer(data_buf, dtype=np.uint8)
        if data_buf is not None and len(data_buf) > 0
        else np.zeros(1, dtype=np.uint8)
    )
    if arr.null_count:
        valid = np.asarray(arr.is_valid()).astype(np.uint8)
    else:
        valid = np.ones(n, dtype=np.uint8)
    return data, np.ascontiguousarray(offsets), valid


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctype)


def native_xxhash64_strings(values: np.ndarray, seed: int) -> np.ndarray:
    data, offsets, valid = _arrow_layout(values)
    n = len(values)
    out = np.empty(n, dtype=np.uint64)
    _lib.xxhash64_batch(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p),
        n, ctypes.c_uint64(seed), _ptr(out, _u64p),
    )
    return out


def native_classify_types(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    data, offsets, valid = _arrow_layout(values)
    valid = valid & np.asarray(mask, dtype=np.uint8)
    n = len(values)
    out = np.empty(n, dtype=np.int32)
    _lib.classify_types_batch(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p), n, _ptr(out, _i32p)
    )
    return out


def native_string_lengths(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    data, offsets, valid = _arrow_layout(values)
    valid = valid & np.asarray(mask, dtype=np.uint8)
    n = len(values)
    out = np.empty(n, dtype=np.int32)
    _lib.string_lengths_batch(
        _ptr(data, _u8p), _ptr(offsets, _i64p), _ptr(valid, _u8p), n, _ptr(out, _i32p)
    )
    return out
