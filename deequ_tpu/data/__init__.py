"""Tabular data frontend.

The reference operates on Spark DataFrames; here a :class:`Dataset` wraps
columnar data (pyarrow Table / Parquet files / pandas / dict-of-arrays) and
yields fixed-size :class:`Batch` objects: per-column numpy value arrays plus
validity masks. Numeric values are materialized as float64 with NaN at nulls
so the device program only ever sees fixed-shape numeric arrays; strings stay
host-side (object arrays) and are turned into numeric *features* (lengths,
regex masks, hashes, type classes) by the feature frontend
(`deequ_tpu/runners/features.py`).

Replaces: Spark `DataFrame` + Row null checks (deequ uses `isNotNull` /
`conditionalSelection`, reference `analyzers/Analyzer.scala:409-432`).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence, Union

import numpy as np

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
except ImportError:  # pragma: no cover - pyarrow is in the base image
    pa = None
    pq = None


class ColumnKind(enum.Enum):
    INTEGRAL = "Integral"
    FRACTIONAL = "Fractional"
    BOOLEAN = "Boolean"
    STRING = "String"
    TIMESTAMP = "Timestamp"
    UNKNOWN = "Unknown"

    @property
    def is_numeric(self) -> bool:
        return self in (ColumnKind.INTEGRAL, ColumnKind.FRACTIONAL)


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    kind: ColumnKind
    nullable: bool = True


@dataclass(frozen=True)
class Schema:
    columns: Sequence[ColumnSchema]

    def __post_init__(self) -> None:
        object.__setattr__(self, "_by_name", {c.name: c for c in self.columns})

    def __contains__(self, name: str) -> bool:
        return name in self._by_name  # type: ignore[attr-defined]

    def __getitem__(self, name: str) -> ColumnSchema:
        return self._by_name[name]  # type: ignore[attr-defined]

    @property
    def names(self) -> List[str]:
        return [c.name for c in self.columns]


def _kind_of_arrow(t: "pa.DataType") -> ColumnKind:
    if pa.types.is_boolean(t):
        return ColumnKind.BOOLEAN
    if pa.types.is_integer(t):
        return ColumnKind.INTEGRAL
    if pa.types.is_floating(t) or pa.types.is_decimal(t):
        return ColumnKind.FRACTIONAL
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return ColumnKind.STRING
    if pa.types.is_temporal(t):
        return ColumnKind.TIMESTAMP
    if pa.types.is_dictionary(t):
        # a dictionary-encoded column behaves as its value type; the codes
        # additionally feed the device frequency path (analyzers/grouping.py)
        return _kind_of_arrow(t.value_type)
    return ColumnKind.UNKNOWN


def _kind_of_numpy(arr: np.ndarray) -> ColumnKind:
    if arr.dtype == np.bool_:
        return ColumnKind.BOOLEAN
    if np.issubdtype(arr.dtype, np.integer):
        return ColumnKind.INTEGRAL
    if np.issubdtype(arr.dtype, np.floating):
        return ColumnKind.FRACTIONAL
    if np.issubdtype(arr.dtype, np.datetime64):
        return ColumnKind.TIMESTAMP
    return ColumnKind.STRING


class Column:
    """One column slice: raw values + validity mask (True = present).

    Dictionary-encoded sources additionally carry ``codes`` (int32 indices
    into the table-wide unified ``dictionary``; nulls and padding are coded
    ``len(dictionary)``) so frequency counting can ride the device scan
    (scatter-free, see ``DeviceFrequencyScan``) instead of a host group-by.

    String columns keep the Arrow array in ``arrow`` and materialize the
    python-object ``values`` LAZILY: the native kernels (hash, classify,
    lengths, HLL) read the Arrow buffers directly, so a scan that never
    touches ``values`` never pays per-value object creation (~1.5us/value —
    it dominated wide-table profiles)."""

    __slots__ = (
        "name", "kind", "_values", "mask", "codes", "_dictionary",
        "_dictionary_arrow", "arrow", "aux"
    )

    def __init__(
        self,
        name: str,
        kind: ColumnKind,
        values: "Optional[np.ndarray]",
        mask: np.ndarray,
        codes: "Optional[np.ndarray]" = None,
        dictionary: "Optional[np.ndarray]" = None,
        dictionary_arrow: "Optional[pa.Array]" = None,
        arrow: "Optional[pa.Array]" = None,
        aux: "Optional[dict]" = None,
    ):
        self.name = name
        self.kind = kind
        self._values = values
        self.mask = mask
        self.codes = codes
        self._dictionary = dictionary
        self._dictionary_arrow = dictionary_arrow
        self.arrow = arrow
        #: per-dataset-column cache for dictionary-derived artifacts (type
        #: codes, lengths, hashes of the DISTINCT values) — shared across
        #: batches so each dictionary is processed once per run, not once
        #: per batch per consumer
        self.aux = aux if aux is not None else {}

    @property
    def has_dictionary(self) -> bool:
        """Dictionary-encoded? Answered WITHOUT decoding (``.dictionary``
        decodes a large string dictionary to python objects on first touch
        — ~1s for a TPC-H comment column — so presence checks must not)."""
        return self._dictionary is not None or self._dictionary_arrow is not None

    @property
    def num_categories(self) -> "Optional[int]":
        if self._dictionary is not None:
            return len(self._dictionary)
        if self._dictionary_arrow is not None:
            return len(self._dictionary_arrow)
        return None

    @property
    def dictionary_source(self):
        """The dictionary payload for the native string kernels: the ARROW
        array when available (buffer-direct, no object materialization).
        Non-string dictionaries return the decoded numpy array — their
        consumers (`hash_column`'s numeric paths) need real dtypes, and a
        numeric decode is a cheap buffer view, not an object explosion."""
        if self._dictionary_arrow is not None and self.kind == ColumnKind.STRING:
            return self._dictionary_arrow
        return self.dictionary

    @property
    def dictionary(self) -> "Optional[np.ndarray]":
        """Decoded dictionary values; decodes LAZILY from the arrow payload
        (cached in ``aux['values']`` across batches). Consumers that only
        need presence/length/native-kernel input use ``has_dictionary`` /
        ``num_categories`` / ``dictionary_source`` instead."""
        if self._dictionary is None and self._dictionary_arrow is not None:
            vals = self.aux.get("values")
            if vals is None or len(vals) != len(self._dictionary_arrow):
                vals = _decode_dictionary(self._dictionary_arrow, self.kind)
                self.aux["values"] = vals
            self._dictionary = vals
        return self._dictionary

    @property
    def values(self) -> np.ndarray:
        if self._values is None:
            if self.has_dictionary and self.codes is not None:
                # lazy decode: most consumers read codes/dictionary or the
                # aux caches; a 10M-row object gather only happens if some
                # python-level consumer genuinely needs per-row values
                num_cats = self.num_categories
                safe = np.where(self.codes < num_cats, self.codes, 0)
                if num_cats:
                    self._values = self.dictionary[safe]
                else:
                    self._values = np.empty(len(self.codes), dtype=object)
            else:
                vals = self.arrow.to_numpy(zero_copy_only=False)
                if vals.dtype != object:
                    vals = vals.astype(object)
                self._values = vals
        return self._values

    @values.setter
    def values(self, vals: np.ndarray) -> None:
        self._values = vals

    @property
    def string_source(self):
        """What the native string kernels should read: the Arrow array when
        available (buffer-direct, no object materialization), else values."""
        return self.arrow if self.arrow is not None else self.values

    def numeric_f64(self) -> np.ndarray:
        """float64 view with NaN at nulls — the device-facing representation."""
        if self.kind == ColumnKind.BOOLEAN:
            out = np.where(self.mask, self.values.astype(np.float64), np.nan)
            return out
        if np.issubdtype(self.values.dtype, np.floating):
            out = self.values.astype(np.float64, copy=True)
            out[~self.mask] = np.nan
            return out
        if np.issubdtype(self.values.dtype, np.number):
            out = self.values.astype(np.float64)
            if not self.mask.all():
                out = np.where(self.mask, out, np.nan)
            return out
        # strings that look numeric: attempt parse (used by the profiler's
        # cast pass, reference `profiles/ColumnProfiler.scala:346-354`)
        out = np.full(len(self.values), np.nan, dtype=np.float64)
        for i in np.flatnonzero(self.mask):
            try:
                out[i] = float(self.values[i])
            except (TypeError, ValueError):
                pass
        return out


class Batch:
    """A fixed-size horizontal slice of the dataset.

    ``row_mask`` marks genuine rows (False rows are padding added to keep
    shapes static across the run, so one XLA program serves every batch).
    """

    def __init__(self, columns: Dict[str, Column], row_mask: np.ndarray, num_rows: int):
        self.columns = columns
        self.row_mask = row_mask
        self.num_rows = num_rows  # valid rows

    def __len__(self) -> int:
        return self.num_rows

    def column(self, name: str) -> Column:
        return self.columns[name]

    def to_pandas(self):
        """View for host-side predicate evaluation (Compliance / where)."""
        import pandas as pd

        data = {}
        for name, col in self.columns.items():
            if col.kind.is_numeric or col.kind == ColumnKind.BOOLEAN:
                data[name] = col.numeric_f64()
            else:
                vals = col.values.astype(object, copy=True)
                vals[~col.mask] = None
                data[name] = vals
        return pd.DataFrame(data)


ArrayLike = Union[np.ndarray, list]


class Dataset:
    """Columnar dataset with batch iteration.

    Sources: dict of arrays (`from_dict`), pandas (`from_pandas`),
    pyarrow Table (`from_arrow`), Parquet files (`from_parquet`).
    """

    def __init__(self, table: "pa.Table", *, probe_encoding: bool = True):
        # derived views (select / casts / the profiler's pass-2 tables) pass
        # probe_encoding=False: their parent table already ran the 64k-row
        # cardinality probes and its verdict stands — re-probing every
        # derived construction costs three count_distinct passes per plain
        # string column for no new information
        if probe_encoding:
            table = _maybe_dictionary_encode(table)
        if any(pa.types.is_dictionary(f.type) for f in table.schema):
            # one table-wide dictionary per column: batch slices then share
            # a stable code space, the contract of the device frequency path
            table = table.unify_dictionaries()
        self._table = table
        self._schema = Schema(
            [ColumnSchema(f.name, _kind_of_arrow(f.type), f.nullable) for f in table.schema]
        )
        #: decoded dictionaries + derived-artifact caches, one per column,
        #: shared by every batch this dataset yields
        self._dict_aux: Dict[str, dict] = {}
        #: memoized derived VIEWS of this dataset (e.g. the profiler's
        #: casted/encoded pass-2 table), so repeated runs reuse one arrow
        #: table identity — which also keeps the engine's device feature
        #: cache hot across runs
        self.derived_cache: Dict[Any, "Dataset"] = {}

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_arrow(table: "pa.Table") -> "Dataset":
        return Dataset(table)

    @staticmethod
    def from_parquet(path: Union[str, Sequence[str]]) -> "Dataset":
        """Read Parquet from a local path or any supported URI scheme
        (``s3://``, ``gs://``, ``hdfs://``, ``memory://``, ...) — the
        reference reads through Hadoop `FileSystem` the same way
        (`io/DfsUtils.scala:24-85`)."""
        from .. import io as dio

        return Dataset(dio.read_parquet_table(path))

    @staticmethod
    def from_pandas(df) -> "Dataset":
        return Dataset(pa.Table.from_pandas(df, preserve_index=False))

    @staticmethod
    def from_dict(data: Mapping[str, ArrayLike]) -> "Dataset":
        arrays = {}
        for name, vals in data.items():
            arrays[name] = pa.array(vals)
        return Dataset(pa.table(arrays))

    # -- schema / shape ------------------------------------------------------

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> int:
        return self._table.num_rows

    @property
    def arrow(self) -> "pa.Table":
        return self._table

    def to_pandas(self):
        return self._table.to_pandas()

    def select(self, names: Sequence[str]) -> "Dataset":
        return Dataset(self._table.select(list(names)), probe_encoding=False)

    def dictionary_size(self, name: str) -> Optional[int]:
        """Entry count of an encoded column's table-wide dictionary WITHOUT
        decoding it (decoding a large string dictionary materializes python
        objects); None for plain columns."""
        if name not in self._schema:
            return None
        t = self._table.schema.field(name).type
        if not pa.types.is_dictionary(t):
            return None
        col = self._table[name]
        if col.num_chunks == 0:
            return 0
        return len(col.chunk(0).dictionary)

    def dictionary_values(self, name: str) -> Optional[np.ndarray]:
        """The table-wide unified dictionary of an encoded column, or None
        for plain columns. Positions are the code space the per-batch
        ``Column.codes`` index into."""
        if name not in self._schema:
            return None
        t = self._table.schema.field(name).type
        if not pa.types.is_dictionary(t):
            return None
        col = self._table[name]
        if col.num_chunks == 0:
            return np.array([], dtype=object)
        return _decode_dictionary(col.chunk(0).dictionary, self._schema[name].kind)

    def with_columns_dictionary_encoded(self, names: Sequence[str]) -> "Dataset":
        """Dictionary-encode the given (plain) columns — works for any
        primitive type, e.g. a float column known to be low-cardinality.
        Columns that fail to encode are left untouched."""
        import pyarrow.compute as pc

        table = self._table
        for name in names:
            if name not in self._schema:
                continue
            if pa.types.is_dictionary(table.schema.field(name).type):
                continue
            try:
                encoded = pc.dictionary_encode(table[name])
            except Exception:  # noqa: BLE001
                continue
            table = table.set_column(
                table.schema.get_field_index(name), name, encoded
            )
        if table is self._table:
            return self
        return Dataset(table, probe_encoding=False)

    def with_column_cast_to_f64(self, name: str) -> "Dataset":
        """Replace a string column by its parsed-float64 version (profiler
        pass-2 cast, reference `profiles/ColumnProfiler.scala:346-354`).
        Values the arrow cast rejects (e.g. "- 1.5", which the reference's
        type-inference regex accepts) fall back to per-value parsing with
        unparseable values becoming null (Spark cast semantics)."""
        import pyarrow.compute as pc

        col = self._table[name]
        idx = self._table.schema.get_field_index(name)
        try:
            casted = pc.cast(col, pa.float64(), safe=False)
        except pa.ArrowInvalid:
            def parse(v):
                if v is None:
                    return None
                try:
                    # Spark cast trims outer whitespace only; interior
                    # spaces make the cast null
                    return float(str(v).strip())
                except ValueError:
                    return None

            casted = pa.array([parse(v) for v in col.to_pylist()], type=pa.float64())
        return Dataset(self._table.set_column(idx, name, casted), probe_encoding=False)

    def random_split(self, train_fraction: float, seed: int = 0) -> ("Dataset", "Dataset"):
        rng = np.random.default_rng(seed)
        n = self._table.num_rows
        picks = rng.random(n) < train_fraction
        idx = np.arange(n)
        return (
            Dataset(self._table.take(pa.array(idx[picks])), probe_encoding=False),
            Dataset(self._table.take(pa.array(idx[~picks])), probe_encoding=False),
        )

    # -- batching ------------------------------------------------------------

    def _materialize_column(self, name: str, chunk: "pa.ChunkedArray") -> Column:
        kind = self._schema[name].kind
        if isinstance(chunk, pa.ChunkedArray):
            # single-chunk slices (the common case: one-chunk tables) pass
            # through zero-copy; combine_chunks would COPY the slice — a
            # full extra memory pass per column per batch
            arr = chunk.chunk(0) if chunk.num_chunks == 1 else chunk.combine_chunks()
        else:
            arr = chunk
        n = len(arr)
        if arr.null_count:
            mask = np.asarray(arr.is_valid())
        else:
            mask = np.ones(n, dtype=bool)
        if isinstance(arr, pa.DictionaryArray):
            aux = self._dict_aux.setdefault(name, {})
            return _materialize_dictionary(name, kind, arr, mask, n, aux)
        if kind.is_numeric:
            values = _numeric_buffer_view(arr, n)
            if values is None:
                values = arr.to_numpy(zero_copy_only=False)
        elif kind == ColumnKind.BOOLEAN:
            values = arr.to_numpy(zero_copy_only=False)
            if values.dtype == object:
                values = np.array([bool(v) if v is not None else False for v in values.tolist()])
        elif kind == ColumnKind.TIMESTAMP:
            values = arr.to_numpy(zero_copy_only=False)
        elif kind == ColumnKind.STRING:
            # lazy: keep the arrow array; object values materialize only if
            # a python-level consumer (regex, group-by, histogram) asks
            return Column(name, kind, None, mask, arrow=arr)
        else:
            values = np.asarray(arr.to_pylist(), dtype=object)
        return Column(name, kind, values, mask)

    def batches(
        self,
        batch_size: int,
        columns: Optional[Sequence[str]] = None,
        pad_to_batch_size: bool = True,
    ) -> Iterator[Batch]:
        names = list(columns) if columns is not None else self._schema.names
        table = self._table.select(names) if names != self._schema.names else self._table
        n = table.num_rows
        for start in range(0, max(n, 1), batch_size):
            sl = table.slice(start, batch_size)
            m = min(batch_size, n - start)  # not sl.num_rows: 0-col tables misreport
            cols: Dict[str, Column] = {}
            for name in names:
                col = self._materialize_column(name, sl[name])
                if pad_to_batch_size and m < batch_size:
                    col = _pad_column(col, batch_size)
                cols[name] = col
            size = batch_size if pad_to_batch_size else m
            row_mask = np.zeros(size, dtype=bool)
            row_mask[:m] = True
            yield Batch(cols, row_mask, m)
            if n == 0:
                break


#: set to "0" to disable ingest-time adaptive dictionary encoding
ADAPTIVE_DICT_ENCODE_ENV = "DEEQU_TPU_ADAPTIVE_DICT_ENCODE"
#: rows sampled to estimate a plain string column's cardinality
_ENCODE_PROBE_ROWS = 1 << 16
#: a probe must stay under this many distinct values to qualify
_ENCODE_MAX_PROBE_DISTINCT = 1 << 13


def _maybe_dictionary_encode(table: "pa.Table") -> "pa.Table":
    """Dictionary-encode plain string columns that a cheap probe finds
    low-cardinality (the ingest-time analog of Parquet/Spark dictionary
    encoding). Every downstream consumer then rides the per-dataset
    dictionary caches — type inference, lengths, hashing and frequency
    counting become O(distinct) per dataset plus an O(rows) code pass,
    instead of per-row string work per batch per analyzer: a TPC-H flag
    column's DataType+HLL host cost drops ~30x. Columns whose probe looks
    high-cardinality stay as-is (encoding them would waste memory for no
    reuse). Disable with DEEQU_TPU_ADAPTIVE_DICT_ENCODE=0."""
    from ..utils import env_flag

    if not env_flag(ADAPTIVE_DICT_ENCODE_ENV, True):
        return table
    n = table.num_rows
    if n == 0:
        return table
    import pyarrow.compute as pc

    for i, field in enumerate(table.schema):
        if not (
            pa.types.is_string(field.type) or pa.types.is_large_string(field.type)
        ):
            continue
        column = table.column(i)
        # probe the head, middle AND tail: a column clustered/sorted by the
        # key (low-card head, high-card tail) must be rejected here, before
        # the full-column encode — the post-encode guard below still
        # catches what three slices miss, but the probes keep the common
        # clustered case from paying a full encode on EVERY construction
        try:
            qualified = True
            for start in (0, max((n - _ENCODE_PROBE_ROWS) // 2, 0),
                          max(n - _ENCODE_PROBE_ROWS, 0)):
                probe = column.slice(start, _ENCODE_PROBE_ROWS)
                distinct = pc.count_distinct(probe).as_py()
                # smaller tables qualify with proportionally smaller
                # dictionaries — 1000 rows with 900 distinct gains nothing
                limit = min(_ENCODE_MAX_PROBE_DISTINCT, max(len(probe) // 8, 1))
                if distinct > limit:
                    qualified = False
                    break
        except Exception:  # noqa: BLE001 - exotic layout: leave column alone
            continue
        if not qualified:
            continue
        try:
            encoded = pc.dictionary_encode(column)
        except Exception:  # noqa: BLE001
            continue
        # post-encode guard: a clustered/sorted column can fool the head
        # probe (low-card head, high-card tail) — revert when the actual
        # dictionary isn't meaningfully smaller than the rows, otherwise
        # every per-dataset O(dict) cache would dwarf the per-row work the
        # encoding exists to save
        built = sum(
            len(encoded.chunk(c).dictionary) for c in range(encoded.num_chunks)
        )
        if built > max(n // 4, _ENCODE_MAX_PROBE_DISTINCT):
            continue
        table = table.set_column(i, field.name, encoded)
    return table


#: fixed-width arrow types whose values buffer is a plain numpy dtype
_ZERO_COPY_DTYPES = None


def _zero_copy_dtype(t: "pa.DataType"):
    global _ZERO_COPY_DTYPES
    if _ZERO_COPY_DTYPES is None:
        _ZERO_COPY_DTYPES = {
            pa.int8(): np.int8, pa.int16(): np.int16,
            pa.int32(): np.int32, pa.int64(): np.int64,
            pa.uint8(): np.uint8, pa.uint16(): np.uint16,
            pa.uint32(): np.uint32, pa.uint64(): np.uint64,
            pa.float32(): np.float32, pa.float64(): np.float64,
        }
    return _ZERO_COPY_DTYPES.get(t)


def _numeric_buffer_view(arr: "pa.Array", n: int) -> Optional[np.ndarray]:
    """Zero-copy numpy view of a primitive arrow array's values buffer.

    Null slots carry whatever bytes Arrow left there (NOT NaN) — callers
    must treat masked-out positions as garbage. This is the contract the
    device feature feed relies on: every analyzer update masks before use,
    so the scan path makes no host-side copy of numeric columns at all."""
    dtype = _zero_copy_dtype(arr.type)
    if dtype is None:
        return None
    buf = arr.buffers()[1]
    if buf is None:
        return None
    view = np.frombuffer(buf, dtype=dtype, count=arr.offset + n)
    return view[arr.offset:]


def _decode_dictionary(dictionary: "pa.Array", kind: ColumnKind) -> np.ndarray:
    """The single decode policy for dictionary payloads — shared by batch
    materialization and Dataset.dictionary_values so the code->value mapping
    cannot drift between the two."""
    if kind.is_numeric or kind == ColumnKind.BOOLEAN:
        return dictionary.to_numpy(zero_copy_only=False)
    return np.asarray(dictionary.to_pylist(), dtype=object)


def _materialize_dictionary(
    name: str,
    kind: ColumnKind,
    arr: "pa.DictionaryArray",
    mask: np.ndarray,
    n: int,
    aux: "Optional[dict]" = None,
) -> Column:
    """Keep the (unified) codes + the ARROW dictionary; BOTH per-row values
    and the decoded dictionary stay LAZY — decoding a large string
    dictionary to python objects costs ~1s for a TPC-H comment column, and
    the native kernels (classify/lengths/hash) read the arrow buffers
    directly, so a profile run may never need the objects at all. Nulls get
    the out-of-range code len(dictionary), which the scatter-free device
    count drops. Derived artifacts cache once per dataset via ``aux``."""
    import pyarrow.compute as pc

    if aux is None:
        aux = {}
    num_cats = len(arr.dictionary)
    if aux.get("num_categories") != num_cats:
        aux.clear()  # dictionary changed: derived artifacts are stale
        aux["num_categories"] = num_cats
    indices = arr.indices
    if indices.null_count == 0 and indices.type == pa.int32():
        # the common fast shape (int32 indices, no nulls): zero-copy view,
        # no per-batch cast/fill pass
        codes = np.asarray(indices.to_numpy(zero_copy_only=True), dtype=np.int32)
    else:
        # widen BEFORE filling: the null sentinel num_cats may not fit the
        # dictionary's narrow index type (e.g. int8 indices, 128 categories)
        codes = np.asarray(
            pc.fill_null(indices.cast(pa.int32()), num_cats).to_numpy(
                zero_copy_only=False
            ),
            dtype=np.int32,
        )
    return Column(
        name, kind, None, mask, codes=codes,
        dictionary_arrow=arr.dictionary, aux=aux,
    )


def _pad_column(col: Column, size: int) -> Column:
    m = len(col.mask)
    pad = size - m
    if pad <= 0:
        return col
    mask = np.zeros(size, dtype=bool)
    mask[:m] = col.mask
    codes = None
    if col.codes is not None:
        # padding rows carry the null code (dropped by the device count)
        codes = np.full(size, col.num_categories, dtype=np.int32)
        codes[:m] = col.codes
    if col.arrow is not None and col._values is None:
        # stay lazy: pad the arrow array with nulls (C-speed concat)
        arrow = pa.concat_arrays([col.arrow, pa.nulls(pad, col.arrow.type)])
        return Column(
            col.name, col.kind, None, mask, codes=codes,
            dictionary=col._dictionary, dictionary_arrow=col._dictionary_arrow,
            arrow=arrow, aux=col.aux,
        )
    if col.has_dictionary and col._values is None:
        # dictionary columns stay lazy too: codes already padded above
        return Column(
            col.name, col.kind, None, mask, codes=codes,
            dictionary=col._dictionary, dictionary_arrow=col._dictionary_arrow,
            aux=col.aux,
        )
    if col.values.dtype == object:
        values = np.empty(size, dtype=object)
        values[:m] = col.values
    else:
        values = np.zeros(size, dtype=col.values.dtype)
        values[:m] = col.values
    return Column(
        col.name, col.kind, values, mask, codes=codes,
        dictionary=col._dictionary, dictionary_arrow=col._dictionary_arrow,
    )
