"""Constraint suggestion rules (reference `suggestions/rules/*.scala`).

Each rule decides applicability from a column profile and emits a
constraint + the fluent-API code string that would create it."""

from __future__ import annotations

import abc
import math
from dataclasses import dataclass
from typing import Dict, List, Tuple

from ..analyzers.grouping import NULL_FIELD_REPLACEMENT
from ..checks import contained_in_predicate, is_one
from ..constraints import (
    ConstrainableDataTypes,
    Constraint,
    completeness_constraint,
    compliance_constraint,
    data_type_constraint,
    uniqueness_constraint,
)
from ..metrics import DistributionValue
from ..profiles import ColumnProfile, NumericColumnProfile


@dataclass
class ConstraintSuggestion:
    """(reference `suggestions/ConstraintSuggestion.scala:25-35`)."""

    constraint: Constraint
    column_name: str
    current_value: str
    description: str
    suggesting_rule: "ConstraintRule"
    code_for_constraint: str


class ConstraintRule(abc.ABC):
    """(reference `suggestions/rules/ConstraintRule.scala:23-44`)."""

    rule_description: str = ""

    @abc.abstractmethod
    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        ...

    @abc.abstractmethod
    def candidate(self, profile: ColumnProfile, num_records: int) -> ConstraintSuggestion:
        ...


def _round_down_2(x: float) -> float:
    """BigDecimal setScale(2, DOWN) analog."""
    return math.floor(x * 100) / 100


class CompleteIfCompleteRule(ConstraintRule):
    """(reference `rules/CompleteIfCompleteRule.scala`)."""

    rule_description = (
        "If a column is complete in the sample, we suggest a NOT NULL constraint"
    )

    def should_be_applied(self, profile, num_records):
        return profile.completeness == 1.0

    def candidate(self, profile, num_records):
        return ConstraintSuggestion(
            completeness_constraint(profile.column, is_one),
            profile.column,
            f"Completeness: {profile.completeness}",
            f"'{profile.column}' is not null",
            self,
            f'.is_complete("{profile.column}")',
        )


class RetainCompletenessRule(ConstraintRule):
    """Models completeness as a binomial variable and suggests the 95%
    lower confidence bound (reference `rules/RetainCompletenessRule.scala`)."""

    rule_description = (
        "If a column is incomplete in the sample, we model its completeness "
        "as a binomial variable, estimate a confidence interval and use this "
        "to define a lower bound for the completeness"
    )

    def should_be_applied(self, profile, num_records):
        return 0.2 < profile.completeness < 1.0

    def candidate(self, profile, num_records):
        p = profile.completeness
        n = max(num_records, 1)
        z = 1.96
        target = _round_down_2(p - z * math.sqrt(p * (1 - p) / n))
        bound_percent = round((1.0 - target) * 100)
        return ConstraintSuggestion(
            completeness_constraint(profile.column, lambda v, t=target: v >= t),
            profile.column,
            f"Completeness: {profile.completeness}",
            f"'{profile.column}' has less than {bound_percent}% missing values",
            self,
            f'.has_completeness("{profile.column}", lambda v: v >= {target}, '
            f'"It should be above {target}!")',
        )


class RetainTypeRule(ConstraintRule):
    """(reference `rules/RetainTypeRule.scala`)."""

    rule_description = "If we detect a non-string type, we suggest a type constraint"

    def should_be_applied(self, profile, num_records):
        return profile.is_data_type_inferred and profile.data_type in (
            "Integral", "Fractional", "Boolean",
        )

    def candidate(self, profile, num_records):
        dt = {
            "Fractional": ConstrainableDataTypes.FRACTIONAL,
            "Integral": ConstrainableDataTypes.INTEGRAL,
            "Boolean": ConstrainableDataTypes.BOOLEAN,
        }[profile.data_type]
        return ConstraintSuggestion(
            data_type_constraint(profile.column, dt, is_one),
            profile.column,
            f"DataType: {profile.data_type}",
            f"'{profile.column}' has type {profile.data_type}",
            self,
            f'.has_data_type("{profile.column}", ConstrainableDataTypes.'
            f"{profile.data_type.upper()})",
        )


def _unique_value_ratio(entries: Dict[str, DistributionValue]) -> float:
    num_unique = sum(1 for v in entries.values() if v.absolute == 1)
    return num_unique / len(entries) if entries else 1.0


def _sql_category_list(keys: List[str]) -> str:
    return ", ".join("'" + k.replace("'", "''") + "'" for k in keys)


def _code_category_list(keys: List[str]) -> str:
    escaped = [k.replace("\\", "\\\\").replace('"', '\\"') for k in keys]
    return ", ".join(f'"{k}"' for k in escaped)


class CategoricalRangeRule(ConstraintRule):
    """(reference `rules/CategoricalRangeRule.scala:26-77`)."""

    rule_description = (
        "If we see a categorical range for a column, we suggest an "
        "IS IN (...) constraint"
    )

    def should_be_applied(self, profile, num_records):
        if profile.histogram is None or profile.data_type != "String":
            return False
        return _unique_value_ratio(profile.histogram.values) <= 0.1

    def candidate(self, profile, num_records):
        by_popularity = sorted(
            (
                (k, v)
                for k, v in profile.histogram.values.items()
                if k != NULL_FIELD_REPLACEMENT
            ),
            key=lambda kv: kv[1].absolute,
            reverse=True,
        )
        keys = [k for k, _ in by_popularity]
        categories_sql = _sql_category_list(keys)
        description = f"'{profile.column}' has value range {categories_sql}"
        predicate = _membership_predicate(profile.column, keys)
        return ConstraintSuggestion(
            compliance_constraint(description, predicate, is_one),
            profile.column,
            "Compliance: 1",
            description,
            self,
            f'.is_contained_in("{profile.column}", [{_code_category_list(keys)}])',
        )


class FractionalCategoricalRangeRule(ConstraintRule):
    """Top categories covering >= 90% of the data
    (reference `rules/FractionalCategoricalRangeRule.scala`)."""

    rule_description = (
        "If we see a categorical range for most values in a column, we "
        "suggest an IS IN (...) constraint that should hold for most values"
    )

    def __init__(self, target_data_coverage_fraction: float = 0.9):
        self.target_data_coverage_fraction = target_data_coverage_fraction

    def _top_categories(self, profile) -> Dict[str, DistributionValue]:
        sorted_values = sorted(
            profile.histogram.values.items(), key=lambda kv: kv[1].ratio, reverse=True
        )
        coverage = 0.0
        out: Dict[str, DistributionValue] = {}
        for key, value in sorted_values:
            if coverage < self.target_data_coverage_fraction:
                out[key] = value
                coverage += value.ratio
        return out

    def should_be_applied(self, profile, num_records):
        if profile.histogram is None or profile.data_type != "String":
            return False
        ratio = _unique_value_ratio(profile.histogram.values)
        top = self._top_categories(profile)
        ratio_sum = sum(v.ratio for v in top.values())
        return ratio <= 0.4 and ratio_sum < 1

    def candidate(self, profile, num_records):
        top = self._top_categories(profile)
        ratio_sums = sum(v.ratio for v in top.values())
        by_popularity = sorted(
            ((k, v) for k, v in top.items() if k != NULL_FIELD_REPLACEMENT),
            key=lambda kv: kv[1].absolute,
            reverse=True,
        )
        keys = [k for k, _ in by_popularity]
        categories_sql = _sql_category_list(keys)
        p = ratio_sums
        n = max(num_records, 1)
        z = 1.96
        target = _round_down_2(p - z * math.sqrt(p * (1 - p) / n))
        description = (
            f"'{profile.column}' has value range {categories_sql} for at "
            f"least {target * 100}% of values"
        )
        hint = f"It should be above {target}!"
        predicate = _membership_predicate(profile.column, keys)
        return ConstraintSuggestion(
            compliance_constraint(
                description, predicate, lambda v, t=target: v >= t, hint=hint
            ),
            profile.column,
            f"Compliance: {ratio_sums}",
            description,
            self,
            f'.is_contained_in("{profile.column}", [{_code_category_list(keys)}], '
            f"lambda v: v >= {target}, \"{hint}\")",
        )


class NonNegativeNumbersRule(ConstraintRule):
    """(reference `rules/NonNegativeNumbersRule.scala`)."""

    rule_description = (
        "If we see only non-negative numbers in a column, we suggest a "
        "corresponding constraint"
    )

    def should_be_applied(self, profile, num_records):
        return (
            isinstance(profile, NumericColumnProfile)
            and profile.minimum is not None
            and profile.minimum >= 0.0
        )

    def candidate(self, profile, num_records):
        description = f"'{profile.column}' has no negative values"
        minimum = (
            str(profile.minimum)
            if isinstance(profile, NumericColumnProfile) and profile.minimum is not None
            else "Error while calculating minimum!"
        )
        return ConstraintSuggestion(
            compliance_constraint(description, f"{profile.column} >= 0", is_one),
            profile.column,
            f"Minimum: {minimum}",
            description,
            self,
            f'.is_non_negative("{profile.column}")',
        )


class UniqueIfApproximatelyUniqueRule(ConstraintRule):
    """(reference `rules/UniqueIfApproximatelyUniqueRule.scala`; not part of
    the DEFAULT set there either)."""

    rule_description = (
        "If the ratio of approximate num distinct values in a column is "
        "close to the number of records (within the error of the HLL "
        "sketch), we suggest a UNIQUE constraint"
    )

    def should_be_applied(self, profile, num_records):
        if num_records == 0:
            return False
        approx_distinctness = profile.approximate_num_distinct_values / num_records
        return profile.completeness == 1.0 and abs(1.0 - approx_distinctness) <= 0.08

    def candidate(self, profile, num_records):
        approx_distinctness = profile.approximate_num_distinct_values / max(num_records, 1)
        return ConstraintSuggestion(
            uniqueness_constraint([profile.column], is_one),
            profile.column,
            f"ApproxDistinctness: {approx_distinctness}",
            f"'{profile.column}' is unique",
            self,
            f'.is_unique("{profile.column}")',
        )


def _membership_predicate(column: str, keys: List[str]) -> str:
    return contained_in_predicate(column, keys)


DEFAULT_RULES: Tuple[ConstraintRule, ...] = (
    CompleteIfCompleteRule(),
    RetainCompletenessRule(),
    RetainTypeRule(),
    CategoricalRangeRule(),
    FractionalCategoricalRangeRule(),
    NonNegativeNumbersRule(),
)
