"""Constraint suggestion: profile the data, apply heuristic rules per
column, optionally evaluate the suggested constraints on a held-out split
(reference `suggestions/ConstraintSuggestionRunner.scala:41-200+`,
`suggestions/rules/*.scala`).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..constraints import Constraint
from ..profiles import ColumnProfile, ColumnProfiles
from .rules import (
    DEFAULT_RULES,
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    ConstraintRule,
    ConstraintSuggestion,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    UniqueIfApproximatelyUniqueRule,
)


class Rules:
    """(reference `ConstraintSuggestionRunner.scala:30-36`)."""

    DEFAULT = DEFAULT_RULES


@dataclass
class ConstraintSuggestionResult:
    """(reference `suggestions/ConstraintSuggestionResult.scala:32-59`)."""

    column_profiles: Dict[str, ColumnProfile]
    num_records: int
    constraint_suggestions: Dict[str, List[ConstraintSuggestion]]
    verification_result: Optional[object] = None

    @property
    def all_suggestions(self) -> List[ConstraintSuggestion]:
        return [s for group in self.constraint_suggestions.values() for s in group]

    def to_json(self) -> str:
        return json.dumps(
            {
                "constraint_suggestions": [
                    {
                        "constraint_name": str(s.constraint),
                        "column_name": s.column_name,
                        "current_value": s.current_value,
                        "description": s.description,
                        "suggesting_rule": type(s.suggesting_rule).__name__,
                        "rule_description": s.suggesting_rule.rule_description,
                        "code_for_constraint": s.code_for_constraint,
                    }
                    for s in self.all_suggestions
                ]
            },
            indent=2,
        )


def apply_rules(profiles, constraint_rules) -> ConstraintSuggestionResult:
    """Rule application alone: profiles in, suggestions out. Shared by the
    one-shot runner below and the incremental plane
    (`runners.incremental.suggest_partitioned`), which feeds it profiles
    computed from stored partition states."""
    suggestions: List[ConstraintSuggestion] = []
    for profile in profiles.profiles.values():
        for rule in constraint_rules:
            if rule.should_be_applied(profile, profiles.num_records):
                suggestions.append(rule.candidate(profile, profiles.num_records))
    by_column: Dict[str, List[ConstraintSuggestion]] = {}
    for s in suggestions:
        by_column.setdefault(s.column_name, []).append(s)
    return ConstraintSuggestionResult(
        profiles.profiles, profiles.num_records, by_column
    )


class ConstraintSuggestionRunner:
    @staticmethod
    def on_data(data) -> "ConstraintSuggestionRunBuilder":
        return ConstraintSuggestionRunBuilder(data)

    @staticmethod
    def run(
        data,
        constraint_rules: Sequence[ConstraintRule],
        restrict_to_columns: Optional[Sequence[str]] = None,
        low_cardinality_histogram_threshold: int = 120,
        print_status_updates: bool = False,
        testset_ratio: Optional[float] = None,
        testset_split_random_seed: Optional[int] = None,
        metrics_repository=None,
        reuse_existing_results_key=None,
        fail_if_results_for_reusing_missing: bool = False,
        save_or_append_results_key=None,
        kll_parameters=None,
        predefined_types: Optional[Dict[str, str]] = None,
        suggestions_path: Optional[str] = None,
        evaluation_path: Optional[str] = None,
        profiles_path: Optional[str] = None,
        batch_size: Optional[int] = None,
    ) -> ConstraintSuggestionResult:
        from ..profiles import ColumnProfiler

        if testset_ratio is not None and not 0.0 < testset_ratio < 1.0:
            raise ValueError("Testset ratio must be in ]0, 1[")

        # train/test split (reference `splitTrainTestSets`)
        if testset_ratio is not None:
            seed = 0 if testset_split_random_seed is None else testset_split_random_seed
            training, test = data.random_split(1.0 - testset_ratio, seed=seed)
        else:
            training, test = data, None

        profiles = ColumnProfiler.profile(
            training,
            restrict_to_columns=restrict_to_columns,
            print_status_updates=print_status_updates,
            low_cardinality_histogram_threshold=low_cardinality_histogram_threshold,
            metrics_repository=metrics_repository,
            reuse_existing_results_using_key=reuse_existing_results_key,
            fail_if_results_for_reusing_missing=fail_if_results_for_reusing_missing,
            save_in_metrics_repository_using_key=save_or_append_results_key,
            kll_parameters=kll_parameters,
            predefined_types=predefined_types,
            batch_size=batch_size,
        )

        # per-profile (= per-column) rule application: the flat list's
        # order equals all_suggestions' grouped order, since each
        # profile's suggestions are contiguous
        result = apply_rules(profiles, constraint_rules)
        suggestions = result.all_suggestions

        from .. import io as dio

        if profiles_path is not None:
            dio.write_text_atomic(profiles_path, profiles.to_json())
        if suggestions_path is not None:
            dio.write_text_atomic(suggestions_path, result.to_json())

        # evaluate suggested constraints on the test split
        # (reference `evaluateConstraintsIfNecessary`)
        if test is not None and suggestions:
            from ..checks import Check, CheckLevel
            from ..verification import VerificationSuite

            check = Check(CheckLevel.WARNING, "generated constraints")
            for s in suggestions:
                check = check.add_constraint(s.constraint)
            verification = VerificationSuite.on_data(test).add_check(check).run()
            result.verification_result = verification
            if evaluation_path is not None:
                statuses = [
                    cr.status.value
                    for r in verification.check_results.values()
                    for cr in r.constraint_results
                ]
                payload = {
                    "constraint_suggestions": [
                        {
                            "constraint_name": str(s.constraint),
                            "column_name": s.column_name,
                            "code_for_constraint": s.code_for_constraint,
                            "constraint_result_on_test_set": status,
                        }
                        for s, status in zip(suggestions, statuses)
                    ]
                }
                dio.write_text_atomic(evaluation_path, json.dumps(payload, indent=2))
        return result


class ConstraintSuggestionRunBuilder:
    """(reference `suggestions/ConstraintSuggestionRunBuilder.scala`)."""

    def __init__(self, data):
        self.data = data
        self._rules: List[ConstraintRule] = []
        self._columns: Optional[Sequence[str]] = None
        self._threshold = 120
        self._print_status = False
        self._testset_ratio: Optional[float] = None
        self._testset_seed: Optional[int] = None
        self._repository = None
        self._reuse_key = None
        self._fail_if_missing = False
        self._save_key = None
        self._kll_parameters = None
        self._predefined_types: Optional[Dict[str, str]] = None
        self._suggestions_path: Optional[str] = None
        self._evaluation_path: Optional[str] = None
        self._profiles_path: Optional[str] = None
        self._batch_size: Optional[int] = None

    def add_constraint_rule(self, rule: ConstraintRule):
        self._rules.append(rule)
        return self

    def add_constraint_rules(self, rules: Sequence[ConstraintRule]):
        self._rules.extend(rules)
        return self

    def restrict_to_columns(self, columns: Sequence[str]):
        self._columns = columns
        return self

    def with_low_cardinality_histogram_threshold(self, threshold: int):
        self._threshold = threshold
        return self

    def print_status_updates(self):
        self._print_status = True
        return self

    def use_train_test_split_with_testset_ratio(
        self, testset_ratio: float, testset_split_random_seed: Optional[int] = None
    ):
        self._testset_ratio = testset_ratio
        self._testset_seed = testset_split_random_seed
        return self

    def use_repository(self, repository):
        self._repository = repository
        return self

    def reuse_existing_results_for_key(self, key, fail_if_results_missing: bool = False):
        self._reuse_key = key
        self._fail_if_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key):
        self._save_key = key
        return self

    def set_kll_parameters(self, parameters):
        self._kll_parameters = parameters
        return self

    def set_predefined_types(self, types: Dict[str, str]):
        self._predefined_types = types
        return self

    def save_constraint_suggestions_json_to_path(self, path: str):
        self._suggestions_path = path
        return self

    def save_evaluation_results_json_to_path(self, path: str):
        self._evaluation_path = path
        return self

    def save_column_profiles_json_to_path(self, path: str):
        self._profiles_path = path
        return self

    def with_batch_size(self, batch_size: int):
        self._batch_size = batch_size
        return self

    def run(self) -> ConstraintSuggestionResult:
        return ConstraintSuggestionRunner.run(
            self.data,
            self._rules,
            restrict_to_columns=self._columns,
            low_cardinality_histogram_threshold=self._threshold,
            print_status_updates=self._print_status,
            testset_ratio=self._testset_ratio,
            testset_split_random_seed=self._testset_seed,
            metrics_repository=self._repository,
            reuse_existing_results_key=self._reuse_key,
            fail_if_results_for_reusing_missing=self._fail_if_missing,
            save_or_append_results_key=self._save_key,
            kll_parameters=self._kll_parameters,
            predefined_types=self._predefined_types,
            suggestions_path=self._suggestions_path,
            evaluation_path=self._evaluation_path,
            profiles_path=self._profiles_path,
            batch_size=self._batch_size,
        )
