"""Per-shard heartbeat/health probing for multi-device meshes.

The reliability layer reacts to thrown faults, and the scan watchdog to a
WHOLE pass hanging — but a sharded fold can also lose exactly ONE shard: a
device drops off the ICI, a ``jax.distributed`` process dies, one chip
wedges while its seven neighbours keep folding. From the caller's side
that looks like either a raised collective error or a silent stall, and in
both cases the question the elastic layer needs answered is *which shards
are still alive*. This module answers it:

- :func:`probe_shards` runs a trivial round-trip (``device_put`` +
  ``block_until_ready``) against every device of a mesh, each under the
  heartbeat deadline, and returns the mesh positions that failed or
  stalled — the ground truth a salvage decision is made from;
- :class:`HeartbeatGate` time-gates the probe (default every
  ``DEEQU_TPU_SHARD_HEARTBEAT_S`` seconds) so the per-chunk fold path pays
  one clock read, not a device round-trip, between heartbeats.

Fault injection: each shard's probe passes through the ``shard_probe``
fault site (tag = shard position), so tests can declare any shard dead
deterministically (``mesh_loss``/``shard_stall`` kinds) without owning
hardware that can actually lose a chip.

``DEEQU_TPU_SHARD_HEARTBEAT_S`` follows the established warn-and-fallback
convention: unparseable values warn once and keep the default; any value
<= 0 disables the periodic heartbeat (explicit probes still work).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from ..exceptions import ShardLossError
from ..reliability.faults import fault_point

_logger = logging.getLogger(__name__)

#: env var: seconds between heartbeat probes of a live mesh fold (also the
#: per-shard probe deadline). <= 0 disables the periodic heartbeat.
HEARTBEAT_ENV = "DEEQU_TPU_SHARD_HEARTBEAT_S"
DEFAULT_HEARTBEAT_S = 5.0

def shard_heartbeat_s() -> Optional[float]:
    """The configured heartbeat interval in seconds, or ``None`` when the
    periodic heartbeat is disabled (value <= 0). Unparseable values warn
    once and keep the default (the shared ``env_number`` convention)."""
    from ..utils import env_number

    value = env_number(HEARTBEAT_ENV, DEFAULT_HEARTBEAT_S, float)
    return value if value > 0 else None


def probe_shards(mesh, deadline_s: Optional[float] = None) -> List[int]:
    """Probe every device of ``mesh`` and return the DEAD mesh positions
    (indices into ``mesh.devices.flat``) — see :func:`probe_devices`."""
    return probe_devices(list(mesh.devices.flat), deadline_s=deadline_s)


def probe_devices(devices, deadline_s: Optional[float] = None) -> List[int]:
    """Probe a DEVICE LIST (mesh-free — the fleet scheduler health-checks
    its whole table with this, positions indexing the given list): a
    probe that raises, or that fails to complete within ``deadline_s``
    (default: the heartbeat interval), declares its device dead.

    Each probe is one scalar ``device_put`` + ``block_until_ready`` — the
    cheapest op that still requires the device runtime to respond. Probes
    run on a single daemon worker so a wedged device cannot hang the
    caller; on timeout the worker is abandoned mid-probe and every
    not-yet-confirmed shard is declared stalled (a wedged chip early in
    the device order must not grant its neighbours a pass by starvation).
    """
    import numpy as np

    import jax

    if deadline_s is None:
        deadline_s = shard_heartbeat_s() or DEFAULT_HEARTBEAT_S
    devices = list(devices)
    dead: List[int] = []
    confirmed = [False] * len(devices)

    def probe_all() -> None:
        for i, device in enumerate(devices):
            try:
                fault_point("shard_probe", tag=str(i))
                jax.device_put(np.int32(1), device).block_until_ready()
            except ShardLossError as exc:
                # an injected loss names its shards; an empty list means
                # "this position"
                dead.extend(exc.lost or (i,))
            except Exception:  # noqa: BLE001 - a raising probe IS the signal
                dead.append(i)
            confirmed[i] = True

    worker = threading.Thread(
        target=probe_all, name="deequ-shard-probe", daemon=True
    )
    worker.start()
    worker.join(deadline_s)
    if worker.is_alive():
        # abandoned mid-probe: everything unconfirmed is stalled
        dead.extend(i for i in range(len(devices)) if not confirmed[i])
    if dead:
        _logger.warning(
            "shard heartbeat: %d/%d shards unresponsive (positions %s)",
            len(set(dead)), len(devices), sorted(set(dead)),
        )
    return sorted({i for i in dead if 0 <= i < len(devices)})


class HeartbeatGate:
    """Time-gated heartbeat: ``due()`` is a clock read; when the interval
    has elapsed, :meth:`check` probes the mesh and returns the dead
    positions (empty list = healthy). Disabled heartbeat -> never due."""

    def __init__(self, interval_s: Optional[float] = None):
        self.interval_s = (
            shard_heartbeat_s() if interval_s is None else interval_s
        )
        self._last = time.monotonic()

    def due(self) -> bool:
        if self.interval_s is None:
            return False
        return (time.monotonic() - self._last) >= self.interval_s

    def check(self, mesh) -> List[int]:
        self._last = time.monotonic()
        return probe_shards(mesh, deadline_s=self.interval_s)
