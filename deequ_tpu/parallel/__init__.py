"""Multi-device execution: row-sharded scans over a jax Mesh.

The reference's only parallelism is data parallelism over row partitions
with algebraic state merge (Spark partial aggregation + shuffle;
`rdd.treeReduce` for KLL — see SURVEY.md §2.9). TPU-native equivalents here:

1. **GSPMD scan** (`sharded_update`): the fused per-batch update is jit'd
   with the feature arrays sharded over the mesh's ``rows`` axis and the
   state pytrees replicated; XLA inserts the partial-reduce + collective
   combine automatically — the analog of Spark's partial-agg + shuffle, but
   compiled, fused and riding ICI.
2. **Explicit collective merge** (`collective_merge_states`): a shard_map
   program that all-gathers per-device state pytrees over the mesh axis and
   folds them with each analyzer's semigroup ``merge`` — the
   `KLLRunner.treeReduce` analog (reference `analyzers/runners/
   KLLRunner.scala:104-112`) for states whose merge is not a plain ``psum``
   (HLL register max, KLL level concat + compaction).

Cross-host: the same code runs under multi-host jax (`jax.distributed`);
mesh axes spanning hosts make the collectives ride DCN. States serialize to
numpy pytrees (see `analyzers/state_provider.py`) for the offline/
partitioned merge path (`AnalysisRunner.run_on_aggregated_states`).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..reliability.faults import fault_point

ROW_AXIS = "rows"


def _ensure_global(tree, mesh: Mesh, specs):
    """Host arrays -> global jax.Arrays laid out per ``specs`` when the
    mesh spans PROCESSES (jax.distributed): a multi-process jit cannot
    auto-shard plain numpy inputs the way single-process jit does, so each
    process contributes its addressable shards from its (identical) host
    copy via ``make_array_from_callback``. Single-process: no-op — jit's
    own in_shardings placement is cheaper. This is what turns the
    module docstring's DCN claim into executable truth (exercised by
    ``tools/dcn_smoke.py``)."""
    if jax.process_count() == 1:
        return tree

    def convert(x, spec):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x  # already a global array (e.g. a prior fold's output)
        arr = np.asarray(x)
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    return jax.tree_util.tree_map(
        convert, tree, specs,
        is_leaf=lambda x: isinstance(x, (np.ndarray, jax.Array)),
    )


def _local_view(tree):
    """Read back a replicated-per-device result in a multi-process run:
    every device holds the identical value, so each process reads its OWN
    first addressable shard (indexing a non-addressable global array would
    throw). Single-process: identity."""
    if jax.process_count() == 1:
        return tree
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x.addressable_data(0))
        if isinstance(x, jax.Array) and not x.is_fully_addressable
        else x,
        tree,
    )


def _shard_map(f, *, mesh, in_specs, out_specs):
    """`shard_map` across jax versions: the top-level API where present,
    else `jax.experimental.shard_map` (0.4.x). Replication checking is
    disabled either way — the merge programs intentionally return
    per-device values from replicated inputs — but the FLAG NAME also
    changed (`check_rep` -> `check_vma`) on a different release than the
    top-level promotion, so each flag spelling is tried rather than keyed
    off the API location."""
    if hasattr(jax, "shard_map"):
        api = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as api
    try:
        return api(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:
        return api(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def make_mesh(num_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the row axis (data parallelism over row shards)."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (ROW_AXIS,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROW_AXIS))


def shard_features(
    features: Dict[str, np.ndarray], mesh: Mesh, batch_rows: Optional[int] = None
) -> Dict[str, jax.Array]:
    """Place feature arrays row-sharded over the mesh. The batch axis is the
    one whose extent equals ``batch_rows`` (the engine pads batches to a
    multiple of the mesh size); e.g. the (2, B) HLL pairs shard on their
    LAST dim. Without ``batch_rows`` it is inferred from the 1-D arrays
    (the row mask is always present)."""
    if batch_rows is None:
        batch_rows = max(
            (a.shape[0] for a in features.values() if a.ndim == 1), default=0
        )
    out = {}
    for key, arr in features.items():
        if arr.ndim >= 1 and arr.shape[0] == batch_rows:
            spec = P(ROW_AXIS, *([None] * (arr.ndim - 1)))
        elif arr.ndim >= 2 and arr.shape[-1] == batch_rows:
            spec = P(*([None] * (arr.ndim - 1)), ROW_AXIS)
        else:
            spec = P()
        out[key] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out


def sharded_update(analyzers: Sequence[Any], mesh: Mesh):
    """jit the fused update with states replicated and features row-sharded;
    XLA turns every reduction into partial-per-device + collective."""

    def fused(states: Tuple, features: Dict[str, jax.Array]) -> Tuple:
        return tuple(a.update(s, features) for a, s in zip(analyzers, states))

    return jax.jit(
        fused,
        in_shardings=(replicated(mesh), None),  # features keep their placement
        out_shardings=replicated(mesh),
        donate_argnums=0,
    )


_SHARDED_INGEST_CACHE: dict = {}

#: jitted collective-merge programs keyed by (analyzers, devices, local
#: shard count, padded leaf shapes/dtypes); bounded FIFO like the engine's
#: merge-fold cache
from ..utils import BoundedLRU

_COLLECTIVE_MERGE_CACHE = BoundedLRU(64)


def sharded_ingest_fold(
    analyzers: Sequence[Any], mesh: Mesh, states_stacked, partials_stacked, flags
):
    """Fold a chunk of host-computed partials into PER-DEVICE states over the
    mesh: the stacked partials (leading dim = n_dev * local_chunk) shard over
    the row axis, and each device lax.scans its local slice into its own
    state copy — the executor-side partial-aggregation split composed WITH
    data parallelism (reference `AnalysisRunner.scala:303-318` + Spark's
    partition parallelism). ``flags`` marks which partials are real; padding
    entries skip all analyzer work. Finish a run by merging the per-device
    states with :func:`collective_merge_states`.

    ``states_stacked``: tuple (per analyzer) of pytrees with leading n_dev
    dim. Returns the updated stacked states."""
    from ..runners.engine import _ingest_signature

    # keyed by ingest SIGNATURES, not analyzer identities: same-class/
    # same-shape batteries over different columns share one compiled
    # sharded fold (the mesh-path analog of the bundled device programs —
    # ingest_partial is a pure function of class + state/partial shapes)
    key = (
        tuple(_ingest_signature(a) for a in analyzers),
        tuple(mesh.devices.flat),
    )
    program = _SHARDED_INGEST_CACHE.get(key)
    if program is None:
        def spec_of(tree):
            # jnp.asarray reads ndim without a D2H transfer of device leaves
            return jax.tree_util.tree_map(
                lambda x: P(ROW_AXIS, *([None] * (jnp.asarray(x).ndim - 1))), tree
            )

        from ..runners.engine import make_flagged_ingest_body

        body = make_flagged_ingest_body(tuple(analyzers))

        def local_fold(states, stacked, local_flags):
            local = jax.tree_util.tree_map(lambda x: x[0], states)
            out, _ = jax.lax.scan(body, local, (local_flags, stacked))
            return jax.tree_util.tree_map(lambda x: x[None], out)

        program = jax.jit(
            _shard_map(
                local_fold,
                mesh=mesh,
                in_specs=(
                    spec_of(states_stacked),
                    spec_of(partials_stacked),
                    P(ROW_AXIS),
                ),
                out_specs=spec_of(states_stacked),
            ),
            donate_argnums=0,  # states are dead after the fold, like the
            # single-device _ingest_program — no per-chunk state copies
        )
        _SHARDED_INGEST_CACHE[key] = program
    fault_point("sharded_fold")
    if jax.process_count() > 1:
        def spec_of_tree(tree):
            # np.ndim reads rank from metadata — jnp.asarray here would
            # device_put every (large) stacked leaf just to ask its rank
            return jax.tree_util.tree_map(
                lambda x: P(ROW_AXIS, *([None] * (np.ndim(x) - 1))), tree
            )

        states_stacked = _ensure_global(
            states_stacked, mesh, spec_of_tree(states_stacked)
        )
        partials_stacked = _ensure_global(
            partials_stacked, mesh, spec_of_tree(partials_stacked)
        )
        flags = _ensure_global(
            np.asarray(flags), mesh, P(ROW_AXIS)
        )
        return program(states_stacked, partials_stacked, flags)
    return program(states_stacked, partials_stacked, np.asarray(flags))


def stack_identity_states(analyzers: Sequence[Any], n_dev: int):
    """n_dev copies of each analyzer's identity state, leading dim n_dev —
    the initial per-device states for :func:`sharded_ingest_fold`."""
    out = []
    for a in analyzers:
        ident = a.init_state()
        out.append(
            jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(
                    jnp.asarray(x)[None], (n_dev,) + jnp.asarray(x).shape
                ),
                ident,
            )
        )
    return tuple(out)


def collective_merge_states(analyzers: Sequence[Any], mesh: Mesh, per_shard_states):
    """Fold per-shard state pytrees with each analyzer's semigroup ``merge``
    in ONE collective device program — the treeReduce analog (reference
    `analyzers/runners/KLLRunner.scala:104-112`).

    ``per_shard_states`` is a tuple (one entry per analyzer) of pytrees whose
    leaves carry a leading shard dim; the shard count comes from that dim,
    NOT the mesh size, so merging e.g. 8 persisted partition states on a
    4-device mesh folds all 8.

    Execution shape (a real tree reduction, not a sequential fold):

    1. pad the shard dim to a multiple of the mesh size with identity states
       (``init_state`` — every state merge is zero-count safe) and lay the
       shards out over the mesh axis, ``k`` local shards per device;
    2. inside ``shard_map``, each device folds its ``k`` local shards;
    3. cross-device combine: a log2(n)-round **butterfly** — each round
       ``lax.ppermute``s the partial state to the XOR partner and merges, so
       every round halves the number of distinct partials and all traffic
       rides ICI (falls back to one ``all_gather`` + local fold when the
       mesh size is not a power of two).
    """
    n_dev = int(mesh.devices.size)

    def shards_of(tree) -> int:
        leaves = jax.tree_util.tree_leaves(tree)
        return int(leaves[0].shape[0]) if leaves else 0

    total = max((shards_of(t) for t in per_shard_states), default=0)
    if total == 0:
        # zero shards: the merge of an empty set is the identity state
        return tuple(a.init_state() for a in analyzers)
    k = -(-total // n_dev)  # local shards per device after padding

    # pad with identity states so the shard dim is exactly n_dev * k
    padded = []
    for a, tree in zip(analyzers, per_shard_states):
        n = shards_of(tree)
        pad = n_dev * k - n
        if pad:
            ident = a.init_state()

            def pad_leaf(x, i):
                tile = jnp.broadcast_to(jnp.asarray(i)[None], (pad,) + jnp.asarray(i).shape)
                return jnp.concatenate([jnp.asarray(x), tile.astype(jnp.asarray(x).dtype)], axis=0)

            tree = jax.tree_util.tree_map(pad_leaf, tree, ident)
        padded.append(tree)
    padded = tuple(padded)

    # cache the jitted program: the closure is new per call, so without this
    # every merge invocation RECOMPILED the whole collective program (tens
    # of seconds of XLA work for a 27-analyzer battery). Keyed by ingest
    # SIGNATURES (class + state shapes), not analyzer identities, so
    # same-shape batteries over different columns share one collective —
    # semigroup ``merge`` is a pure function of class + state shapes.
    from ..runners.engine import _ingest_signature

    shape_sig = tuple(
        (leaf.shape, np.dtype(leaf.dtype).str)
        for leaf in jax.tree_util.tree_leaves(padded)
    )
    cache_key = (
        tuple(_ingest_signature(a) for a in analyzers),
        tuple(mesh.devices.flat),
        k,
        shape_sig,
    )
    program = _COLLECTIVE_MERGE_CACHE.get(cache_key)
    if program is None:
        shard_spec = jax.tree_util.tree_map(
            lambda x: P(ROW_AXIS, *([None] * (jnp.asarray(x).ndim - 1))), padded
        )
        pow2 = (n_dev & (n_dev - 1)) == 0

        def merge_program(stacked):
            out = []
            for a, tree in zip(analyzers, stacked):
                # 2) local fold of the k resident shards
                acc = jax.tree_util.tree_map(lambda x: x[0], tree)
                for i in range(1, k):
                    acc = a.merge(acc, jax.tree_util.tree_map(lambda x, _i=i: x[_i], tree))
                # 3) cross-device combine
                if n_dev > 1 and pow2:
                    shift = 1
                    while shift < n_dev:
                        perm = [(i, i ^ shift) for i in range(n_dev)]
                        partner = jax.tree_util.tree_map(
                            lambda x: jax.lax.ppermute(x, ROW_AXIS, perm), acc
                        )
                        acc = a.merge(acc, partner)
                        shift <<= 1
                elif n_dev > 1:
                    gathered = jax.tree_util.tree_map(
                        lambda x: jax.lax.all_gather(x, ROW_AXIS), acc
                    )
                    acc = jax.tree_util.tree_map(lambda x: x[0], gathered)
                    for i in range(1, n_dev):
                        acc = a.merge(
                            acc, jax.tree_util.tree_map(lambda x, _i=i: x[_i], gathered)
                        )
                out.append(jax.tree_util.tree_map(lambda x: x[None], acc))
            return tuple(out)

        program = jax.jit(
            _shard_map(
                merge_program,
                mesh=mesh,
                in_specs=(shard_spec,),
                out_specs=shard_spec,
            )
        )
        _COLLECTIVE_MERGE_CACHE[cache_key] = program
    fault_point("collective_merge")
    if jax.process_count() > 1:
        spec = jax.tree_util.tree_map(
            lambda x: P(ROW_AXIS, *([None] * (np.ndim(x) - 1))), padded
        )
        padded = _ensure_global(padded, mesh, spec)
    merged = program(padded)
    # every device holds the identical full merge; take device 0's copy
    # (each PROCESS reads its own addressable replica on a DCN mesh)
    merged = _local_view(merged)
    return tuple(
        jax.tree_util.tree_map(lambda x: x[0], tree) for tree in merged
    )


# elastic fault tolerance rides on the primitives above; imported LAST so
# the submodules can `from . import sharded_ingest_fold` etc. without a
# cycle (PEP 328 partial-module semantics: the names are already bound)
from .elastic import (  # noqa: E402,F401
    ElasticMeshFold,
    MESH_LADDER_ENV,
    add_shard_loss_listener,
    host_merge_states,
    mesh_batch_quantum,
    mesh_ladder,
    next_rung,
    remove_shard_loss_listener,
    salvage_stacked_states,
    stack_canonical_states,
)
from .health import (  # noqa: E402,F401
    HEARTBEAT_ENV,
    HeartbeatGate,
    probe_devices,
    probe_shards,
    shard_heartbeat_s,
)
