"""Elastic degraded-mesh execution: a sharded fold that survives shard loss.

The multi-chip scan (`sharded_ingest_fold` + `collective_merge_states`)
folds PER-DEVICE algebraic states: each shard's state is a semigroup value
covering exactly the batch partials that device folded. Before this
module, one dead device, dead DCN process or stalled shard aborted the
whole pass and threw away every SURVIVING shard's folded state — the exact
failure the state algebra makes unnecessary, because per-shard states are
mergeable by construction. This module closes that gap:

1. **Detection**: a fold dispatch raising :class:`ShardLossError` (real
   collective failure, injected ``mesh_loss``/``shard_stall`` fault, or a
   heartbeat probe declaring a shard dead — `parallel/health.py`) names the
   lost mesh positions.
2. **Salvage**: the surviving shards' states are fetched host-side and
   merged into ONE canonical state per analyzer (`host_merge_states` —
   device-free, so it works while the mesh is broken).
3. **Re-shard**: the mesh is rebuilt over the surviving devices at the
   next rung of the ladder (``DEEQU_TPU_MESH_LADDER``, default 8→4→2→1),
   the canonical merge becomes shard 0's state and the fold resumes. When
   the ladder is exhausted the fold drops to **host mode** — the canonical
   states keep folding eagerly on the host, the last-resort tier — so
   folded state is never lost even when no mesh can be rebuilt.
4. **Replay**: every fold records which global batch indices each shard
   owns; a lost shard's batches are exactly recomputable, and the engine
   replays them (and only them) on the rebuilt mesh, restoring the final
   merge to cover every batch exactly once.

Checkpoints compose: the engine checkpoints the CANONICAL merged states
(:meth:`ElasticMeshFold.canonical`), which are mesh-shape independent — a
checkpoint taken on 8 devices resumes on 4 (or on the host) bit-for-bit at
the state level, because the canonical form never mentions the mesh.
"""

from __future__ import annotations

import logging
import math
import os
import time
from typing import Any, List, Optional, Sequence, Set, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

#: env var: comma-separated descending device counts the re-shard ladder
#: walks after a loss (e.g. "8,4,2,1"). Follows the warn-and-fallback
#: convention: unparseable values warn once and keep the default.
MESH_LADDER_ENV = "DEEQU_TPU_MESH_LADDER"
DEFAULT_MESH_LADDER = (8, 4, 2, 1)

_ENV_WARNED = False


def mesh_ladder() -> Tuple[int, ...]:
    """The configured re-shard ladder, descending."""
    raw = os.environ.get(MESH_LADDER_ENV)
    if raw is None:
        return DEFAULT_MESH_LADDER
    try:
        rungs = tuple(
            sorted({int(p) for p in raw.split(",") if p.strip()}, reverse=True)
        )
        if not rungs or any(r < 1 for r in rungs):
            raise ValueError(raw)
    except ValueError:
        global _ENV_WARNED
        if not _ENV_WARNED:
            _ENV_WARNED = True
            _logger.warning(
                "ignoring unparseable %s=%r (expected comma-separated "
                "positive device counts); keeping the default ladder %s",
                MESH_LADDER_ENV, raw, DEFAULT_MESH_LADDER,
            )
        return DEFAULT_MESH_LADDER
    return rungs


def next_rung(ladder: Sequence[int], survivors: int) -> Optional[int]:
    """The largest ladder rung a mesh of ``survivors`` devices can fill,
    or None (ladder exhausted -> host mode)."""
    fitting = [r for r in ladder if r <= survivors]
    return max(fitting) if fitting else None


def mesh_batch_quantum(n_dev: int, ladder: Optional[Sequence[int]] = None) -> int:
    """The multiple mesh batch sizes round to. Shape-INDEPENDENT across the
    ladder: rounding to ``lcm(n_dev, max rung)`` gives every rung of the
    (power-of-two) ladder the same effective batch size, which is what
    makes a checkpoint taken under one mesh shape resumable under a
    smaller one (the meta record pins ``batch_size``; batch boundaries
    must not move when the mesh shrinks)."""
    rungs = mesh_ladder() if ladder is None else tuple(ladder)
    return math.lcm(max(1, int(n_dev)), max(rungs))


def host_merge_states(analyzers: Sequence[Any], shard_states: List[Tuple]) -> Tuple:
    """Merge per-shard states into one canonical state per analyzer with a
    host-side eager left fold of each analyzer's semigroup ``merge`` — no
    mesh, no collectives, so it works while the mesh is broken. Leaves
    come back as numpy (host-resident: immune to further device loss).

    ``shard_states``: list over shards of tuples (one state pytree per
    analyzer). Empty list -> identity states."""
    import jax

    def to_host(tree):
        return jax.tree_util.tree_map(np.asarray, tree)

    if not shard_states:
        return tuple(to_host(a.init_state()) for a in analyzers)
    merged = []
    for i, a in enumerate(analyzers):
        acc = shard_states[0][i]
        for shard in shard_states[1:]:
            acc = a.merge(acc, shard[i])
        merged.append(to_host(acc))
    return tuple(merged)


def stack_canonical_states(analyzers: Sequence[Any], canonical: Tuple, n_dev: int):
    """Stack canonical merged states back into per-device form for a fresh
    (possibly smaller) mesh: shard 0 carries the merge, shards 1..n-1 the
    identity — algebraically the same total state, re-shardable."""
    import jax
    import jax.numpy as jnp

    out = []
    for a, state in zip(analyzers, canonical):
        ident = a.init_state()

        def stack_leaf(c, i):
            c = jnp.asarray(c)
            if n_dev == 1:
                return c[None]
            tile = jnp.broadcast_to(
                jnp.asarray(i)[None], (n_dev - 1,) + jnp.asarray(i).shape
            ).astype(c.dtype)
            return jnp.concatenate([c[None], tile], axis=0)

        out.append(jax.tree_util.tree_map(stack_leaf, state, ident))
    return tuple(out)


def salvage_stacked_states(
    analyzers: Sequence[Any], stacked: Tuple, lost: Sequence[int]
) -> Tuple[List[Tuple], List[int]]:
    """Fetch the SURVIVING shards of stacked per-device states host-side.

    Returns ``(shard_states, salvaged_positions)`` where ``shard_states``
    is a list (one entry per salvaged shard, ascending position) of
    per-analyzer state tuples. A shard whose fetch itself fails (its
    buffers died with the device) is treated as lost too — salvage never
    raises for a fetchable subset."""
    import jax

    lost_set = set(int(i) for i in lost)
    leaves = jax.tree_util.tree_leaves(stacked)
    n_shards = int(leaves[0].shape[0]) if leaves else 0
    shard_states: List[Tuple] = []
    salvaged: List[int] = []
    for pos in range(n_shards):
        if pos in lost_set:
            continue
        try:
            state = tuple(
                jax.tree_util.tree_map(lambda x, _p=pos: np.asarray(x[_p]), tree)
                for tree in stacked
            )
        except Exception as exc:  # noqa: BLE001 - a dead buffer = a lost shard
            _logger.warning(
                "shard %d unsalvageable (%s); treating it as lost", pos, exc
            )
            continue
        shard_states.append(state)
        salvaged.append(pos)
    return shard_states, salvaged


#: fleet-level subscribers told which DEVICE OBJECTS a salvage declared
#: lost, the moment the elastic layer knows — so a fleet scheduler can
#: re-pack tenants off the dead chip without waiting for the job's
#: post-run harvest. Advisory: a raising listener is logged, never allowed
#: to break the recovery it observes.
_SHARD_LOSS_LISTENERS: List[Any] = []


def add_shard_loss_listener(fn) -> None:
    if fn not in _SHARD_LOSS_LISTENERS:
        _SHARD_LOSS_LISTENERS.append(fn)


def remove_shard_loss_listener(fn) -> None:
    try:
        _SHARD_LOSS_LISTENERS.remove(fn)
    except ValueError:
        pass


def _notify_shard_loss(devices: Sequence) -> None:
    for fn in list(_SHARD_LOSS_LISTENERS):
        try:
            fn(devices)
        except Exception:  # noqa: BLE001 - listeners are advisory
            _logger.warning("shard-loss listener failed", exc_info=True)


class MeshExhaustedError(RuntimeError):
    """Internal: no ladder rung fits the survivors (callers drop to host
    mode; this never escapes ElasticMeshFold)."""


class ElasticMeshFold:
    """A shard-loss-tolerant wrapper around ``sharded_ingest_fold``.

    The engine feeds it stacked chunk partials exactly as it fed the raw
    fold; the wrapper owns the per-device states, the batch-ownership
    ledger, the heartbeat gate, and the salvage / re-shard / host-mode
    recovery described in the module docstring. After the last chunk the
    engine drains :meth:`take_lost_batches` (recomputing and re-folding
    exactly those batches), then calls :meth:`finish` for the final
    canonical merge.
    """

    def __init__(
        self,
        analyzers: Sequence[Any],
        mesh,
        monitor=None,
        ladder: Optional[Sequence[int]] = None,
        heartbeat_s: Optional[float] = None,
    ):
        from . import stack_identity_states
        from .health import HeartbeatGate

        self.analyzers = tuple(analyzers)
        self.mesh = mesh
        self.monitor = monitor
        self.ladder = tuple(ladder) if ladder is not None else mesh_ladder()
        self.host_mode = False
        self.reshards = 0
        n_dev = int(mesh.devices.size)
        self.states = stack_identity_states(self.analyzers, n_dev)
        #: per mesh position: the global batch indices folded into that
        #: shard's state (what a loss of the shard would cost)
        self._owned: List[Set[int]] = [set() for _ in range(n_dev)]
        #: batches lost with dead shards, pending recompute+refold
        self._lost_batches: Set[int] = set()
        self._gate = HeartbeatGate(heartbeat_s)

    # -- introspection -------------------------------------------------------

    @property
    def n_dev(self) -> int:
        return 1 if self.host_mode else int(self.mesh.devices.size)

    @property
    def pending_replay(self) -> bool:
        return bool(self._lost_batches)

    def take_lost_batches(self) -> List[int]:
        """Pop the batches lost with dead shards (the engine replays them)."""
        todo = sorted(self._lost_batches)
        self._lost_batches.clear()
        return todo

    # -- lifecycle -----------------------------------------------------------

    def seed(self, canonical: Tuple, folded_batches: int) -> None:
        """Resume from checkpointed CANONICAL states covering batches
        ``[0, folded_batches)``. The canonical merge becomes shard 0's
        state; its batches enter the ledger so a later loss of shard 0
        replays them instead of silently dropping the resumed history."""
        if self.host_mode:
            self.states = tuple(canonical)
        else:
            self.states = stack_canonical_states(
                self.analyzers, tuple(canonical), self.n_dev
            )
        self._owned = [set() for _ in range(self.n_dev)]
        self._owned[0] = set(range(int(folded_batches)))

    def fold(self, stacked: Tuple, flags, batch_indices: Sequence[int]):
        """Fold one chunk of stacked partials. ``batch_indices`` names the
        global batch index behind each REAL slot (slot j real iff
        ``flags[j]``; list length = number of real slots). Survives shard
        loss internally: on loss the chunk retries on the rebuilt mesh (or
        folds on the host when the ladder is out)."""
        from . import sharded_ingest_fold

        flags = np.asarray(flags, dtype=bool)
        batch_indices = [int(i) for i in batch_indices]
        while not self.host_mode:
            if self._gate.due():
                dead = self._gate.check(self.mesh)
                if dead:
                    from ..exceptions import ShardStallError

                    self._recover(
                        ShardStallError(dead, "heartbeat",
                                        detail="shard heartbeat missed")
                    )
                    continue
            try:
                self.states = sharded_ingest_fold(
                    self.analyzers, self.mesh, self.states, stacked, flags
                )
            except Exception as exc:  # noqa: BLE001 - classified below
                from ..exceptions import ShardLossError
                from ..reliability.isolation import classify_failure

                if isinstance(exc, ShardLossError):
                    self._recover(exc)
                    continue
                if classify_failure(exc) == "device" and self.n_dev > 1:
                    # a raw collective/runtime error on a >1-device mesh:
                    # probe WHO died rather than abandoning every survivor
                    from .health import probe_shards

                    dead = probe_shards(self.mesh)
                    self._recover(
                        ShardLossError(
                            dead or [0], "sharded_fold",
                            detail=f"{type(exc).__name__}: {exc}",
                        )
                    )
                    continue
                raise
            self._record_ownership(flags, batch_indices)
            return self.states
        # host last resort: eager fold of the real slots, in batch order
        self._host_fold(stacked, flags)
        return self.states

    def _record_ownership(self, flags, batch_indices: List[int]) -> None:
        chunk = len(flags)
        local = max(1, chunk // self.n_dev)
        real = 0
        for j in range(chunk):
            if not flags[j]:
                continue
            self._owned[min(j // local, self.n_dev - 1)].add(
                batch_indices[real]
            )
            real += 1

    def _host_fold(self, stacked: Tuple, flags) -> None:
        import jax

        states = list(self.states)
        for j in range(len(flags)):
            if not flags[j]:
                continue
            for i, a in enumerate(self.analyzers):
                partial = jax.tree_util.tree_map(
                    lambda x, _j=j: x[_j], stacked[i]
                )
                states[i] = a.ingest_partial(states[i], partial)
        self.states = tuple(
            jax.tree_util.tree_map(np.asarray, s) for s in states
        )

    # -- recovery ------------------------------------------------------------

    def _recover(self, exc) -> None:
        """Salvage survivors, rebuild the mesh one rung down (or drop to
        host mode), queue the lost shards' batches for replay."""
        from ..observability import record_failure
        from ..observability import trace as _trace
        from . import make_mesh

        lost = sorted(set(exc.lost)) or [0]
        devices = list(self.mesh.devices.flat)
        old_n = len(devices)
        record_failure(exc)
        # tell fleet-level subscribers WHICH devices died (positions are
        # mesh-local; device objects are global identities)
        _notify_shard_loss([devices[i] for i in lost if 0 <= i < old_n])
        _trace.add_event(
            "shard_loss", site=getattr(exc, "site", ""), lost=lost,
            mesh_devices=old_n,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        if self.monitor is not None:
            self.monitor.bump("shard_losses", len(lost))
        _logger.warning(
            "mesh shard loss (%d of %d shards: %s); salvaging surviving "
            "states and re-sharding", len(lost), old_n, lost,
        )
        t0 = time.perf_counter()
        shard_states, salvaged = salvage_stacked_states(
            self.analyzers, self.states, lost
        )
        canonical = host_merge_states(self.analyzers, shard_states)
        if self.monitor is not None:
            self.monitor.bump("salvaged_states", len(salvaged))
        # every batch a non-salvaged shard folded must be recomputed
        salvaged_set = set(salvaged)
        kept: Set[int] = set()
        for pos, owned in enumerate(self._owned):
            if pos in salvaged_set:
                kept |= owned
            else:
                self._lost_batches |= owned
        _trace.add_event(
            "salvage", shards=len(salvaged),
            batches_kept=len(kept), batches_lost=len(self._lost_batches),
            seconds=round(time.perf_counter() - t0, 4),
        )
        survivors = [d for i, d in enumerate(devices) if i not in set(lost)]
        rung = next_rung(self.ladder, len(survivors))
        if rung is None:
            self.host_mode = True
            self.states = canonical
            self._owned = [kept]
            if self.monitor is not None:
                self.monitor.bump("mesh_reshards")
                self.monitor.note_degraded("mesh:host")
            self.reshards += 1
            _trace.add_event(
                "mesh_reshard", from_devices=old_n, to_devices=0, tier="host",
            )
            _logger.warning(
                "re-shard ladder exhausted (%d survivors, ladder %s); "
                "continuing the fold on the host tier with the salvaged "
                "canonical states", len(survivors), self.ladder,
            )
            return
        self.mesh = make_mesh(devices=survivors[:rung])
        self.states = stack_canonical_states(self.analyzers, canonical, rung)
        self._owned = [set() for _ in range(rung)]
        self._owned[0] = kept
        self.reshards += 1
        if self.monitor is not None:
            self.monitor.bump("mesh_reshards")
            self.monitor.note_degraded(f"mesh:{old_n}->{rung}")
        _trace.add_event(
            "mesh_reshard", from_devices=old_n, to_devices=rung, tier="mesh",
        )
        _logger.warning(
            "mesh rebuilt over %d surviving devices (ladder %s); resuming "
            "the fold from the salvaged merge", rung, self.ladder,
        )

    # -- termination ---------------------------------------------------------

    def canonical(self) -> Tuple:
        """The canonical merged states RIGHT NOW (for mesh-shape-independent
        checkpoints) without consuming the per-device states. Mesh-path
        merges that themselves hit a shard loss recover (salvage +
        re-shard) and re-merge."""
        if self.host_mode:
            return self.states
        from . import collective_merge_states

        while not self.host_mode:
            try:
                return collective_merge_states(
                    self.analyzers, self.mesh, self.states
                )
            except Exception as exc:  # noqa: BLE001 - classified below
                from ..exceptions import ShardLossError

                if isinstance(exc, ShardLossError):
                    self._recover(exc)
                    continue
                raise
        return self.states

    def finish(self) -> Tuple:
        """Final canonical merge. The engine must drain
        :meth:`take_lost_batches` first — finishing with pending replays
        would under-count exactly the lost shards' batches."""
        if self.pending_replay:
            raise RuntimeError(
                "ElasticMeshFold.finish() called with lost batches pending "
                "replay; drain take_lost_batches() first"
            )
        return self.canonical()
