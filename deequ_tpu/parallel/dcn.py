"""DCN (cross-host) fold/merge library: the multi-process leg of the
parallel tier, promoted from ``tools/dcn_smoke.py`` into library code the
cluster tier composes (ROADMAP item 2).

One machine or many, the shape is the same: N OS processes, each owning
its local chips, ``jax.distributed.initialize``d into ONE global mesh
whose row axis spans processes — every collective then crosses the
process boundary (on one box over the gloo CPU backend, the DCN
stand-in; on real pods over the actual DCN). On that mesh the ordinary
``sharded_ingest_fold`` + ``collective_merge_states`` run unchanged, so
cross-host battery aggregation is the SAME butterfly merge the
single-host fleet uses, just with network legs.

What lives here:

- process bring-up (:func:`initialize_dcn`, :func:`dcn_worker_env`) — the
  gloo + one-device-per-process env plumbing every multi-process test and
  tool used to copy-paste;
- the host-partial helpers (:func:`host_partials`, :func:`stack_partials`)
  feeding the mesh fold;
- :func:`merge_host_states`: each process contributes its HOST-side
  aggregate state as its shard of a global stacked array, and one
  log2(n) butterfly merge returns the cluster-wide battery state — the
  cluster tier's cross-host aggregation primitive (a coalescer drains
  per-host first; only the drained per-host aggregates ride the DCN);
- the loss-tolerant wrappers (:func:`with_deadline`,
  :func:`salvage_local_states`, :func:`replay_partials`): a dead peer
  makes the next cross-process step fail or hang, so every DCN dispatch
  runs under a deadline; on loss the survivor salvages its OWN
  addressable shard (algebraic states are mergeable by construction) and
  replays what the dead shard owned with eager host-side semigroup folds
  — no collectives, the mesh is gone.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import (
    collective_merge_states,
    make_mesh,
    sharded_ingest_fold,
)

#: default seconds a cross-process fold/merge may take before the peer is
#: declared lost (the drills' bar; operators size it to their DCN)
DEFAULT_DCN_DEADLINE_S = 15.0


def dcn_worker_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for a spawned DCN worker process: CPU platform with ONE
    device per process, so the mesh axis SPANS processes and every
    collective crosses the process boundary — the DCN path."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return env


def initialize_dcn(
    coordinator_address: str, num_processes: int, process_id: int
) -> None:
    """Join this process into the global mesh: gloo cross-process CPU
    collectives + ``jax.distributed.initialize``. Idempotent per process
    (re-initialize raises inside jax; callers spawn fresh processes)."""
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def with_deadline(fn, seconds: float):
    """Run ``fn`` on a daemon thread with a deadline; returns ``(value,
    error, timed_out)``. The DCN loss detector: a dead peer makes a
    cross-process step either raise or hang — the deadline converts the
    hang into a detectable loss signal without wedging the survivor."""
    box: dict = {}
    done = threading.Event()

    def body():
        try:
            box["value"] = fn()
        except BaseException as exc:  # noqa: BLE001 - reported to caller
            box["error"] = exc
        finally:
            done.set()

    threading.Thread(target=body, daemon=True).start()
    timed_out = not done.wait(seconds)
    return box.get("value"), box.get("error"), timed_out


def host_partials(
    analyzers: Sequence[Any], data, batch_rows: int
) -> List[Tuple]:
    """Per-batch host partial tuples of ``data`` (the mesh fold's input
    currency): one tuple of per-analyzer partial states per batch."""
    from ..analyzers.base import HostBatchContext

    partials = []
    for index, batch in enumerate(
        data.batches(batch_rows, pad_to_batch_size=False)
    ):
        ctx = HostBatchContext(batch, batch_index=index)
        partials.append(tuple(a.host_partial(ctx) for a in analyzers))
    return partials


def stack_partials(analyzers: Sequence[Any], partials: Sequence[Tuple]):
    """Stack per-batch partial tuples along a leading batch axis, one
    stacked pytree per analyzer (what ``sharded_ingest_fold`` scans)."""
    return tuple(
        jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[p[i] for p in partials],
        )
        for i in range(len(analyzers))
    )


def fold_partials(
    analyzers: Sequence[Any], mesh, states, partials: Sequence[Tuple]
):
    """Fold a chunk of host partials over the (possibly cross-process)
    mesh; blocks until the dispatch completes so a dead peer surfaces
    here, not at an arbitrary later sync point."""
    flags = np.ones(len(partials), dtype=bool)
    out = sharded_ingest_fold(
        analyzers, mesh, states, stack_partials(analyzers, partials), flags
    )
    jax.block_until_ready(jax.tree_util.tree_leaves(out))
    return out


def salvage_local_states(states) -> Tuple:
    """This process's addressable shard of per-device stacked states —
    the surviving state after a peer died (the peer's shard died with the
    peer). Works on global (multi-process) and local arrays alike."""

    def local_shard(tree):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(x.addressable_data(0))[0]
            if isinstance(x, jax.Array) and not x.is_fully_addressable
            else np.asarray(x[0]),
            tree,
        )

    return tuple(local_shard(tree) for tree in states)


def replay_partials(
    analyzers: Sequence[Any],
    salvaged: Sequence[Any],
    partials: Sequence[Tuple],
    indices: Sequence[int],
) -> Tuple:
    """Replay the batch slices a dead shard owned into the salvaged
    states: eager host-side semigroup folds (``ingest_partial``), no
    collectives — the mesh is gone. Algebraic states make this exact,
    not approximate: replay + salvage equals the lost fold."""
    finished = []
    for i, a in enumerate(analyzers):
        acc = salvaged[i]
        for j in indices:
            acc = a.ingest_partial(acc, partials[j][i])
        finished.append(acc)
    return tuple(finished)


def merge_host_states(
    analyzers: Sequence[Any],
    local_states: Sequence[Any],
    mesh=None,
    deadline_s: float = DEFAULT_DCN_DEADLINE_S,
):
    """Cross-host battery aggregation: every process contributes its
    HOST-side aggregate states (one per analyzer — e.g. a worker's
    drained per-host session aggregate) as its own shard of a global
    stacked array, then ONE log2(n) butterfly merge
    (``collective_merge_states``) returns the cluster-wide state to every
    process. Runs under ``deadline_s``; returns ``(merged_states, None)``
    on success or ``(None, reason)`` when a peer failed/hung — the caller
    salvages via the partition store instead.

    Single-process: the identity (the local states ARE the aggregate)."""
    if jax.process_count() == 1:
        return (
            tuple(
                jax.tree_util.tree_map(np.asarray, s) for s in local_states
            ),
            None,
        )
    mesh = mesh if mesh is not None else make_mesh()
    n_dev = int(mesh.devices.size)
    pid = int(jax.process_index())

    # per-shard stack: row pid carries THIS process's aggregate, every
    # other row the identity state. collective_merge_states lays the rows
    # out over the mesh axis via make_array_from_callback, under which
    # each process materializes only its own addressable row — so row i
    # of the GLOBAL array is process i's aggregate, and the identity
    # rows here are placement filler that is never read cross-process
    # (and merge-transparent even if a backend materializes them).
    def stacked_for(a, state):
        ident = a.init_state()

        def leaf(x, i):
            arr = np.asarray(x)
            base = np.asarray(i).astype(arr.dtype)
            out = np.broadcast_to(base[None], (n_dev,) + arr.shape).copy()
            out[pid] = arr
            return out

        return jax.tree_util.tree_map(leaf, state, ident)

    stacked = tuple(
        stacked_for(a, s) for a, s in zip(analyzers, local_states)
    )

    def run():
        merged = collective_merge_states(analyzers, mesh, stacked)
        jax.block_until_ready(jax.tree_util.tree_leaves(merged))
        return merged

    merged, err, timed_out = with_deadline(run, deadline_s)
    if merged is not None:
        return merged, None
    reason = (
        "collective merge timed out" if timed_out
        else f"collective merge failed: {err}"
    )
    return None, reason
