"""Typed failure taxonomy of the continuous verification service.

Every job submitted to the service terminates with either a result or one
of these errors — never a bare exception and never a silent hang. The
split mirrors the engine-side metric taxonomy (`deequ_tpu/exceptions.py`):
callers branch on TYPE, not on message strings.
"""

from __future__ import annotations


class ServiceError(Exception):
    """Base of every service-plane failure."""


class ServiceOverloaded(ServiceError):
    """Admission control shed this job: the pending queue is at capacity.

    Raised AT SUBMIT TIME — load sheds instead of queueing unboundedly, so
    a burst degrades into fast typed rejections rather than an ever-growing
    queue whose tail jobs all blow their deadlines anyway."""

    def __init__(self, queue_depth: int, max_queue_depth: int):
        self.queue_depth = queue_depth
        self.max_queue_depth = max_queue_depth
        super().__init__(
            f"verification service overloaded: {queue_depth} jobs pending "
            f"(max {max_queue_depth}); retry with backoff or shed load"
        )


class QuotaExceeded(ServiceOverloaded):
    """A tenant exceeded ITS OWN admission budget (rows/s, bytes/s, or
    queue share — typically set from its catalog document), not the
    service-wide queue bound.

    Deliberately a :class:`ServiceOverloaded` subclass: every transport
    mapping that sheds overload typed (HTTP 429, retry-with-backoff
    guidance) applies unchanged — but the type carries WHICH tenant blew
    WHICH budget, so a flooding tenant reads its own name in the error
    instead of blaming the service. Raised AT ADMISSION after any bounded
    backpressure wait (``block_s``) expires; neighbors' admission is
    untouched."""

    def __init__(
        self, tenant: str, resource: str, limit: float, observed: float
    ):
        self.tenant = str(tenant)
        self.resource = str(resource)
        self.limit = float(limit)
        self.observed = float(observed)
        # the parent's queue-shaped attrs stay valid for callers that
        # branch on ServiceOverloaded without knowing about quotas
        self.queue_depth = 0
        self.max_queue_depth = 0
        Exception.__init__(
            self,
            f"tenant {tenant!r} over its {resource} quota "
            f"({observed:.6g} > {limit:.6g}); retry with backoff — "
            "neighbors are unaffected",
        )


class JobTimeout(ServiceError):
    """The job's deadline elapsed before a result was delivered.

    ``completed=False``: the job never ran (it aged out in the queue) or
    was cut short — no side effects. ``completed=True``: the job FINISHED,
    just past its deadline; its side effects (streaming state folds,
    repository saves) have committed and the result is reachable on the
    handle's ``late_value`` — do not blindly re-run such a job."""

    def __init__(
        self,
        job_id: str,
        deadline_s: float,
        waited_s: float,
        completed: bool = False,
    ):
        self.job_id = job_id
        self.deadline_s = deadline_s
        self.waited_s = waited_s
        self.completed = completed
        suffix = " (work completed late; side effects committed)" if completed else ""
        super().__init__(
            f"job {job_id} exceeded its {deadline_s:.3f}s deadline "
            f"({waited_s:.3f}s elapsed){suffix}"
        )


class TransientFailure(ServiceError):
    """A retryable failure (flaky feed link, contended device, injected
    fault). The scheduler retries with exponential backoff up to the job's
    retry budget; exhausting it converts the last failure into
    :class:`JobFailed`."""


class JobFailed(ServiceError):
    """Permanent job failure: a non-retryable error, or a transient one
    whose retry budget ran out. The original error rides ``__cause__``."""

    def __init__(self, job_id: str, attempts: int, cause: BaseException):
        self.job_id = job_id
        self.attempts = attempts
        super().__init__(
            f"job {job_id} failed permanently after {attempts} attempt(s): "
            f"{type(cause).__name__}: {cause}"
        )
        self.__cause__ = cause


class SessionClosed(ServiceError):
    """A micro-batch arrived for a streaming session that was closed."""

    def __init__(self, tenant: str, dataset: str):
        self.tenant = tenant
        self.dataset = dataset
        super().__init__(f"streaming session {tenant}/{dataset} is closed")


class ServiceClosed(ServiceError):
    """A job was submitted after the service shut down."""
