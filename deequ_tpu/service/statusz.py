"""/statusz: ONE versioned ops snapshot of every service plane.

The service tier is six interacting planes (scheduler, tuning, cluster,
catalog, fleetwatch, partition store); debugging it one counter at a time
means six mental joins. ``/statusz`` (the borgmon tradition) serves a
single schema-checked JSON document that snapshots all of them at once —
and the soak harnesses (``tools/cluster_soak.py``, ``tools/chaos_soak.py``)
assert their invariants against THIS document instead of reaching into
internals, so the snapshot can never silently rot: the moment a plane
stops reporting, the soaks fail.

Contract:

- ``statusz_version`` is a monotonically bumped schema version; consumers
  gate on it before parsing deeper.
- ``planes`` holds one object per registered plane. A plane whose
  snapshot callable raises degrades to ``{"error": ...}`` — a sick plane
  must not take down the snapshot that would diagnose it — and
  :func:`validate_statusz` reports it.
- :data:`REQUIRED_PLANES` is the closed set every full service exposes;
  :func:`validate_statusz` checks presence, shape, and the per-plane
  required keys in :data:`PLANE_REQUIRED_KEYS`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List

#: bump on ANY backwards-incompatible change to the document shape or the
#: per-plane required keys (consumers gate on it before parsing deeper)
STATUSZ_VERSION = 1

#: every full service exposes exactly these planes (a worker that is not
#: cluster-attached still reports ``cluster: {"attached": false}``)
REQUIRED_PLANES = (
    "scheduler", "tuning", "cluster", "catalog", "fleetwatch",
    "partition_store",
)

#: keys each plane's section must carry — the "schema-checked" part of the
#: contract, kept deliberately shallow: presence + type of the load-bearing
#: fields, not the full value space
PLANE_REQUIRED_KEYS: Dict[str, tuple] = {
    "scheduler": ("queue_depth", "active_jobs", "shed_total",
                  "quota_shed_total"),
    "tuning": ("enabled",),
    "cluster": ("attached",),
    "catalog": ("enabled",),
    "fleetwatch": ("quarantined_sessions", "watched_series"),
    "partition_store": ("attached",),
}


class StatuszRegistry:
    """Plane name -> snapshot callable. ``snapshot()`` assembles the one
    document; registration is idempotent last-wins (a cluster worker
    overwrites the default detached ``cluster`` section with its own
    membership view)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sections: Dict[str, Callable[[], Dict[str, Any]]] = {}

    def register(
        self, plane: str, fn: Callable[[], Dict[str, Any]]
    ) -> None:
        with self._lock:
            self._sections[plane] = fn

    def planes(self) -> List[str]:
        with self._lock:
            return sorted(self._sections)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            sections = sorted(self._sections.items())
        planes: Dict[str, Any] = {}
        for plane, fn in sections:
            try:
                planes[plane] = fn()
            except Exception as exc:  # noqa: BLE001 - a sick plane must
                # not take down the snapshot that would diagnose it
                planes[plane] = {
                    "error": f"{type(exc).__name__}: {exc}"[:500]
                }
        return {
            "statusz_version": STATUSZ_VERSION,
            "generated_unix_s": time.time(),
            "planes": planes,
        }


def validate_statusz(doc: Any) -> List[str]:
    """Schema check; returns the list of problems ([] = valid). The soaks
    assert this comes back empty, so every required plane must be present,
    healthy (no ``error`` key), and carrying its required fields."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected object"]
    version = doc.get("statusz_version")
    if version != STATUSZ_VERSION:
        problems.append(
            f"statusz_version is {version!r}, expected {STATUSZ_VERSION}"
        )
    if not isinstance(doc.get("generated_unix_s"), (int, float)):
        problems.append("generated_unix_s missing or not a number")
    planes = doc.get("planes")
    if not isinstance(planes, dict):
        return problems + ["planes missing or not an object"]
    for plane in REQUIRED_PLANES:
        section = planes.get(plane)
        if not isinstance(section, dict):
            problems.append(f"plane {plane!r} missing or not an object")
            continue
        if "error" in section:
            problems.append(f"plane {plane!r} errored: {section['error']}")
            continue
        for key in PLANE_REQUIRED_KEYS.get(plane, ()):
            if key not in section:
                problems.append(f"plane {plane!r} missing key {key!r}")
    return problems
