"""Cache-aware placement: route jobs where their compiled programs live.

A battery's fused ``PackedScanProgram`` is cached per process keyed by the
exact analyzer tuple (`runners/engine.py`). A COLD battery pays a trace +
XLA compile measured at up to 575x the warm dispatch — long enough that one
cold job must never stall the queue behind it. The router therefore:

- answers "device" when the battery's fused program is already cached
  (warm: zero compile in the request path);
- answers "host" for a cold battery — the host ingest tier runs on small
  signature-bundled programs that converge across batteries and datasets,
  so a cold run completes promptly next to the data — while a background
  warmer builds the device program off the request path;
- remembers which WORKER ran each signature so the scheduler can prefer
  handing a battery back to the thread whose device-side working set
  (feature cache, donation buffers) is already hot.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence, Set, Tuple

_logger = logging.getLogger(__name__)

from ..analyzers.base import Analyzer, ScanShareableAnalyzer
from .metrics import ServiceMetrics

#: a battery signature: the deduped scan-shareable analyzer tuple, the same
#: object the engine keys its program cache on
Signature = Tuple[ScanShareableAnalyzer, ...]


def battery_signature(analyzers: Sequence[Analyzer]) -> Signature:
    """The deduped scan-shareable subset in first-encounter order — the
    fused battery `do_analysis_run` will build from these analyzers,
    normalized by the ENGINE's own helper so warmth keys can never drift
    from program-cache keys.

    This is the warmth KEY, not necessarily the exact compiled battery:
    data-dependent device-frequency scans join at run time and
    precondition failures drop analyzers, so the engine's program-cache
    key can differ. The router therefore also counts a signature warm once
    a job carrying it has RUN — whatever that run compiled is resident —
    rather than trusting cache introspection alone."""
    from ..runners.engine import _deduped_battery

    return _deduped_battery(analyzers)


def _mesh_devices(mesh) -> int:
    """Device count of a mesh-ish value: a ``jax.sharding.Mesh``, a
    :class:`~deequ_tpu.service.fleet.SubMeshLease`, an int, or None."""
    if mesh is None:
        return 1
    if isinstance(mesh, int):
        return mesh
    n = getattr(mesh, "n_dev", None)
    if n is not None:
        return int(n)
    devices = getattr(mesh, "devices", None)
    return int(devices.size) if devices is not None else 1


def shape_qualified_signature(
    analyzers: Sequence[Analyzer], batch_size: int, mesh=None
) -> Tuple:
    """``battery_signature`` plus the padded batch size, plus — for
    multi-device runs — the MESH SHAPE. jit compiles per SHAPE, so warmth
    must be claimed per (battery, batch size): a battery warm at one
    shape still cold-compiles at another, and routing it to the device
    tier would stall a worker on exactly the compile the router exists to
    keep off the queue. The mesh qualifier closes the same hole one level
    up: a pjit'd program's collective layout is baked per device set, so
    a battery warmed for the 8-device mesh must read COLD for the
    4-device sub-mesh a fleet re-pack hands the tenant (the sub-mesh
    white-box test pins this). Single-chip runs (``mesh=None``/1) keep
    the exact pre-fleet key, byte-for-byte — the DEEQU_TPU_FLEET=0
    escape hatch depends on it. An EMPTY battery (grouping/host-only
    checks) stays the empty signature — there is nothing to warm, and
    decide() must keep its no-battery early-out."""
    battery = battery_signature(analyzers)
    if not battery:
        return ()
    signature = battery + (("__batch__", int(batch_size)),)
    n_dev = _mesh_devices(mesh)
    if n_dev > 1:
        signature += (("__mesh__", n_dev),)
    return signature


def make_warm_fn(
    router: "PlacementRouter",
    analyzers: Sequence[Analyzer],
    mesh,
    data,
    batch_size: int,
) -> Optional[Callable[[], None]]:
    """The warm closure a submitter hands the scheduler: ``None`` when the
    battery is already warm at this batch shape (no artifacts built on hot
    paths); otherwise a thunk that compiles the production-shaped program
    from a DETACHED 1-row sample, so the queued closure never pins the
    job's dataset. The single construction point for both one-shot jobs
    and streaming ingests — the two paths' warmth behavior cannot drift
    apart. ``mesh`` may be a Mesh or a fleet :class:`SubMeshLease`; the
    warm then compiles the pjit'd program for that exact device slice,
    and the warmth key carries its shape."""
    signature = shape_qualified_signature(analyzers, batch_size, mesh)
    if not signature or router.is_warm(signature):
        return None
    from ..runners.engine import detached_warm_sample, warm_fused_program

    warm_mesh = getattr(mesh, "mesh", mesh)  # a lease unwraps to its Mesh
    sample = detached_warm_sample(data)

    def warm():
        warm_fused_program(
            analyzers, warm_mesh, data=sample, batch_size=batch_size
        )

    return warm


class PlacementRouter:
    def __init__(
        self,
        metrics: Optional[ServiceMetrics] = None,
        mesh=None,
        background_warm: bool = True,
    ):
        self.metrics = metrics or ServiceMetrics()
        self.mesh = mesh
        from ..utils import BoundedLRU

        self._lock = threading.Lock()
        #: worker affinity per signature — bounded like every other
        #: long-lived structure here, so churned-out batteries' analyzer
        #: tuples don't stay pinned in host memory forever
        self._workers_by_sig = BoundedLRU(256)
        #: warmth evidence from completed device runs/warms. Bounded to the
        #: same order as the engine's program cache (256): when the LRU
        #: there evicts a battery, this record ages out around the same
        #: churn, so an evicted battery eventually reads cold again and
        #: re-warms in the background instead of stalling a request
        self._ran = BoundedLRU(256)
        #: signatures with a warm currently IN FLIGHT (dedup only — every
        #: terminal path discards, so a cold battery can always re-warm)
        self._warming: Set[Signature] = set()
        #: signature -> remaining host-tier probation runs after a
        #: device-tier failure on that battery (engine failover evidence
        #: harvested from RunMonitor). While positive, decide() answers
        #: "host" outright: the battery keeps completing next to the data
        #: instead of re-hitting a sick device; the countdown then
        #: re-admits it to the device tier, so a transient fault does not
        #: exile a battery forever. Bounded like every long-lived map here.
        self._device_suspect = BoundedLRU(256)
        self._warmer: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="deequ-warmer")
            if background_warm
            else None
        )
        self.metrics.describe(
            "deequ_service_placement_cache_hits_total",
            "Jobs routed to a worker whose fused scan program was already compiled.",
        )
        self.metrics.describe(
            "deequ_service_placement_cache_misses_total",
            "Jobs whose battery was cold: routed to the host tier while the "
            "device program compiles in the background.",
        )
        self.metrics.describe(
            "deequ_service_programs_warmed_total",
            "Background warms that completed (compiled the production-shaped "
            "fused program).",
        )
        self.metrics.describe(
            "deequ_service_warm_failures_total",
            "Background warms that raised; the battery stays on the host "
            "tier (see the service log for the exception).",
        )
        self.metrics.describe(
            "deequ_service_device_failures_total",
            "Jobs whose engine run recorded a device-tier failure "
            "(failover or OOM bisection); the battery enters host-tier "
            "probation.",
        )
        self.metrics.describe(
            "deequ_service_suspect_host_routes_total",
            "Placement decisions answered 'host' because the battery was "
            "on device-failure probation.",
        )

    def is_warm(self, signature: Signature) -> bool:
        """Side-effect-free warmth probe (no counters, no warm scheduling):
        submitters use it to skip building warm artifacts for batteries
        that are already hot. ``signature`` is either a plain battery tuple
        (engine cache introspection applies, shape-agnostic) or a
        shape-qualified one from `shape_qualified_signature` (warmth rests
        purely on completed runs/warms AT THAT SHAPE)."""
        if not signature:
            return True
        if self._ran.get(signature):
            return True
        battery = tuple(
            a for a in signature if isinstance(a, ScanShareableAnalyzer)
        )
        if len(battery) == len(signature):
            from ..runners.engine import fused_program_is_cached

            return fused_program_is_cached(signature, self.mesh)
        return False

    def decide(
        self,
        signature: Signature,
        warm: Optional[Callable[[], None]] = None,
    ) -> Optional[str]:
        """Placement for a job with this battery: ``None`` (engine default /
        auto) when warm, ``"host"`` when cold. A cold decision also enqueues
        a background warm — ``warm`` (typically a real 1-padded-batch device
        run over the job's own data, which compiles the exact production
        program) or, absent one, a program registration — so the cold
        window closes after roughly one compile regardless of arrival
        rate."""
        from ..observability import trace as _trace

        if not signature:
            return None
        with self._lock:
            probation = self._device_suspect.get(signature)
            if probation:
                # the battery recently took a device-tier fault: serve it
                # from the host tier for the rest of its probation, then
                # let it try the device again
                self._device_suspect[signature] = probation - 1
                self.metrics.inc("deequ_service_suspect_host_routes_total")
                _trace.add_event(
                    "placement_routed", decision="host", reason="probation",
                    probation_left=probation - 1,
                )
                return "host"
        if self.is_warm(signature):  # .get inside refreshes LRU recency
            self.metrics.inc("deequ_service_placement_cache_hits_total")
            _trace.add_event("placement_routed", decision="auto", reason="warm")
            return None
        self.metrics.inc("deequ_service_placement_cache_misses_total")
        _trace.add_event(
            "placement_routed", decision="host", reason="cold",
            background_warm=warm is not None and self._warmer is not None,
        )
        if warm is not None and self._warmer is not None:
            self._warm_in_background(signature, warm)
        elif self._warmer is None:
            # background warming is off entirely: shelter THIS job on the
            # host tier, then let the next one take the device tier's
            # inline compile — permanently host-routing the battery would
            # make the device path unreachable forever
            self._ran[signature] = True
        # else: a warm-capable service raced warmth eviction between submit
        # (is_warm said hot, so no warm_fn was built) and pickup — run on
        # the host tier now WITHOUT faking warmth; the next submission sees
        # cold and builds a real warm_fn
        return "host"

    def _warm_in_background(
        self, signature: Signature, warm: Callable[[], None]
    ) -> None:
        with self._lock:
            if signature in self._warming:
                return
            self._warming.add(signature)

        def run_warm():
            try:
                warm()
                # the warm ran the REAL pipeline (full analyzer list,
                # production batch shape) on the device tier: that is
                # warmth evidence in its own right, and it covers batteries
                # whose compiled key drifts from the signature (run-time
                # device-frequency scans)
                self._ran[signature] = True
                self.metrics.inc("deequ_service_programs_warmed_total")
            except Exception:  # noqa: BLE001 - advisory, but NOT silent: a
                # persistently failing warm leaves the battery cold forever,
                # and an operator needs more than a climbing miss counter
                _logger.warning(
                    "background warm failed for battery of %d analyzers",
                    len(signature), exc_info=True,
                )
                self.metrics.inc("deequ_service_warm_failures_total")
            finally:
                # _warming is an in-flight marker, never a permanent claim:
                # a battery that goes cold again (warmth aged out, program
                # evicted) must always be able to re-warm
                with self._lock:
                    self._warming.discard(signature)

        try:
            self._warmer.submit(run_warm)
        except RuntimeError:
            # executor already shut down (service closing with jobs still
            # draining): warming is advisory — never let it kill the
            # worker that asked for a placement
            with self._lock:
                self._warming.discard(signature)

    #: decisions a battery spends on the host tier after a device failure
    #: before it may try the device again
    SUSPECT_PROBATION_RUNS = 8

    def note_device_failure(self, signature: Signature) -> None:
        """The engine recorded a device-tier failure (failover to host /
        OOM bisection) running this battery — the scheduler harvests this
        from the job's RunMonitor. Routes the battery to the host tier for
        the next :data:`SUSPECT_PROBATION_RUNS` decisions and drops its
        warmth claim: whatever program was resident is now suspect."""
        if not signature:
            return
        with self._lock:
            self._device_suspect[signature] = self.SUSPECT_PROBATION_RUNS
        self._ran.pop(signature, None)
        self.metrics.inc("deequ_service_device_failures_total")

    # -- worker affinity -----------------------------------------------------

    def note_ran(
        self,
        signature: Signature,
        worker_id: int,
        placement: Optional[str] = None,
    ) -> None:
        """Record that ``worker_id`` executed ``signature``. Only a run
        whose EXECUTED placement was the device tier counts as warmth
        evidence (its dispatch compiled the fused program, run-time
        augmentations included) — a host-tier run never builds the device
        program, and treating it as warm would send the next job straight
        into the cold compile. Worker affinity records either way."""
        if not signature:
            return
        if placement == "device":
            self._ran[signature] = True
        with self._lock:
            workers = self._workers_by_sig.get(signature)
            if workers is None:
                workers = set()
                self._workers_by_sig[signature] = workers
            workers.add(worker_id)

    def preferred_workers(self, signature: Signature) -> Set[int]:
        with self._lock:
            return set(self._workers_by_sig.get(signature) or ())

    def close(self) -> None:
        if self._warmer is not None:
            # cancel queued warms: each is a potential multi-second XLA
            # compile, and the executor's non-daemon threads would block
            # interpreter exit until every one finished
            self._warmer.shutdown(wait=False, cancel_futures=True)
