"""Continuous verification service: the layer between
``VerificationSuite.run()`` and callers.

One process-wide :class:`VerificationService` hosts:

- a multi-tenant **job scheduler** (`scheduler.JobScheduler`): bounded
  admission with typed load shedding, priority classes, per-job deadlines,
  retry-with-backoff on transient failures;
- **streaming micro-batch sessions** (`streaming.StreamingSession`):
  per-(tenant, dataset) incremental verification over persisted algebraic
  states, checks evaluated on every merge;
- **cache-aware placement** (`placement.PlacementRouter`): warm fused
  batteries run on the device tier, cold ones fall back to the host tier
  while the device program compiles in the background;
- a **fleet scheduler** (`fleet.FleetScheduler`): on a multi-chip
  accelerator, every tenant's scans shard across that tenant's DISJOINT
  sub-mesh slice of the device mesh by default, with elastic re-packing
  over the survivors when a shard dies (DEEQU_TPU_FLEET=0 restores
  single-chip routing byte-for-byte);
- an **export plane** (`metrics.ServiceMetrics` / `MetricsExporter`):
  Prometheus-text and JSON snapshots of per-phase timings, queue depth,
  retry/shed counts and cache hit rates, fed from each run's RunMonitor.

Usage::

    service = VerificationService(workers=4, max_queue_depth=128)
    handle = service.submit_verification(data, [check], tenant="team-a")
    result = handle.result(timeout=60)

    session = service.session("team-a", "clickstream", [check])
    session.ingest(micro_batch)          # checks evaluated on the merge

    print(service.prometheus_text())
    service.close()
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Tuple

from ..analyzers import Analyzer
from ..checks import Check
from ..data import Dataset
from .errors import (
    JobFailed,
    JobTimeout,
    QuotaExceeded,
    ServiceClosed,
    ServiceError,
    ServiceOverloaded,
    SessionClosed,
    TransientFailure,
)
from ..exceptions import SchemaDriftError
from .catalog import CatalogError, CatalogPlane, TenantCatalog, TenantDocument
from .coalesce import CrossoverRouter, FoldCoalescer
from .drift import DriftReport, SchemaContract
from .fleet import (
    FLEET_ENV,
    FleetScheduler,
    SubMeshLease,
    fleet_enabled,
    mesh_substrate,
)
from .fleetwatch import (
    FLEETWATCH_ENV,
    FleetWatch,
    HarvestReport,
    WatchSpec,
    fleetwatch_enabled,
)
from .metrics import MetricsExporter, ServiceMetrics
from .placement import (
    PlacementRouter,
    battery_signature,
    shape_qualified_signature,
)
from .scheduler import (
    JobContext,
    JobHandle,
    JobScheduler,
    Priority,
    TenantQuota,
)
from .streaming import StreamingSession, session_key

__all__ = [
    "VerificationService",
    "JobScheduler", "JobHandle", "JobContext", "Priority",
    "StreamingSession",
    "PlacementRouter", "battery_signature", "shape_qualified_signature",
    "ServiceMetrics", "MetricsExporter",
    "FoldCoalescer", "CrossoverRouter",
    "FleetScheduler", "SubMeshLease", "fleet_enabled", "mesh_substrate",
    "FLEET_ENV",
    "FleetWatch", "HarvestReport", "WatchSpec", "fleetwatch_enabled",
    "FLEETWATCH_ENV",
    "ServiceError", "ServiceOverloaded", "JobTimeout", "JobFailed",
    "TransientFailure", "SessionClosed", "ServiceClosed",
    "SchemaContract", "DriftReport", "SchemaDriftError",
    "TenantCatalog", "TenantDocument", "CatalogPlane", "CatalogError",
    "TenantQuota", "QuotaExceeded",
]


class VerificationService:
    """The orchestration facade of the service plane."""

    def __init__(
        self,
        workers: int = 4,
        max_queue_depth: int = 128,
        *,
        state_root: Optional[str] = None,
        mesh=None,
        background_warm: bool = True,
        metrics: Optional[ServiceMetrics] = None,
        fleet: Optional[bool] = None,
        partition_store=None,
        catalog=None,
    ):
        self.metrics = metrics or ServiceMetrics()
        self.router = PlacementRouter(
            self.metrics, mesh=mesh, background_warm=background_warm
        )
        # fleet scheduling: with no EXPLICIT mesh and a multi-device
        # accelerator (or DEEQU_TPU_FLEET=1 forcing the virtual-device
        # fallback), every tenant's scans shard across its own disjoint
        # sub-mesh by default. ``fleet=False`` (or DEEQU_TPU_FLEET=0)
        # restores single-chip routing byte-for-byte; an explicit
        # ``mesh=`` keeps the legacy one-global-mesh behavior unchanged.
        from .fleet import FleetScheduler, fleet_enabled

        self.fleet = None
        if mesh is None and (
            fleet if fleet is not None else fleet_enabled()
        ):
            self.fleet = FleetScheduler(self.metrics)
        self.scheduler = JobScheduler(
            workers=workers,
            max_queue_depth=max_queue_depth,
            metrics=self.metrics,
            router=self.router,
            fleet=self.fleet,
        )
        self.state_root = state_root
        self.mesh = mesh
        # partition-aware incremental verification (ROADMAP item 4): the
        # service-default PartitionStateStore. Accepts a store instance or
        # a root path; unset falls back to DEEQU_TPU_PARTITION_STORE (None
        # when that is unset too). Streaming sessions flush their states
        # into it as a partition on close, and verify_partitioned below
        # plans deltas against it.
        from ..repository.partition_store import (
            PartitionStateStore,
            default_partition_store,
        )

        if partition_store is None:
            self.partition_store = default_partition_store()
        elif isinstance(partition_store, str):
            self.partition_store = PartitionStateStore(partition_store)
        else:
            self.partition_store = partition_store
        from .coalesce import FoldCoalescer

        #: cross-session fold coalescing + tiny-delta host fast path
        #: (DEEQU_TPU_COALESCE=0 bypasses it per ingest, exactly
        #: reproducing the serial path)
        self.coalescer = FoldCoalescer(self)
        from .fleetwatch import FleetWatch

        #: the standing fleet-scale anomaly watch: every scheduler harvest
        #: of a WATCHED tenant re-scores the fleet's metric histories in
        #: batched detect_batch calls and surfaces anomalies on the export
        #: plane (DEEQU_TPU_FLEETWATCH=0 detaches the trigger; explicit
        #: harvest_now() always works)
        self.fleetwatch = FleetWatch(self)
        self.fleetwatch.attach()
        from ..tuning import bootstrap_service

        #: the self-tuning control plane: loads this substrate's
        #: calibration profile (quarantining corrupt ones, never failing
        #: the boot), reseeds the CrossoverRouter, and runs the online
        #: shadow-route controller off the scheduler's harvest tick.
        #: None when DEEQU_TPU_AUTOTUNE=0 — every knob then reads its
        #: static default, byte-for-byte the untuned service.
        self.tuning_controller = bootstrap_service(self)
        self._sessions: Dict[Tuple[str, str], StreamingSession] = {}
        self._sessions_lock = threading.Lock()
        self._exporter: Optional[MetricsExporter] = None

        def open_sessions() -> int:
            with self._sessions_lock:  # a scrape must not race session()
                return sum(1 for s in self._sessions.values() if not s.closed)

        self.metrics.set_gauge_fn(
            "deequ_service_open_sessions", open_sessions,
            "Streaming sessions currently accepting micro-batches.",
        )
        from .streaming import describe_streaming_series

        describe_streaming_series(self.metrics)
        # the tenant isolation plane's declarative frontend: a catalog of
        # per-tenant suite DOCUMENTS (checks, row gate, quotas, watches,
        # drift/priority policy), bound to this service by a CatalogPlane
        # that materializes sessions from documents on first ingest and
        # hot-reloads them at fold boundaries. Accepts a TenantCatalog
        # instance or a root path; None = no catalog (every session is
        # constructed programmatically, exactly as before).
        self.catalog_plane = None
        if catalog is not None:
            if isinstance(catalog, str):
                catalog = TenantCatalog(catalog, metrics=self.metrics)
            self.catalog_plane = CatalogPlane(self, catalog)
        from .statusz import StatuszRegistry

        #: the unified ops snapshot (/statusz): one plane per subsystem,
        #: registered here so the document always covers the full closed
        #: set — a worker process later OVERWRITES the detached "cluster"
        #: section with its membership view (last-wins registration)
        self.statusz = StatuszRegistry()
        self._register_statusz_planes()

    def _register_statusz_planes(self) -> None:
        """Register the six REQUIRED_PLANES sections of the /statusz
        document against this service's live objects. Sections read
        through to the planes at snapshot time — never cached."""

        def scheduler_section():
            return {
                "queue_depth": self.scheduler.pending(),
                "active_jobs": self.scheduler._active,
                "workers": len(self.scheduler._workers),
                "shed_total": self.metrics.counter_value(
                    "deequ_service_jobs_shed_total"
                ),
                "quota_shed_total": self.metrics.counter_value(
                    "deequ_service_quota_shed_total"
                ),
                "ingest_shed_total": self.metrics.counter_value(
                    "deequ_service_ingest_shed_total"
                ),
            }

        def tuning_section():
            controller = self.tuning_controller
            if controller is None:
                return {"enabled": False}
            snap = controller.snapshot()
            return {
                "enabled": True,
                "active_knobs": snap.get("tuned", {}),
                "experiments": snap.get("experiments", {}),
                "decisions": snap.get("decisions", []),
                "floor": {
                    "static_rate_ewma": snap.get("static_rate_ewma"),
                    "static_samples": snap.get("static_samples"),
                    "live_rate_ewma": snap.get("live_rate_ewma"),
                    "live_samples": snap.get("live_samples"),
                },
            }

        def catalog_section():
            plane = self.catalog_plane
            if plane is None:
                return {"enabled": False}
            catalog = plane.catalog
            return {
                "enabled": True,
                "tenant_versions": {
                    tenant: catalog.current_version(tenant)
                    for tenant in catalog.tenants()
                },
            }

        def partition_store_section():
            store = self.partition_store
            if store is None:
                return {"attached": False}
            from ..repository.partition_store import (
                partition_quarantined_total,
            )

            section = {
                "attached": True,
                "path": getattr(store, "path", None),
                "quarantined_total": partition_quarantined_total(),
            }
            # compaction lag lives on the metrics-HISTORY repositories
            # (PartitionedMetricsRepository); the long-lived ones the
            # service knows are the fleet watch's — aggregate theirs
            lags = {}
            with self.fleetwatch._lock:
                repos = {
                    f"{t}/{d}": w.repository
                    for (t, d), w in self.fleetwatch._watches.items()
                }
            for key, repo in sorted(repos.items()):
                lag_fn = getattr(repo, "compaction_lag", None)
                if callable(lag_fn):
                    try:
                        lags[key] = lag_fn()
                    except Exception:  # noqa: BLE001 - one sick repo
                        # must not blank the whole section
                        lags[key] = {"error": "unreadable"}
            section["compaction_lag"] = lags
            section["max_loose_entries"] = max(
                (lag.get("max_loose", 0) for lag in lags.values()
                 if isinstance(lag, dict) and "max_loose" in lag),
                default=0,
            )
            return section

        self.statusz.register("scheduler", scheduler_section)
        self.statusz.register("tuning", tuning_section)
        self.statusz.register(
            "cluster", lambda: {"attached": False}
        )
        self.statusz.register("catalog", catalog_section)
        self.statusz.register(
            "fleetwatch", self.fleetwatch.statusz_section
        )
        self.statusz.register("partition_store", partition_store_section)

    # -- one-shot jobs -------------------------------------------------------

    def submit_verification(
        self,
        data: Dataset,
        checks: Sequence[Check],
        *,
        required_analyzers: Sequence[Analyzer] = (),
        tenant: str = "default",
        priority: Priority = Priority.NORMAL,
        deadline_s: Optional[float] = None,
        max_retries: int = 2,
        retry_on: Tuple[type, ...] = (),
        batch_size: Optional[int] = None,
        metrics_repository: Optional[Any] = None,
        save_or_append_results_with_key: Optional[Any] = None,
    ) -> JobHandle:
        """Queue one verification run; returns immediately with a
        :class:`JobHandle` (or raises :class:`ServiceOverloaded`)."""
        from ..runners.analysis_runner import collect_required_analyzers
        from ..verification import VerificationSuite

        # materialize BEFORE collecting: a one-shot iterable would be
        # consumed by the signature walk and the job would silently verify
        # zero checks
        checks = list(checks)
        required = list(required_analyzers)
        analyzers = collect_required_analyzers(checks, required)

        def run(ctx: JobContext):
            return VerificationSuite.do_verification_run(
                data,
                checks,
                required,
                metrics_repository=metrics_repository,
                save_or_append_results_with_key=save_or_append_results_with_key,
                batch_size=effective_bs,
                monitor=ctx.monitor,
                # fleet default path: the tenant's leased sub-mesh shards
                # this scan's row stream; an explicit service mesh keeps
                # the legacy one-global-mesh behavior; neither -> single
                # chip (the escape-hatch path, byte-for-byte)
                sharding=ctx.mesh if ctx.mesh is not None else self.mesh,
                placement=ctx.placement,
            )

        from .placement import make_warm_fn
        from .streaming import _session_batch_size

        # the SAME sizing rule as streaming ingests (power-of-two bucket
        # clamped to the engine default): jit compiles per shape, so
        # datasets of wandering row counts must converge on a bounded
        # shape set. The run below is passed this same explicit batch
        # size, so the warmth key can never drift from the dispatched
        # shape.
        effective_bs = _session_batch_size(int(data.num_rows), batch_size)
        # warmth is claimed per MESH SHAPE too: under the fleet the
        # expected slice for this tenant qualifies the key (and the warm
        # compiles for that exact slice), so a re-packed tenant reads
        # cold at its new shape instead of reusing a mismatched program
        warm_mesh = (
            self.fleet.peek(tenant) if self.fleet is not None else self.mesh
        )
        signature = shape_qualified_signature(
            analyzers, effective_bs, warm_mesh
        )
        warm = make_warm_fn(
            self.router, analyzers, warm_mesh, data, effective_bs
        )
        return self.scheduler.submit(
            run,
            tenant=tenant,
            priority=priority,
            deadline_s=deadline_s,
            max_retries=max_retries,
            retry_on=retry_on,
            signature=signature,
            warm_fn=warm,
            mesh_tenant=tenant if self.fleet is not None else None,
        )

    def verify(self, data: Dataset, checks: Sequence[Check], **kw):
        """Blocking convenience: submit + wait for the result."""
        timeout = kw.pop("timeout", None)
        return self.submit_verification(data, checks, **kw).result(timeout)

    # -- partition-aware incremental verification ----------------------------

    def submit_partitioned_verification(
        self,
        dataset_name: str,
        partitions,
        checks: Sequence[Check],
        *,
        checksums=None,
        required_analyzers: Sequence[Analyzer] = (),
        tenant: str = "default",
        priority: Priority = Priority.NORMAL,
        deadline_s: Optional[float] = None,
        max_retries: int = 0,
        batch_size: Optional[int] = None,
        store=None,
        metrics_repository: Optional[Any] = None,
        save_or_append_results_with_key: Optional[Any] = None,
        delete_dropped: bool = False,
    ) -> JobHandle:
        """Queue one INCREMENTAL verification run against the service's
        partition store: the delta planner diffs ``partitions`` against
        the store, scans only new/changed partitions — riding the
        tenant's fleet sub-mesh when the fleet scheduler is on — and
        merges stored + fresh states into suite metrics. The job's
        RunMonitor counters (partitions scanned/reused/invalidated/
        dropped) harvest onto the export plane per tenant.

        ``max_retries`` defaults to 0: a partition scan PERSISTS states
        and commits manifests as it goes, so a blind re-run after a
        partial failure re-plans (already-committed partitions reuse) —
        retrying is safe but rarely what a caller wants implicitly."""
        from ..verification import VerificationSuite

        target = store if store is not None else self.partition_store
        if target is None:
            raise ValueError(
                "no partition store: pass store=, construct the service "
                "with partition_store=, or set DEEQU_TPU_PARTITION_STORE"
            )
        checks = list(checks)
        required = list(required_analyzers)

        def run(ctx: JobContext):
            return VerificationSuite.verify_partitioned(
                target,
                dataset_name,
                partitions,
                checks,
                required,
                checksums=checksums,
                batch_size=batch_size,
                monitor=ctx.monitor,
                # fresh-partition scans shard across the tenant's leased
                # sub-mesh (fleet default path), the explicit service
                # mesh, or a single chip — the submit_verification order
                sharding=ctx.mesh if ctx.mesh is not None else self.mesh,
                placement=ctx.placement,
                metrics_repository=metrics_repository,
                save_or_append_results_with_key=save_or_append_results_with_key,
                delete_dropped=delete_dropped,
            )

        return self.scheduler.submit(
            run,
            tenant=tenant,
            priority=priority,
            deadline_s=deadline_s,
            max_retries=max_retries,
            mesh_tenant=tenant if self.fleet is not None else None,
        )

    def verify_partitioned(
        self, dataset_name: str, partitions, checks: Sequence[Check], **kw
    ):
        """Blocking convenience of
        :meth:`submit_partitioned_verification`."""
        timeout = kw.pop("timeout", None)
        return self.submit_partitioned_verification(
            dataset_name, partitions, checks, **kw
        ).result(timeout)

    # -- streaming sessions --------------------------------------------------

    def session(
        self, tenant: str, dataset: str, checks: Sequence[Check] = (), **kw
    ) -> StreamingSession:
        """Get-or-create the streaming session for (tenant, dataset). On
        first creation, ``checks`` (and any StreamingSession kwargs) define
        the session; later calls return the live session unchanged."""
        key = session_key(tenant, dataset)
        with self._sessions_lock:
            existing = self._sessions.get(key)
            if existing is not None and not existing.closed:
                return existing
            if existing is not None and not checks and not kw:
                # a bare get of a CLOSED session must not silently
                # recreate it with zero checks and empty state — the
                # caller would fold batches into a session that verifies
                # nothing and always reports SUCCESS
                raise SessionClosed(tenant, dataset)
            if "state_provider" not in kw and self.state_root is not None:
                from urllib.parse import quote

                from ..analyzers.state_provider import FileSystemStateProvider

                # quote each component so a "/" INSIDE a tenant or dataset
                # name cannot alias another (tenant, dataset) pair's
                # namespace — ("team/a", "x") must not share ("team", "a/x")
                # — and prefix each so an EMPTY component still yields a
                # distinct path segment (("", "x") must not share ("x", ""))
                kw["state_provider"] = FileSystemStateProvider(
                    self.state_root,
                    namespace=f"t-{quote(tenant, safe='')}/"
                    f"d-{quote(dataset, safe='')}",
                )
            session = StreamingSession(self, tenant, dataset, checks, **kw)
            self._sessions[key] = session
            return session

    # -- fleet watch ---------------------------------------------------------

    def watch_metrics(
        self,
        tenant: str,
        repository: Any,
        analyzers,
        strategy: Any = None,
        dataset: str = "default",
        tags: Optional[Dict[str, str]] = None,
    ):
        """Register a standing anomaly watch over ``tenant``'s committed
        metric history (see `service.fleetwatch`): on every scheduler
        harvest the fleet watch re-scores every watched series in batched
        ``detect_batch`` calls and surfaces anomalies as
        ``deequ_service_anomaly_*`` export series plus trace-correlated
        flight dumps."""
        return self.fleetwatch.watch(
            tenant, repository, analyzers, strategy=strategy,
            dataset=dataset, tags=tags,
        )

    def get_session(
        self, tenant: str, dataset: str, include_closed: bool = False
    ) -> Optional[StreamingSession]:
        """The LIVE session for (tenant, dataset), or None — a pure
        lookup, never a create (the ingest endpoint resolves targets with
        this so an unknown name is a 404, not a silent zero-check
        session). ``include_closed=True`` also returns a CLOSED session —
        how the endpoint tells "never existed" (404) from "gone" (410)."""
        with self._sessions_lock:
            session = self._sessions.get(session_key(tenant, dataset))
            if session is None or (session.closed and not include_closed):
                return None
            return session

    # -- export plane --------------------------------------------------------

    def prometheus_text(self) -> str:
        return self.metrics.prometheus_text()

    def json_snapshot(self) -> Dict[str, Any]:
        return self.metrics.json_snapshot()

    def start_exporter(
        self, host: str = "127.0.0.1", port: int = 0, ingest: bool = True
    ) -> MetricsExporter:
        """Serve the HTTP plane: ``/metrics`` + ``/trace`` + the unified
        ``/statusz`` ops snapshot, and (with ``ingest=True``, the default)
        the Arrow IPC ingest frontend at ``POST
        /ingest/v1/<tenant>/<dataset>`` bound to this service's streaming
        sessions."""
        if self._exporter is not None:
            if host != self._exporter.host or port not in (
                0, self._exporter.port
            ):
                # silently returning the old binding would leave the
                # operator scraping a port nothing listens on
                raise ValueError(
                    f"metrics exporter already bound to "
                    f"{self._exporter.host}:{self._exporter.port}; cannot "
                    f"rebind to {host}:{port}"
                )
            return self._exporter
        endpoint = None
        if ingest:
            from ..ingest import IngestEndpoint

            endpoint = IngestEndpoint(self)
        self._exporter = MetricsExporter(
            self.metrics, host=host, port=port, ingest=endpoint,
            statusz=self.statusz.snapshot,
        )
        return self._exporter

    # -- lifecycle -----------------------------------------------------------

    def close(self, wait: bool = True, timeout: Optional[float] = None) -> None:
        # drain FIRST: already-admitted folds must complete (shutdown's
        # "workers drain every pending job" contract) — closing sessions
        # beforehand would kill queued pipelined ingests with SessionClosed
        # and silently drop their batches
        self.scheduler.shutdown(wait=wait, timeout=timeout)
        # with wait=False (or an expired timeout) folds may still be
        # queued OR mid-execution on a worker: leave the sessions open so
        # the daemon workers finish folding them — new ingests are already
        # rejected typed at the scheduler (ServiceClosed), so nothing
        # leaks in
        if self.scheduler.idle():
            with self._sessions_lock:
                for session in self._sessions.values():
                    session.close()
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
        if self.fleet is not None:
            self.fleet.close()

    def __enter__(self) -> "VerificationService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
