"""Streaming micro-batch ingestion: long-lived per-(tenant, dataset)
verification sessions.

Schelter et al. (VLDB 2018) frame incremental verification over growing
datasets as the production mode: don't rescan history, fold each arriving
delta into persisted ALGEBRAIC states and recompute metrics from the merge.
A :class:`StreamingSession` is that mode hosted on the TPU engine: every
micro-batch runs one fused pass over the delta with
``aggregate_with=save_states_with=<the session's state provider>`` — the
existing `StateLoader`/`StatePersister` machinery — so after batch N the
persisted states equal a single batch run over the concatenation of batches
1..N, and the session's checks are evaluated against the CUMULATIVE metrics
after every merge: anomalies surface mid-stream, not at end-of-day.

Batches enter through the service scheduler (admission control, deadlines,
retry, cache-aware placement all apply); merges within one session are
serialized by a session lock, so concurrent ingests never interleave their
load-merge-persist cycles.

Schema integrity: the first folded batch captures a
:class:`~deequ_tpu.service.drift.SchemaContract` (column names, value
dtypes, dictionary-encoding) and every later batch validates against it
BEFORE the fold — compatible widenings (int32 arriving where int64 was
promised) are coerced and counted; incompatible drift (column added,
dropped, retyped) raises a typed ``SchemaDriftError`` with the persisted
states untouched, or coerces/degrades per the session's ``drift_policy``.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional, Sequence, Tuple

_logger = logging.getLogger(__name__)

from ..analyzers import Analyzer
from ..analyzers.state_provider import (
    InMemoryStateProvider,
    StateLoader,
    StatePersister,
)
from ..checks import Check, CheckStatus
from ..data import Dataset
from .errors import SessionClosed
from .scheduler import JobContext, Priority


def describe_streaming_series(metrics) -> None:
    """Register HELP text for every export-plane series the streaming
    sessions increment (called once per service). Literal per-series
    ``describe`` calls, not a data-driven loop: the export-plane
    completeness check in tools/statlint matches descriptions statically,
    and an unrolled call per series is what it (and a grepping operator)
    can see."""
    metrics.describe(
        "deequ_service_stream_batches_total",
        "Micro-batches folded into streaming sessions' persisted states.",
    )
    metrics.describe(
        "deequ_service_stream_rows_total",
        "Rows folded into streaming sessions' persisted states.",
    )
    metrics.describe(
        "deequ_service_stream_check_failures_total",
        "Per-fold check evaluations that did not come back SUCCESS, by "
        "status — the mid-stream anomaly signal.",
    )
    metrics.describe(
        "deequ_service_drift_rejections_total",
        "Micro-batches rejected BEFORE folding for incompatible schema "
        "drift (typed SchemaDriftError; persisted states untouched).",
    )
    metrics.describe(
        "deequ_service_drift_coercions_total",
        "Columns coerced to the session contract's dtype on compatible "
        "widenings (int32 arriving where int64 was promised).",
    )
    metrics.describe(
        "deequ_service_drift_repairs_total",
        "Micro-batches coerce-REPAIRED across hard schema drift per the "
        "session's drift policy (the producer's schema changed).",
    )
    metrics.describe(
        "deequ_service_drift_degraded_total",
        "Micro-batches folded with drifted columns degraded to typed "
        "Failure metrics per the session's drift policy.",
    )
    metrics.describe(
        "deequ_service_callback_failures_total",
        "on_result callbacks that raised; the fold had already committed, "
        "so the failure is contained, logged and counted here.",
    )


#: reconfigure()'s "field not passed" sentinel (None is meaningful for
#: row_gate: it means REMOVE the gate)
_UNSET = object()


def _bucket_batch_size(rows: int) -> int:
    """Micro-batch rows -> the next power of two (floor 1024): every jit
    compile is shape-specialized, so folding each arriving batch at its raw
    row count would compile a fresh program per distinct size — padding to
    at most log2 bucket shapes keeps warmth claims honest for streams whose
    batch sizes wander."""
    size = 1024
    while size < rows:
        size *= 2
    return size


def _session_batch_size(rows: int, batch_size) -> int:
    """The fold batch size: caller's choice, else the power-of-two bucket
    CLAMPED to the engine's default — an oversize micro-batch streams as
    ordinary engine-sized batches instead of one giant padded shape."""
    from ..config import DEFAULT_BATCH_SIZE

    return batch_size or min(DEFAULT_BATCH_SIZE, _bucket_batch_size(rows))


class StreamingSession:
    """One tenant's continuously-verified dataset."""

    def __init__(
        self,
        service,
        tenant: str,
        dataset: str,
        checks: Sequence[Check],
        *,
        required_analyzers: Sequence[Analyzer] = (),
        state_provider: Optional[Any] = None,
        priority: Priority = Priority.NORMAL,
        deadline_s: Optional[float] = None,
        max_retries: int = 0,
        batch_size: Optional[int] = None,
        on_result: Optional[Callable[[Any], None]] = None,
        keep_results: int = 256,
        drift_policy: str = "reject",
        admission_block_s: Optional[float] = None,
        row_gate: Optional[Any] = None,
    ):
        # max_retries defaults to 0 because a fold MUTATES persisted state:
        # a transient failure in the middle of a run can leave some
        # analyzers' states already merged, and re-running the fold would
        # double-count the batch. Opt into retries only when the state
        # provider is transactional for a whole fold. (A failure AFTER the
        # fold completed — e.g. an on_result callback — is safe either way:
        # the completed result is memoized per job and never re-folded.)
        if state_provider is not None and not (
            isinstance(state_provider, StateLoader)
            and isinstance(state_provider, StatePersister)
        ):
            raise TypeError(
                "state_provider must be both a StateLoader and a "
                f"StatePersister, got {type(state_provider).__name__}"
            )
        self.service = service
        self.tenant = tenant
        self.dataset = dataset
        self.checks = list(checks)
        self.required_analyzers = list(required_analyzers)
        self.provider = state_provider or InMemoryStateProvider()
        self.priority = priority
        self.deadline_s = deadline_s
        self.max_retries = max_retries
        self.batch_size = batch_size
        self.on_result = on_result
        #: seconds an over-quota ingest WAITS for queue space before the
        #: typed shed (backpressure for streaming producers); None keeps
        #: the scheduler's shed-immediately default
        self.admission_block_s = admission_block_s
        #: optional row-level gate (`deequ_tpu.ingest.rowgate.RowGate`):
        #: every frame is conformance-masked BEFORE the fold, clean rows
        #: fold bit-exact, rejects quarantine typed. Normally installed
        #: from the tenant catalog's ``row_gate`` document section.
        self.row_gate = row_gate
        from .drift import DRIFT_POLICIES

        if drift_policy not in DRIFT_POLICIES:
            raise ValueError(
                f"drift_policy must be one of {DRIFT_POLICIES}, "
                f"got {drift_policy!r}"
            )
        self.drift_policy = drift_policy
        self._serial = threading.Lock()  # orders load-merge-persist cycles
        #: held across [coalescer enqueue -> scheduler submit] so a
        #: session's pending-fold queue order always equals its job
        #: submission order (the FIFO the coalescer's drains rely on);
        #: never held during a fold
        self._submit_order = threading.Lock()
        #: coalesce eligibility plans keyed by (reconfigure epoch, schema
        #: fingerprint); the epoch makes invalidation a read-side key
        #: change instead of a cross-lock dict clear (`_coalesce_plan`
        #: writes under the submit lock, reconfigure() under the serial
        #: lock — they must not share a mutable field)
        self._plans: dict = {}
        self._plans_epoch = 0
        self._closed = False
        self._schema = None
        #: the schema promise captured from the FIRST folded batch; every
        #: later batch validates against it BEFORE the fold so persisted
        #: states are never contaminated by mixed-schema merges. For a
        #: DURABLE (path-backed) provider the contract persists beside the
        #: states, so a session resumed in a new process still validates
        #: against the schema its persisted states were folded under — a
        #: fresh capture from the first post-restart batch would let a
        #: drifted producer contaminate days of state unchallenged
        self._contract = self._load_contract()
        #: drift observability: widenings coerced / batches folded degraded
        #: / batches whose HARD drift the coerce policy repaired
        self.drift_coercions = 0
        self.drift_degraded_batches = 0
        self.drift_repaired_batches = 0
        import itertools

        #: per-SUBMISSION counter for job ids — batches_ingested only moves
        #: when a fold runs, so pipelined ingests (wait=False) would all
        #: report the same batch identity in timeouts/failures
        self._submit_seq = itertools.count()
        self.batches_ingested = 0
        self.rows_ingested = 0
        #: columnar payload bytes folded (wire-equivalent arrow buffer
        #: sizes — what the ingest plane's MB/s numbers are made of)
        self.bytes_ingested = 0
        from collections import deque

        #: the most recent ``keep_results`` batch results — bounded, so a
        #: session ingesting for weeks cannot grow memory per micro-batch
        #: (counts live in batches_ingested / the export plane)
        self.results = deque(maxlen=max(int(keep_results), 1))
        from ..runners.analysis_runner import collect_required_analyzers

        self._analyzers = collect_required_analyzers(
            self.checks, self.required_analyzers
        )

    # -- ingestion -----------------------------------------------------------

    def ingest(
        self,
        data,
        *,
        wait: bool = True,
        timeout: Optional[float] = None,
        deadline_s: Optional[float] = None,
        block_s: Optional[float] = None,
    ):
        """Fold one micro-batch into the session's persisted states and
        evaluate the checks on the merged (cumulative) metrics.

        ``data`` is any columnar payload `deequ_tpu.ingest.as_dataset`
        accepts: a :class:`Dataset`, a pyarrow ``Table``/``RecordBatch``,
        a **dict of numpy arrays** (zero-copy for numeric dtypes — the
        recommended in-process shape; no pandas hop), or a pandas
        DataFrame (the legacy path, which pays the conversion).

        With ``wait=True`` (default) returns the batch's
        ``VerificationResult``; with ``wait=False`` returns the
        :class:`JobHandle` so callers can pipeline batches."""
        if self._closed:
            raise SessionClosed(self.tenant, self.dataset)
        from ..ingest.columnar import as_dataset

        data = as_dataset(data)
        # per-tenant admission budget FIRST (one dict lookup for quota-
        # free tenants): the producer is charged for the WHOLE incoming
        # frame — garbage rows included — before any CPU is spent masking
        # or folding it, so an over-quota flood sheds typed (QuotaExceeded
        # -> 429) at the cheapest possible point
        from ..ingest.columnar import payload_bytes

        self.service.scheduler.charge_quota(
            self.tenant,
            rows=int(data.num_rows),
            nbytes=payload_bytes(data),
            block_s=(
                block_s if block_s is not None else self.admission_block_s
            ),
        )
        if self.row_gate is not None:
            # one vectorized conformance mask per frame BEFORE the fold:
            # clean rows continue bit-exact (arrow filter of the original
            # buffers), rejects quarantine typed; a fully-rejected frame
            # raises FrameQuarantinedError here and nothing folds
            data = self.row_gate.split(data, self.tenant, self.dataset)
        done: dict = {}  # per-job memo: a retried job must never re-fold
        bs = _session_batch_size(int(data.num_rows), self.batch_size)
        effective_deadline = (
            deadline_s if deadline_s is not None else self.deadline_s
        )

        from .placement import make_warm_fn, shape_qualified_signature

        # cross-session coalescing (service.coalesce): an eligible
        # micro-batch fold routes through the coalescer — the tiny-delta
        # host fast path, or a signature-keyed group that a worker stacks
        # into ONE device launch. prepare() returning None (knob off,
        # ineligible battery, multi-batch, mesh) keeps the exact
        # pre-coalescing path below. The submit-order lock makes [enqueue
        # -> submit] atomic per session, so the coalescer's per-session
        # FIFO equals the scheduler's serial-key FIFO; deadline'd folds
        # are never cross-drained (drainable=False), keeping JobTimeout
        # semantics with the fold's own job.
        coalescer = getattr(self.service, "coalescer", None)
        pending = None
        barrier = False
        with self._submit_order:
            if coalescer is not None:
                pending = coalescer.prepare(
                    self, data, bs, drainable=effective_deadline is None
                )
            if pending is not None:
                runner = coalescer.run_fold

                def fold(ctx: JobContext, _p=pending):
                    return runner(ctx, _p)

                if pending.route == "fast":
                    # no device program to warm, no affinity to track —
                    # the empty signature also short-circuits the
                    # scheduler's affinity scan (one less lock round-trip
                    # per pickup on the hot path)
                    signature, warm = (), None
                else:
                    signature = shape_qualified_signature(self._analyzers, bs)
                    if coalescer._fleet_stream_eligible(
                        pending.plan, int(data.num_rows),
                        tenant=self.tenant,
                    ):
                        # the drain will shard this fold over the fleet
                        # sub-mesh (host partials + collectives): there
                        # is no single-chip fused program to warm, and
                        # compiling one in the background would be a
                        # wasted cold XLA compile per (battery, bucket)
                        warm = None
                    else:
                        warm = make_warm_fn(
                            self.service.router, self._analyzers,
                            self.service.mesh, data, bs,
                        )
            else:
                # a SERIAL-path fold raises the session's coalescer
                # barrier: no later drainable fold may be cross-drained
                # ahead of it (per-session FIFO spans both paths); the
                # fold body clears it once, on its first attempt
                barrier = (
                    coalescer.note_serial_fold(self)
                    if coalescer is not None else False
                )
                skey = (self.tenant, self.dataset)

                def fold(ctx: JobContext):
                    try:
                        return self._fold_batch(ctx, data, done, bs)
                    finally:
                        if barrier and "barrier_cleared" not in done:
                            done["barrier_cleared"] = True
                            coalescer.clear_serial_barrier(skey)

                # under the fleet, a LARGE serial delta shards over the
                # tenant's sub-mesh (the job leases it via mesh_tenant);
                # warmth keys carry the slice shape so a re-packed
                # tenant's battery reads cold at its new mesh shape
                serial_mesh = self._fold_mesh_hint(int(data.num_rows))
                signature = shape_qualified_signature(
                    self._analyzers, bs, serial_mesh
                )
                warm = make_warm_fn(
                    self.service.router, self._analyzers,
                    serial_mesh if serial_mesh is not None
                    else self.service.mesh,
                    data, bs,
                )
            try:
                handle = self.service.scheduler.submit(
                    fold,
                    tenant=self.tenant,
                    priority=self.priority,
                    deadline_s=effective_deadline,
                    max_retries=self.max_retries,
                    # per-shape warmth: the bucketed batch size is part of
                    # the key
                    signature=signature,
                    job_id=(
                        f"{self.tenant}/{self.dataset}"
                        f"#{next(self._submit_seq)}"
                    ),
                    warm_fn=warm,
                    # scheduler-level serialization: one fold at a time per
                    # session, in submission order — pipelined ingests
                    # occupy ONE worker and cannot fold out of order
                    # (per-batch anomaly attribution)
                    serial_key=(self.tenant, self.dataset),
                    # backpressure: wait for queue space up to block_s
                    # before the typed shed (per-call override, else the
                    # session default)
                    block_s=(
                        block_s if block_s is not None
                        else self.admission_block_s
                    ),
                    # while a drain sweeps this fold's coalesce key, the
                    # job stays queued for bulk absorption instead of
                    # being picked (scheduler._eligible)
                    defer_key=(
                        pending.key
                        if pending is not None and pending.drainable
                        else None
                    ),
                    # SERIAL-path folds over fleet-sized deltas lease the
                    # tenant's sub-mesh per attempt (coalesced folds lease
                    # inside their drain instead); the ONE hint computed
                    # above keeps the warmth key and the lease opt-in
                    # agreeing even across a concurrent re-pack
                    mesh_tenant=(
                        self.tenant
                        if pending is None and serial_mesh is not None
                        else None
                    ),
                    # commit/job-finish atomicity: a job that dies OUTSIDE
                    # the fold body (worker fault, deadline kill in queue)
                    # reconciles with the coalescer — adopting a drain's
                    # committed result, or withdrawing the unclaimed fold
                    # so no later drain can commit it after the failure
                    recover_fn=(
                        (lambda ctx, exc, _p=pending:
                         coalescer.reconcile_orphan(ctx, _p, exc))
                        if pending is not None else None
                    ),
                )
            except BaseException:
                if pending is not None:
                    # shed/closed before admission: the fold must not
                    # linger claimable in the coalescer
                    coalescer.abandon(pending)
                elif barrier:
                    # a shed serial fold never runs its body: release the
                    # barrier it raised
                    coalescer.clear_serial_barrier(
                        (self.tenant, self.dataset)
                    )
                raise
            if pending is not None:
                coalescer.mark_submitted(pending, handle, signature)
        if wait:
            from .errors import JobFailed, JobTimeout

            try:
                return handle.result(timeout)
            except JobTimeout:
                if handle.late_value is not None:
                    # the fold COMPLETED late: the batch is already merged
                    # into the persisted states — hand back the committed
                    # result rather than baiting a double-counting retry
                    return handle.late_value
                raise
            except JobFailed as exc:
                from ..exceptions import SchemaDriftError

                if isinstance(exc.__cause__, SchemaDriftError):
                    # surface the drift contract directly: the caller's
                    # remedy (fix the producer, change drift_policy) has
                    # nothing to do with job plumbing
                    raise exc.__cause__
                raise
        return handle

    def _fold_mesh_hint(self, rows: int):
        """The mesh this session's SERIAL fold of ``rows`` rows would
        shard over: the service's explicit mesh when one exists, else the
        tenant's fleet slice for fleet-sized deltas (a lease-shaped peek
        — the job's attempt leases the real thing), else None (single
        chip). Drives both the warmth key and the mesh_tenant opt-in."""
        svc = self.service
        if svc.mesh is not None:
            return svc.mesh
        fleet = getattr(svc, "fleet", None)
        if fleet is None:
            return None
        from .fleet import fleet_stream_min_rows

        if rows < fleet_stream_min_rows():
            return None
        lease = fleet.peek(self.tenant)
        return lease if lease.n_dev >= 2 else None

    def _fold_batch(
        self, ctx: JobContext, data: Dataset, done: dict, batch_size: int
    ):
        from ..verification import VerificationSuite

        if "result" in done:
            # this job already folded the batch on an earlier attempt —
            # re-folding would merge the batch into the persisted states a
            # second time; hand back the memoized committed result
            return self._notify(done)
        from ..reliability.faults import fault_point

        # chaos site: fails a fold BEFORE any state mutates, so retry
        # semantics stay exercisable without double-count hazards
        fault_point("stream_fold", tag=ctx.job_id)
        with self._serial:
            if self._closed:
                raise SessionClosed(self.tenant, self.dataset)
            data, pending_contract, _degraded = self._pre_fold(data)
            result = VerificationSuite.do_verification_run(
                data,
                self.checks,
                self.required_analyzers,
                aggregate_with=self.provider,
                save_states_with=self.provider,
                batch_size=batch_size,
                monitor=ctx.monitor,
                # the attempt's fleet lease (ctx.mesh) when one was
                # granted, else the service's explicit mesh, else single
                # chip — exactly the order _fold_mesh_hint promised the
                # warmth key
                sharding=(
                    ctx.mesh if ctx.mesh is not None else self.service.mesh
                ),
                placement=ctx.placement,
            )
            self._commit_fold(result, data, pending_contract, done)
        return self._notify(done)

    def _pre_fold(self, data: Dataset):
        """Under ``self._serial``: the schema-contract half of a fold.
        Returns ``(data_to_fold, pending_contract, guard_degraded)`` —
        ``pending_contract`` is non-None only for the session's FIRST fold
        (committed by `_commit_fold` after the fold succeeds), and
        ``guard_degraded`` flags a degrade-policy guard outcome (columns
        excluded) that only the full runner's per-analyzer degradation can
        honor. Raises typed ``SchemaDriftError`` with states untouched."""
        if self._contract is None:
            # the contract COMMITS only after this batch's fold
            # succeeds: a first batch whose fold raises never folded,
            # so its schema must not pin the session (a wrong-schema
            # first batch would otherwise reject every corrected
            # batch after it until an operator deleted the contract)
            from .drift import SchemaContract

            return data, SchemaContract.capture(data), False
        degraded_before = self.drift_degraded_batches
        data = self._guard_schema(data)
        return data, None, self.drift_degraded_batches != degraded_before

    def _commit_fold(self, result, data: Dataset, pending_contract, done: dict):
        """Under ``self._serial``: one successful fold's bookkeeping —
        contract commit, counters, bounded results ring, export-plane
        series. Shared verbatim between the serial path and the
        coalescer's fast/device folds so the two can never drift."""
        done["result"] = result
        if pending_contract is not None:
            self._contract = pending_contract
            self._store_contract()
        self._schema = self._schema or data.schema
        self.batches_ingested += 1
        self.rows_ingested += int(data.num_rows)
        from ..ingest.columnar import payload_bytes

        self.bytes_ingested += payload_bytes(data)
        self.results.append(result)
        metrics = self.service.metrics
        metrics.inc_many([
            ("deequ_service_stream_batches_total", 1.0,
             {"tenant": self.tenant, "dataset": self.dataset}),
            ("deequ_service_stream_rows_total", float(data.num_rows),
             {"tenant": self.tenant, "dataset": self.dataset}),
        ])
        if result.status != CheckStatus.SUCCESS:
            # the mid-stream anomaly signal: a failing merge is visible
            # on the export plane the moment it happens
            metrics.inc(
                "deequ_service_stream_check_failures_total",
                tenant=self.tenant, dataset=self.dataset,
                status=result.status.value,
            )

    def _coalesce_plan(self, data: Dataset):
        """The session's coalesce eligibility plan for this schema
        (``None`` = serial path). Per-session memo over the coalescer's
        SHARED plan cache — same-battery fleets build one plan total."""
        schema = data.schema
        # the epoch is read FIRST: a concurrent reconfigure() swaps the
        # analyzer battery before bumping it, so a plan memoized under
        # the new epoch was provably built from the new battery (a plan
        # built mid-swap lands under the old epoch and is never read)
        fp = tuple((c.name, c.kind) for c in schema.columns)
        key = (self._plans_epoch, fp)
        if key not in self._plans:
            # the shared cache keys by (battery, schema) — the epoch is a
            # session-local memo concern only
            self._plans[key] = self.service.coalescer.plan_for(
                self._analyzers, schema, fp
            )
        return self._plans[key]

    def _guard_schema(self, data: Dataset) -> Dataset:
        """The drift guard, run under the serial lock BEFORE anything
        mutates; the contract itself is captured (and committed only
        after a successful fold) in ``_fold_batch``. Raises typed
        ``SchemaDriftError`` (policy ``reject``, or an un-coercible
        batch) with persisted states untouched; returns the (possibly
        repaired) dataset to fold otherwise."""
        from ..exceptions import SchemaDriftError
        from ..observability import record_failure
        from ..observability import trace as _trace

        metrics = self.service.metrics
        try:
            report = self._contract.validate(
                data,
                policy=self.drift_policy,
                session=f"{self.tenant}/{self.dataset}",
            )
        except SchemaDriftError as exc:
            # a rejected batch is a typed failure an operator will want the
            # trace for: event + flight-recorder dump, then the existing
            # counter bump and raise
            record_failure(exc)
            metrics.inc(
                "deequ_service_drift_rejections_total",
                tenant=self.tenant, dataset=self.dataset,
            )
            raise
        if report.coercions:
            self.drift_coercions += len(report.coercions)
            _trace.add_event(
                "drift_coerced", columns=len(report.coercions),
                session=f"{self.tenant}/{self.dataset}",
            )
            metrics.inc(
                "deequ_service_drift_coercions_total",
                float(len(report.coercions)),
                tenant=self.tenant, dataset=self.dataset,
            )
        if report.repaired:
            self.drift_repaired_batches += 1
            _trace.add_event(
                "drift_repaired", repaired=list(report.repaired)[:8],
                session=f"{self.tenant}/{self.dataset}",
            )
            metrics.inc(
                "deequ_service_drift_repairs_total",
                tenant=self.tenant, dataset=self.dataset,
            )
            _logger.warning(
                "session %s/%s coerce-repaired hard schema drift before "
                "folding: %s — the producer's schema changed",
                self.tenant, self.dataset, report.repaired,
            )
        if report.degraded:
            self.drift_degraded_batches += 1
            _trace.add_event(
                "drift_degraded", columns=list(report.degraded)[:8],
                session=f"{self.tenant}/{self.dataset}",
            )
            metrics.inc(
                "deequ_service_drift_degraded_total",
                tenant=self.tenant, dataset=self.dataset,
            )
            _logger.warning(
                "session %s/%s folding batch with %d drifted column(s) "
                "degraded per policy: %s",
                self.tenant, self.dataset, len(report.degraded),
                report.degraded,
            )
        if report.table is None:
            return data
        return Dataset.from_arrow(report.table)

    # -- contract persistence ------------------------------------------------

    _CONTRACT_FILENAME = "schema-contract.json"

    def _contract_path(self):
        path = getattr(self.provider, "path", None)
        if path is None:
            return None
        from .. import io as dio

        return dio.join(path, self._CONTRACT_FILENAME)

    def _load_contract(self):
        path = self._contract_path()
        if path is None:
            return None
        import json

        from .. import io as dio
        from .drift import ColumnContract, SchemaContract

        if not dio.exists(path):
            return None
        try:
            with dio.open_file(path, "r") as fh:
                d = json.load(fh)
            from ..integrity import verify_json_checksum

            verify_json_checksum(
                {k: v for k, v in d.items() if k != "checksum"},
                d.get("checksum", ""), "schema contract", path,
            )
            return SchemaContract(
                tuple(ColumnContract(**c) for c in d["columns"])
            )
        except Exception:  # noqa: BLE001 - recapture beats refusing folds
            _logger.warning(
                "schema contract at %s is unreadable or corrupt; "
                "re-capturing from the next folded batch", path,
                exc_info=True,
            )
            return None

    def _store_contract(self, path: Optional[str] = None) -> None:
        path = path if path is not None else self._contract_path()
        if path is None or self._contract is None:
            return
        import json

        from .. import io as dio
        from ..integrity import checksum_json

        d = {
            "columns": [
                {"name": c.name, "dtype": c.dtype, "dictionary": c.dictionary}
                for c in self._contract.columns
            ]
        }
        d["checksum"] = checksum_json(d)
        try:
            dio.write_text_atomic(path, json.dumps(d))
        except Exception:  # noqa: BLE001 - durability is best-effort;
            # the in-process contract still guards every fold
            _logger.warning(
                "could not persist schema contract to %s", path, exc_info=True
            )

    def _notify(self, done: dict):
        """Deliver on_result at most once per fold, CONTAINED: by the time
        the callback runs, the batch is already merged into the persisted
        states — failing the job for a callback error would discard a
        committed result and bait the caller into a double-counting
        re-ingest. Callback failures are logged and counted instead."""
        result = done["result"]
        if self.on_result is not None and "notified" not in done:
            done["notified"] = True
            try:
                self.on_result(result)
            except Exception:  # noqa: BLE001 - advisory delivery
                _logger.warning(
                    "on_result callback failed for session %s/%s",
                    self.tenant, self.dataset, exc_info=True,
                )
                self.service.metrics.inc(
                    "deequ_service_callback_failures_total",
                    tenant=self.tenant, dataset=self.dataset,
                )
        return result

    # -- hot reconfiguration -------------------------------------------------

    def reconfigure(
        self,
        *,
        checks=None,
        drift_policy: Optional[str] = None,
        priority: Optional[Priority] = None,
        row_gate: Any = _UNSET,
    ) -> None:
        """Swap the session's declarative surface IN PLACE at a fold
        boundary — the hot-reload primitive the tenant catalog's
        :class:`~deequ_tpu.service.catalog.CatalogPlane` drives: a catalog
        edit re-materializes checks, drift policy, priority and row gate
        on the live session without a restart, and without touching the
        persisted algebraic states (analyzers shared between the old and
        new check set keep their cumulative history; newly-required
        analyzers start folding from their next batch).

        Serialized against folds by the session lock, so every fold runs
        under exactly ONE configuration — never a half-swapped one. Fields
        left at their defaults are untouched (``row_gate`` uses a sentinel
        so passing ``None`` explicitly REMOVES the gate)."""
        with self._serial:
            if checks is not None:
                self.checks = list(checks)
                from ..runners.analysis_runner import (
                    collect_required_analyzers,
                )

                self._analyzers = collect_required_analyzers(
                    self.checks, self.required_analyzers
                )
                # coalesce plans key off the analyzer battery: stale
                # plans would drain folds with the OLD battery's program.
                # Invalidate by epoch (the memo key carries it) — the
                # plans dict itself belongs to the submit lock
                self._plans_epoch += 1
            if drift_policy is not None:
                from .drift import DRIFT_POLICIES

                if drift_policy not in DRIFT_POLICIES:
                    raise ValueError(
                        f"drift_policy must be one of {DRIFT_POLICIES}, "
                        f"got {drift_policy!r}"
                    )
                self.drift_policy = drift_policy
            if priority is not None:
                self.priority = priority
            if row_gate is not _UNSET:
                self.row_gate = row_gate

    # -- state-only queries --------------------------------------------------

    def current(self):
        """Re-evaluate the session's checks from the persisted states alone
        — no data pass (the `run_on_aggregated_states` mode). Requires at
        least one ingested batch (the schema comes from it)."""
        from ..verification import VerificationSuite

        with self._serial:
            if self._schema is None:
                raise ValueError(
                    f"session {self.tenant}/{self.dataset} has no ingested "
                    "batches yet"
                )
            return VerificationSuite.run_on_aggregated_states(
                self._schema,
                self.checks,
                [self.provider],
                required_analyzers=self.required_analyzers,
            )

    @property
    def latest(self):
        """The most recent batch's VerificationResult (None before any)."""
        return self.results[-1] if self.results else None

    @property
    def closed(self) -> bool:
        return self._closed

    def flush_to_partition(
        self, store=None, partition: Optional[str] = None
    ) -> Optional[str]:
        """Flush the session's cumulative algebraic states into a
        partition store as ONE partition of ``self.dataset`` — the bridge
        from the streaming plane to incremental verification: a finished
        ingestion window becomes a reusable partition, and moving the
        session to another host (ROADMAP item 3) is a flush + re-open,
        not a re-scan. Returns the partition name (None when the session
        never folded a batch).

        Called under the serial lock by :meth:`close` when the service
        has a partition store; callable explicitly mid-life too (each
        flush overwrites the session's partition with the newest
        cumulative states and a version token derived from the fold
        counters)."""
        with self._serial:
            return self._flush_to_partition_locked(store, partition)

    def _flush_to_partition_locked(self, store=None, partition=None):
        store = store if store is not None else getattr(
            self.service, "partition_store", None
        )
        if store is None or self.batches_ingested == 0 or self._schema is None:
            return None
        from ..integrity import checksum_json
        from ..observability import trace as _trace
        from ..runners.incremental import analyzer_key, contract_fingerprint

        name = partition or f"session-{self.tenant}"
        keys = []
        provider = store.provider(self.dataset, name)
        store.invalidate(self.dataset, name)
        for a in self._analyzers:
            state = self.provider.load(a)
            if state is None:
                continue
            provider.persist(a, state)
            keys.append(analyzer_key(a))
        # MIGRATE the schema contract alongside the states: a session
        # re-opened on another host against this partition's provider
        # loads the same checksummed contract in __init__, so drift
        # policies fire identically pre- and post-migration — without
        # this, the re-opened session would recapture its contract from
        # the first batch the NEW host sees, and a producer that drifted
        # in the gap would contaminate the migrated states unchallenged.
        contract_path = getattr(provider, "path", None)
        if contract_path is not None:
            from .. import io as dio

            self._store_contract(
                dio.join(contract_path, self._CONTRACT_FILENAME)
            )
        store.commit(
            self.dataset, name,
            fingerprint=contract_fingerprint(self._schema),
            # the version token: a deterministic digest of the fold
            # counters — a re-flush after more folds reads as changed
            content_checksum=checksum_json({
                "batches": self.batches_ingested,
                "rows": self.rows_ingested,
                "bytes": self.bytes_ingested,
            }),
            num_rows=self.rows_ingested,
            analyzer_keys=keys,
            schema=[
                (c.name, c.kind.value) for c in self._schema.columns
            ],
        )
        _trace.add_event(
            "session_flushed_to_partition", dataset=self.dataset,
            partition=name, rows=self.rows_ingested,
        )
        return name

    def close(self) -> None:
        with self._serial:
            if self._closed:
                return
            self._closed = True
            # a session backed by a service-level partition store flushes
            # its cumulative states as a partition on close: the window
            # it verified becomes reusable input for incremental runs
            # (best-effort — closing must never fail on a full disk)
            try:
                self._flush_to_partition_locked()
            except Exception:  # noqa: BLE001 - flush is an optimization
                _logger.warning(
                    "could not flush session %s/%s states to the "
                    "partition store", self.tenant, self.dataset,
                    exc_info=True,
                )


def session_key(tenant: str, dataset: str) -> Tuple[str, str]:
    return (str(tenant), str(dataset))
