"""Fleet watch: the service's standing data-quality observability job
(ROADMAP item 5's finish line).

One-shot anomaly checks score one series at a time, on demand. A service
hosting fleets of tenants (the PR 12/13 planes) wants the CONTINUOUS
shape instead: every time the scheduler harvests a finished job — i.e.
every time a tenant may have committed fresh metrics — the fleet watch
re-scores every watched tenant's metric history, batched: all series
assemble into one padded ``[N, T]`` tensor per strategy bundle and score
through ONE ``detect_batch`` call (the PR 10 OnlineNormal shape, now
carried by every strategy incl. Holt-Winters), with per-series
newest-point search intervals so a ragged fleet's freshest points are the
ones judged.

Results land on the export plane as ``deequ_service_anomaly_*`` series
(scored / flagged / quarantined per tenant, scoring wall time), and every
FLAGGED anomaly schedules a flight-recorder dump correlated to the
harvesting job's trace — the 3am operator opens the dump and sees which
tenant, which analyzer, which value, inside the job tree that triggered
the scoring.

Poisoned histories degrade, never spread: a tenant whose repository
quarantined payloads during the load (bit rot, torn writes, or the
injected ``corrupt`` fault kind at the ``repository_load`` site) is
counted quarantined and scored on whatever entries survived; the other
tenants' scores are untouched (the chaos soak's ``fleetwatch_drill`` pins
it).

Knobs (config.py; shared warn-once parsers):

- ``DEEQU_TPU_FLEETWATCH``: "0" detaches the watch from the scheduler's
  harvests (explicit ``harvest_now()`` still works).
- ``DEEQU_TPU_FLEETWATCH_WINDOW_MONTHS``: history window scored per
  harvest, in month buckets (default 12; 0 = unbounded) — rides the
  partitioned repository's O(queried window) loads.
- ``DEEQU_TPU_FLEETWATCH_BUNDLE``: max series per ``detect_batch`` call
  (default 16384) — one harvest of 10k series is one call.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..observability import trace as _trace
from ..observability.recorder import recorder

FLEETWATCH_ENV = "DEEQU_TPU_FLEETWATCH"
FLEETWATCH_WINDOW_ENV = "DEEQU_TPU_FLEETWATCH_WINDOW_MONTHS"
FLEETWATCH_BUNDLE_ENV = "DEEQU_TPU_FLEETWATCH_BUNDLE"

#: the tenant name the watch's own scheduler jobs run under (never
#: watched, so a harvest of the watch job cannot re-trigger itself)
WATCH_TENANT = "__fleetwatch__"

#: minimum points a series needs before its newest point can be judged
#: against any history at all
_MIN_POINTS = 2


def fleetwatch_enabled() -> bool:
    from ..utils import env_flag

    return env_flag(FLEETWATCH_ENV, True)


def fleetwatch_window_months() -> int:
    from ..utils import env_number

    return env_number(FLEETWATCH_WINDOW_ENV, 12, int, minimum=0)


def fleetwatch_bundle_size() -> int:
    from ..utils import env_number

    return env_number(FLEETWATCH_BUNDLE_ENV, 16384, int, minimum=1)


def window_after_ms(months: int, now_ms: Optional[int] = None) -> Optional[int]:
    """The inclusive ``after`` bound covering the most recent ``months``
    month buckets (None = unbounded): the first millisecond of the month
    ``months - 1`` buckets back, so the current partial month always
    counts as one bucket — the same bucket arithmetic the partitioned
    repository lists by."""
    if months <= 0:
        return None
    from datetime import datetime, timezone

    now = (
        datetime.now(timezone.utc) if now_ms is None
        else datetime.fromtimestamp(now_ms / 1000.0, tz=timezone.utc)
    )
    total = now.year * 12 + (now.month - 1) - (months - 1)
    start = datetime(total // 12, total % 12 + 1, 1, tzinfo=timezone.utc)
    return int(start.timestamp() * 1000)


@dataclass(frozen=True)
class WatchSpec:
    """One tenant's standing watch: which repository holds its committed
    metric history, which analyzers' series to score, with which
    strategy."""

    tenant: str
    dataset: str
    repository: Any
    analyzers: Tuple[Any, ...]
    strategy: Any
    tags: Optional[Tuple[Tuple[str, str], ...]] = None


@dataclass
class HarvestReport:
    """What one fleet-watch scoring pass did (also the chaos drill's
    verdict input)."""

    tenants: int = 0
    series_scored: int = 0
    series_skipped: int = 0
    detect_calls: int = 0
    scoring_seconds: float = 0.0
    #: (tenant, dataset, analyzer repr, point index, value, detail)
    flagged: List[Tuple[str, str, str, int, Optional[float], str]] = field(
        default_factory=list
    )
    quarantined_tenants: List[str] = field(default_factory=list)


class FleetWatch:
    """The standing watch. Construct with a ``VerificationService`` (or
    anything exposing ``.scheduler`` and ``.metrics``); register tenants
    with :meth:`watch`; :meth:`attach` hooks scheduler harvests so every
    completed job re-scores the fleet. ``harvest_now()`` scores inline —
    tests, drills and cron-style callers use it directly."""

    def __init__(self, service: Any):
        self._service = service
        self.metrics = service.metrics
        self._lock = threading.Lock()
        self._watches: Dict[Tuple[str, str], WatchSpec] = {}
        self._job_pending = False
        self._attached = False
        #: fingerprints of anomalies already dumped/counted: a STANDING
        #: anomaly (same tenant/analyzer/point/value re-flagged every
        #: harvest) stays in each HarvestReport but exports ONE
        #: flagged-counter bump and ONE flight dump — re-dumping per
        #: harvest would exhaust the recorder's process-wide dump budget
        #: in minutes and suppress genuine failure dumps
        self._seen_flags: set = set()
        #: (tenant, dataset) watches currently inside a STANDING
        #: quarantine episode: the exported quarantined counter and the
        #: typed flight record fire once per episode, not once per
        #: harvest (a corrupt entry re-quarantines on every load until it
        #: heals; the mark clears on the first clean load so a LATER
        #: corruption counts anew)
        self._quarantine_marks: set = set()
        #: cached per-series model fits for strategies exposing
        #: ``fit_batch`` (Holt-Winters): the L-BFGS-B optimization is the
        #: dominant serial cost and its inputs (the training slice) only
        #: change when a tenant commits a new point — re-fitting an
        #: unchanged history every harvest would re-pay it per job
        #: completion. Keyed by (watch, analyzer, training fingerprint);
        #: bounded like _seen_flags.
        self._fit_cache: Dict[Any, Any] = {}
        self.last_report: Optional[HarvestReport] = None
        self.metrics.describe(
            "deequ_service_anomaly_series_scored_total",
            "Metric series (tenant x analyzer) scored by the fleet watch's "
            "batched anomaly pass, per tenant.",
        )
        self.metrics.describe(
            "deequ_service_anomaly_flagged_total",
            "Anomalous newest points the fleet watch flagged, per tenant "
            "(each also schedules a trace-correlated flight dump).",
        )
        self.metrics.describe(
            "deequ_service_anomaly_quarantined_total",
            "Tenants whose metric history quarantined corrupt payloads "
            "during a fleet-watch load (scored on the surviving entries).",
        )
        self.metrics.describe(
            "deequ_service_anomaly_harvests_total",
            "Fleet-watch scoring passes completed.",
        )
        self.metrics.describe(
            "deequ_service_anomaly_scoring_seconds_total",
            "Wall clock spent inside batched detect_batch scoring calls "
            "across fleet-watch harvests.",
        )
        self.metrics.set_gauge_fn(
            "deequ_service_anomaly_watched_series",
            self._watched_series,
            "Metric series (tenant x analyzer) under standing fleet-watch "
            "scoring.",
        )
        from .metrics import SloEvaluator

        #: latency objectives fed from the service histograms; burn rates
        #: surface as deequ_service_slo_burn_rate{slo=...} gauges beside
        #: the anomaly series (the fleet watch IS the alerting plane)
        self.slo = SloEvaluator(self.metrics)
        self.metrics.describe(
            "deequ_service_slo_burn_rate",
            "Error-budget burn rate per latency objective over its "
            "window: (1 - achieved fraction) / (1 - objective), from the "
            "service latency histogram buckets. 1 = burning exactly at "
            "budget; >1 = objective missed if the window persists.",
        )
        self.watch_slo(
            "fold_latency", "deequ_service_fold_latency_seconds",
            threshold_s=2.0, objective=0.99,
        )
        self.watch_slo(
            "admission_wait", "deequ_service_admission_wait_seconds",
            threshold_s=0.5, objective=0.99,
        )

    def _watched_series(self) -> int:
        with self._lock:
            return sum(len(w.analyzers) for w in self._watches.values())

    def watch_slo(
        self,
        slug: str,
        histogram: str,
        threshold_s: float,
        objective: float = 0.99,
        window_s: float = 300.0,
        **labels: str,
    ) -> None:
        """Register a latency objective over ``histogram`` (optionally
        filtered to one tenant/priority via ``labels``) and surface its
        burn rate as a ``deequ_service_slo_burn_rate{slo=...}`` gauge."""
        self.slo.add_objective(
            slug, histogram, threshold_s, objective, window_s, **labels
        )
        self.metrics.set_gauge_fn(
            "deequ_service_slo_burn_rate",
            lambda slug=slug: self.slo.burn_rate(slug),
            None, slo=slug,
        )

    def statusz_section(self) -> Dict[str, Any]:
        """The fleetwatch plane of the /statusz document."""
        with self._lock:
            quarantined = sorted(
                f"{tenant}/{dataset}"
                for tenant, dataset in self._quarantine_marks
            )
            watches = len(self._watches)
        return {
            "quarantined_sessions": quarantined,
            "watched_series": self._watched_series(),
            "watches": watches,
            "slo_burn_rates": self.slo.burn_rates(),
        }

    # -- registration --------------------------------------------------------

    def watch(
        self,
        tenant: str,
        repository: Any,
        analyzers: Sequence[Any],
        strategy: Any = None,
        dataset: str = "default",
        tags: Optional[Dict[str, str]] = None,
    ) -> WatchSpec:
        """Register (or replace) the standing watch for ``(tenant,
        dataset)``. ``strategy`` defaults to a 3-sigma
        ``OnlineNormalStrategy`` — the reference's continuous-monitoring
        default."""
        if strategy is None:
            from ..anomalydetection import OnlineNormalStrategy

            strategy = OnlineNormalStrategy()
        spec = WatchSpec(
            tenant=str(tenant),
            dataset=str(dataset),
            repository=repository,
            analyzers=tuple(analyzers),
            strategy=strategy,
            tags=tuple(sorted(tags.items())) if tags else None,
        )
        with self._lock:
            if (spec.tenant, spec.dataset) in self._watches:
                # re-registration replaces the watch wholesale: drop the
                # old strategy's cached fits too
                self._drop_watch_state_locked(spec.tenant, spec.dataset)
            self._watches[(spec.tenant, spec.dataset)] = spec
        return spec

    def unwatch(self, tenant: str, dataset: str = "default") -> bool:
        with self._lock:
            self._drop_watch_state_locked(str(tenant), str(dataset))
            return self._watches.pop((str(tenant), str(dataset)), None) is not None

    def _drop_watch_state_locked(self, tenant: str, dataset: str) -> None:
        """Purge a watch's cached fits and episode marks (callers hold
        the lock): a dead or re-registered watch must not retain fits its
        replacement could alias, nor an open quarantine episode."""
        self._fit_cache = {
            k: v for k, v in self._fit_cache.items()
            if not (k[0] == tenant and k[1] == dataset)
        }
        self._quarantine_marks.discard((tenant, dataset))

    # -- scheduler coupling --------------------------------------------------

    def attach(self) -> None:
        """Hook scheduler harvests: every completed job belonging to a
        WATCHED tenant marks the fleet dirty and (if none is pending)
        schedules one standing scoring job. Idempotent; a no-op when
        ``DEEQU_TPU_FLEETWATCH=0``."""
        if not fleetwatch_enabled():
            return
        with self._lock:
            if self._attached:
                return
            self._attached = True
        self._service.scheduler.add_harvest_listener(self._on_harvest)

    def _on_harvest(self, tenant: str) -> None:
        with self._lock:
            if not any(t == tenant for t, _ in self._watches):
                return
            # debounce to ONE in-flight scoring job; a harvest arriving
            # while one runs schedules the next pass the moment
            # _job_pending clears (the pass scores the WHOLE fleet — the
            # standing-watch contract — so there is no per-tenant backlog
            # to track)
            if self._job_pending:
                return
            self._job_pending = True
        try:
            self._service.scheduler.submit(
                self._run_harvest_job,
                tenant=WATCH_TENANT,
                priority=_low_priority(),
                max_retries=0,
                serial_key=WATCH_TENANT,
                job_id=f"fleetwatch-{int(time.time() * 1000)}",
                # liveness: if the job terminates WITHOUT its body running
                # (an injected worker fault between pickup and fn, a
                # raising router) the pending flag must still clear, or
                # the standing watch would be dead until process restart
                recover_fn=self._recover_harvest_job,
            )
        except Exception:  # noqa: BLE001 - a full queue (or shutdown)
            # must not take the triggering job's harvest down with it; the
            # next harvest re-schedules
            with self._lock:
                self._job_pending = False

    def _recover_harvest_job(self, ctx, exc):
        with self._lock:
            self._job_pending = False
        return None  # nothing to adopt; the job fails normally

    def _run_harvest_job(self, ctx) -> HarvestReport:
        with self._lock:
            self._job_pending = False
        return self.harvest_now()

    # -- scoring -------------------------------------------------------------

    def harvest_now(self) -> HarvestReport:
        """Score every watched tenant's windowed metric history NOW: one
        padded series tensor and ONE ``detect_batch`` call per strategy
        bundle (chunked only past ``DEEQU_TPU_FLEETWATCH_BUNDLE``
        series), newest point judged per series. Returns the
        :class:`HarvestReport`; counters land on the export plane and
        every flagged anomaly schedules a flight dump on the current
        trace (the harvesting job's, when scheduled)."""
        report = HarvestReport()
        with self._lock:
            watches = list(self._watches.values())
        after_ms = window_after_ms(fleetwatch_window_months())
        with _trace.span(
            "fleetwatch:harvest", kind="fleetwatch", watches=len(watches)
        ) as sp:
            # 1. gather: every watched (tenant, analyzer) series, with its
            # ragged newest-point interval
            series_values: List[List[float]] = []
            #: (spec, analyzer, point timestamps) per assembled series
            series_meta: List[Tuple[WatchSpec, Any, list]] = []
            bundles: Dict[Any, List[int]] = {}
            quarantined: set = set()
            for spec in watches:
                # attribution is PER REPOSITORY INSTANCE: a concurrent
                # quarantine elsewhere in the process (another tenant's
                # store, a partition-state blob) must never read as THIS
                # tenant's history rotting
                before = getattr(spec.repository, "quarantines", 0)
                try:
                    histories = self._load_history(spec, after_ms)
                except Exception as exc:  # noqa: BLE001 - one tenant's
                    # unreadable history must not starve the fleet: count
                    # it quarantined-typed and keep scoring the others
                    self._quarantine_tenant(spec, exc, report, quarantined)
                    continue
                if getattr(spec.repository, "quarantines", 0) > before:
                    from ..exceptions import CorruptStateError

                    self._quarantine_tenant(
                        spec,
                        CorruptStateError(
                            "metrics history", repr(spec.repository),
                            "payloads quarantined during fleet-watch load",
                        ),
                        report, quarantined,
                    )
                else:
                    # a clean load closes any standing quarantine
                    # episode: the NEXT corruption counts/dumps anew
                    with self._lock:
                        self._quarantine_marks.discard(
                            (spec.tenant, spec.dataset)
                        )
                for analyzer, values, times in histories:
                    if len(values) < _MIN_POINTS:
                        report.series_skipped += 1
                        continue
                    # Holt-Winters' two-full-cycles rule, applied BEFORE
                    # bundling: one too-young tenant must not degrade its
                    # whole bundle to per-series calls (the _detect
                    # fallback) every harvest
                    m = getattr(spec.strategy, "series_periodicity", None)
                    if m is not None and len(values) - 1 < 2 * m:
                        report.series_skipped += 1
                        continue
                    bundles.setdefault(spec.strategy, []).append(
                        len(series_values)
                    )
                    series_values.append(values)
                    series_meta.append((spec, analyzer, times))
            # 2. score: ONE batched call per strategy bundle (chunked only
            # past the bundle-size cap)
            bundle_cap = fleetwatch_bundle_size()
            flagged_updates: List[Tuple[str, float, Dict[str, str]]] = []
            scored_by_tenant: Dict[str, int] = {}
            for strategy, indices in bundles.items():
                for lo in range(0, len(indices), bundle_cap):
                    chunk = indices[lo:lo + bundle_cap]
                    values = [series_values[i] for i in chunk]
                    intervals = [(len(v) - 1, len(v)) for v in values]
                    params = self._cached_fits(
                        strategy, chunk, values, intervals, series_meta
                    )
                    t0 = time.perf_counter()
                    results, calls = self._detect(
                        strategy, values, intervals, params
                    )
                    report.scoring_seconds += time.perf_counter() - t0
                    report.detect_calls += calls
                    for local, rows in enumerate(results):
                        spec, analyzer, times = series_meta[chunk[local]]
                        if rows is None:
                            report.series_skipped += 1
                            continue
                        scored_by_tenant[spec.tenant] = (
                            scored_by_tenant.get(spec.tenant, 0) + 1
                        )
                        for index, anomaly in rows:
                            detail = (
                                f"tenant={spec.tenant} dataset={spec.dataset} "
                                f"analyzer={analyzer!r} point={index} "
                                f"value={anomaly.value}: "
                                f"{anomaly.detail or 'anomalous'}"
                            )
                            report.flagged.append((
                                spec.tenant, spec.dataset, repr(analyzer),
                                int(index), anomaly.value, detail,
                            ))
                            # a STANDING anomaly re-flags in every
                            # report, but exports/dumps once — re-dumping
                            # the same point per harvest would drain the
                            # recorder's process-wide dump budget and
                            # inflate the counter by harvest rate
                            # keyed by the point's TIMESTAMP (not its
                            # window-relative index): a NEW incident at a
                            # later date must count and dump even when
                            # the windowed history has the same length
                            fp = (
                                spec.tenant, spec.dataset, repr(analyzer),
                                times[int(index)], anomaly.value,
                            )
                            with self._lock:
                                if fp in self._seen_flags:
                                    continue
                                if len(self._seen_flags) >= 65536:
                                    # bounded memory beats a leak; a
                                    # clear at worst re-dumps standing
                                    # anomalies once
                                    self._seen_flags.clear()
                                self._seen_flags.add(fp)
                            flagged_updates.append((
                                "deequ_service_anomaly_flagged_total", 1.0,
                                {"tenant": spec.tenant},
                            ))
                            _trace.add_event(
                                "anomaly_flagged", span=sp,
                                tenant=spec.tenant, dataset=spec.dataset,
                                analyzer=repr(analyzer), index=int(index),
                                value=anomaly.value,
                            )
                            # the trace-correlated flight dump: released
                            # the moment the harvesting job's span (or
                            # this root) closes
                            recorder().note_failure(
                                "AnomalyFlagged",
                                getattr(sp, "trace_id", None), detail,
                            )
            report.tenants = len({w.tenant for w in watches})
            report.series_scored = sum(scored_by_tenant.values())
            updates = flagged_updates + [
                ("deequ_service_anomaly_series_scored_total", float(n),
                 {"tenant": tenant})
                for tenant, n in scored_by_tenant.items()
            ]
            updates.append(
                ("deequ_service_anomaly_harvests_total", 1.0, {})
            )
            updates.append((
                "deequ_service_anomaly_scoring_seconds_total",
                report.scoring_seconds, {},
            ))
            self.metrics.inc_many(updates)
            sp.set_attr("series_scored", report.series_scored)
            sp.set_attr("flagged", len(report.flagged))
        self.last_report = report
        return report

    def _cached_fits(self, strategy, chunk, values, intervals, series_meta):
        """Per-series model parameters for a fit-bearing strategy
        (``fit_batch`` — Holt-Winters), re-fitting ONLY the series whose
        training slice changed since the last harvest; None for
        strategies with no fit step. Parameters are bit-identical to an
        uncached run (the cache stores what the same optimizer call
        returned for the same training input)."""
        if not hasattr(strategy, "fit_batch"):
            return None
        keys = []
        for local, i in enumerate(chunk):
            spec, analyzer, _times = series_meta[i]
            start = intervals[local][0]
            training = tuple(values[local][:start])
            # keyed by the strategy's VALUE (type + periodicity, its
            # only fit-relevant hyperparameter), never its id() — a
            # recycled object address must not serve parameters fitted
            # under a different model
            keys.append((
                spec.tenant, spec.dataset, repr(analyzer),
                type(strategy).__name__,
                getattr(strategy, "series_periodicity", None),
                start, hash(training),
            ))
        with self._lock:
            params = [self._fit_cache.get(k) for k in keys]
        missing = [j for j, p in enumerate(params) if p is None]
        if missing:
            try:
                fitted = strategy.fit_batch(
                    [values[j] for j in missing],
                    [intervals[j] for j in missing],
                )
            except ValueError:
                return None  # _detect's per-series fallback handles it
            with self._lock:
                if len(self._fit_cache) >= 65536:
                    self._fit_cache.clear()  # bounded beats a leak
                for j, p in zip(missing, fitted):
                    params[j] = p
                    self._fit_cache[keys[j]] = p
        return params

    @staticmethod
    def _detect(strategy, values, intervals, params=None):
        """One batched call, returning ``(per-series rows, calls made)``.
        A ValueError from a mixed-validity fleet (a validation the gather
        pre-filters missed) degrades — with a warning, and honestly
        counted — to per-series calls so ONE unscorable series costs
        itself, not its bundle; unscorable series report None rows."""
        kw = {} if params is None else {"params": params}
        try:
            return strategy.detect_batch(values, intervals, **kw), 1
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "fleet-watch bundle of %d series degraded to per-series "
                "scoring (one series failed %s validation)",
                len(values), type(strategy).__name__, exc_info=True,
            )
            out = []
            for j, (v, iv) in enumerate(zip(values, intervals)):
                try:
                    pkw = (
                        {} if params is None else {"params": [params[j]]}
                    )
                    out.append(strategy.detect_batch([v], [iv], **pkw)[0])
                except ValueError:
                    out.append(None)
            return out, len(values)

    def _load_history(self, spec: WatchSpec, after_ms: Optional[int]):
        """[(analyzer, [values...]), ...] for one tenant, loading ONLY the
        scoring window (the partitioned repository walks just those month
        buckets) and extracting each analyzer's numeric series in time
        order, missing values dropped — the `HistoryUtils` contract."""
        from ..anomalydetection.wiring import extract_metric_values

        loader = spec.repository.load().for_analyzers(list(spec.analyzers))
        if spec.tags:
            loader = loader.with_tag_values(dict(spec.tags))
        if after_ms is not None:
            loader = loader.after(after_ms)
        results = loader.get()
        out = []
        for analyzer in spec.analyzers:
            points = extract_metric_values(results, analyzer)
            points = sorted(
                (p for p in points if p.metric_value is not None),
                key=lambda p: p.time,
            )
            out.append((
                analyzer,
                [p.metric_value for p in points],
                [p.time for p in points],
            ))
        return out

    def _quarantine_tenant(
        self, spec: WatchSpec, exc: BaseException, report: HarvestReport,
        quarantined: set,
    ) -> None:
        if spec.tenant in quarantined:
            return
        quarantined.add(spec.tenant)
        report.quarantined_tenants.append(spec.tenant)
        # the export counter and the typed flight record fire once per
        # STANDING episode (a corrupt entry re-quarantines on every load
        # until it heals; counting per harvest would inflate by harvest
        # rate); the report lists the tenant every harvest regardless
        with self._lock:
            mark = (spec.tenant, spec.dataset)
            new_episode = mark not in self._quarantine_marks
            self._quarantine_marks.add(mark)
        if not new_episode:
            return
        self.metrics.inc(
            "deequ_service_anomaly_quarantined_total", tenant=spec.tenant
        )
        _trace.add_event(
            "fleetwatch_history_quarantined", tenant=spec.tenant,
            dataset=spec.dataset, error=f"{type(exc).__name__}: {exc}",
        )
        from ..observability.recorder import record_failure

        record_failure(exc)


def _low_priority():
    from .scheduler import Priority

    return Priority.LOW
