"""Tenant catalog: per-tenant quality suites as versioned, checksummed
DATA.

ROADMAP item 5's isolation premise: a fleet of a million tenants cannot
be a million Python call sites constructing ``Check`` objects — tenants
become DOCUMENTS. One JSON document per tenant declares its whole quality
suite: checks, anomaly watches, drift policy, row-gate schema, partition
retention, priority/SLO class and admission quotas. The catalog stores
them versioned + checksummed in the partition-store layout
(``<root>/t-<tenant>/v00000001.json``), and the service plane
materializes live state (streaming session, row gate, quotas, watches)
from the CURRENT document on first ingest — and re-materializes it at
fold boundaries when the document changes, without a restart.

Robustness contract (the reason this module exists):

- **last-good wins.** A corrupt or invalid document version NEVER drops a
  live tenant: :meth:`TenantCatalog.load` quarantines the bad version
  content-addressed (the partition store's ``.quarantine`` convention),
  bumps exactly one typed counter, and serves the newest version that
  parses + verifies. Only a tenant with NO good version raises
  :class:`CatalogError`.
- **writes are validated + atomic.** :meth:`TenantCatalog.register`
  validates the document (typed :class:`CatalogError` on rejection —
  an operator typo is caught at write time, not at 3am on the ingest
  path) and writes the next version via atomic rename, so a torn write
  can only ever produce a missing version, not a half document.
- **hot/cold tiering.** Registered-but-idle tenants cost a directory on
  disk and nothing in memory: session + watch state materialize on first
  ingest (:meth:`CatalogPlane.ensure_session`) and evict on idle TTL
  (:meth:`CatalogPlane.sweep`), so 1M registered / 1k active costs 1k
  tenants.
- the ``catalog_load`` fault site wires document loading into the chaos
  plane: an injected ``corrupt`` fault quarantines exactly like a torn
  on-disk document.

Document model (every key optional unless noted)::

    {
      "checks": [{"name": str, "level": "error"|"warning",
                  "constraints": [{"kind": str, "column": str,
                                   "min": num, "max": num, ...}]}],
      "row_gate": {"columns": [{"name": str (required),
                                "type": "string"|"int"|"decimal"|"timestamp",
                                "nullable": bool, "min_length": int,
                                "max_length": int, "matches": str,
                                "min_value": num, "max_value": num,
                                "precision": int, "scale": int,
                                "mask": str}]},
      "watches": [{"analyzer": {"kind": str, "column": str,
                                "columns": [str]},
                   "strategy": {"kind": "online_normal"|"simple_threshold"
                                |"absolute_change", ...params}}],
      "drift_policy": "reject"|"coerce"|"degrade",
      "priority": "high"|"normal"|"low",
      "quotas": {"rows_per_s": num, "bytes_per_s": num,
                 "queue_share": num in (0, 1]},
      "retention": {"keep_partitions": int},
      "session": {"batch_size": int, "keep_results": int,
                  "admission_block_s": num, "deadline_s": num,
                  "max_retries": int}
    }
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

_logger = logging.getLogger(__name__)

from .. import io as dio
from ..utils import env_number

#: seconds a HOT tenant (materialized session + watch state) may sit idle
#: before :meth:`CatalogPlane.sweep` evicts it back to the cold tier
#: (close + flush; the document stays registered). Warn-once parser.
CATALOG_HOT_TTL_ENV = "DEEQU_TPU_CATALOG_HOT_TTL_S"
DEFAULT_CATALOG_HOT_TTL_S = 300.0

#: seconds between version polls of a hot tenant's document at fold
#: boundaries — the hot-reload debounce: a 10k-fold/s tenant must not
#: stat the catalog directory 10k times a second. Warn-once parser.
CATALOG_POLL_ENV = "DEEQU_TPU_CATALOG_POLL_S"
DEFAULT_CATALOG_POLL_S = 2.0


def catalog_hot_ttl_s() -> float:
    return float(env_number(
        CATALOG_HOT_TTL_ENV, DEFAULT_CATALOG_HOT_TTL_S, float, minimum=0.0
    ))


def catalog_poll_s() -> float:
    return float(env_number(
        CATALOG_POLL_ENV, DEFAULT_CATALOG_POLL_S, float, minimum=0.0
    ))


class CatalogError(Exception):
    """A tenant catalog operation failed TYPED: an invalid document at
    registration time (the operator hears about the typo immediately), or
    a load for a tenant with no good version (unregistered, or every
    version corrupt AND no last-good cached). Never raised for a tenant
    that has ANY servable version — a bad edit degrades to last-good, not
    to an error."""

    def __init__(self, tenant: str, detail: str):
        self.tenant = str(tenant)
        super().__init__(f"tenant catalog [{tenant}]: {detail}")


@dataclass(frozen=True)
class TenantDocument:
    """One validated, checksummed catalog version as loaded from disk."""

    tenant: str
    version: int
    doc: Dict[str, Any]


# -- document validation + declarative builders ------------------------------

#: constraint kinds the declarative schema accepts, with the document keys
#: each reads. Deliberately a CLOSED set with numeric bounds instead of
#: arbitrary expressions: documents are data written by operators, and
#: data must not smuggle code (no eval, no lambdas on the wire).
_CONSTRAINT_KINDS = {
    "size": ("min", "max"),
    "complete": ("column",),
    "completeness": ("column", "min", "max"),
    "unique": ("column",),
    "uniqueness": ("columns", "min"),
    "distinctness": ("columns", "min"),
    "entropy": ("column", "min", "max"),
    "min": ("column", "min", "max"),
    "max": ("column", "min", "max"),
    "mean": ("column", "min", "max"),
    "sum": ("column", "min", "max"),
    "standard_deviation": ("column", "min", "max"),
    "min_length": ("column", "min", "max"),
    "max_length": ("column", "min", "max"),
    "approx_count_distinct": ("column", "min", "max"),
    "pattern": ("column", "pattern"),
    "non_negative": ("column",),
    "positive": ("column",),
    "contained_in": ("column", "allowed"),
}

_ROW_GATE_TYPES = ("string", "int", "decimal", "timestamp")

_WATCH_ANALYZERS = (
    "size", "completeness", "mean", "minimum", "maximum", "sum",
    "standard_deviation", "approx_count_distinct", "uniqueness",
    "distinctness", "entropy",
)

_WATCH_STRATEGIES = ("online_normal", "simple_threshold", "absolute_change")


def _bound_assertion(lo, hi):
    """min/max bounds -> the assertion callable the Check builders take.
    Closed over plain floats — documents carry bounds, never code."""
    lo = None if lo is None else float(lo)
    hi = None if hi is None else float(hi)

    def assertion(value: float) -> bool:
        if lo is not None and value < lo:
            return False
        if hi is not None and value > hi:
            return False
        return True

    return assertion


def _reject(tenant: str, detail: str):
    raise CatalogError(tenant, detail)


def validate_document(tenant: str, doc: Any) -> Dict[str, Any]:
    """Structural validation of one tenant document; raises typed
    :class:`CatalogError` naming the offending key. Returns ``doc``.
    Validation is deliberately strict on SHAPE (unknown constraint kinds,
    wrong types, unknown policies all reject) — a silently-ignored typo'd
    check is a tenant who believes they are verified and is not."""
    if not isinstance(doc, dict):
        _reject(tenant, f"document must be a JSON object, got {type(doc).__name__}")
    for check in doc.get("checks", ()):
        if not isinstance(check, dict):
            _reject(tenant, "checks[] entries must be objects")
        level = check.get("level", "error")
        if level not in ("error", "warning"):
            _reject(tenant, f"unknown check level {level!r}")
        for c in check.get("constraints", ()):
            if not isinstance(c, dict):
                _reject(tenant, "constraints[] entries must be objects")
            kind = c.get("kind")
            if kind not in _CONSTRAINT_KINDS:
                _reject(tenant, f"unknown constraint kind {kind!r}")
            allowed = _CONSTRAINT_KINDS[kind]
            for key in c:
                if key != "kind" and key not in allowed:
                    _reject(
                        tenant,
                        f"constraint kind {kind!r} does not take {key!r}",
                    )
            if "column" in allowed and not isinstance(
                c.get("column", ""), str
            ):
                _reject(tenant, f"constraint {kind!r}: column must be a string")
            if "column" in allowed and "columns" not in allowed and not c.get("column"):
                _reject(tenant, f"constraint {kind!r} requires a column")
            if "columns" in allowed and not c.get("columns"):
                _reject(tenant, f"constraint {kind!r} requires columns")
            if kind == "pattern":
                if not c.get("pattern"):
                    _reject(tenant, "constraint 'pattern' requires a pattern")
                try:
                    re.compile(c["pattern"])
                except re.error as err:
                    _reject(
                        tenant,
                        f"constraint 'pattern': invalid regex "
                        f"{c['pattern']!r} ({err})",
                    )
            if kind == "contained_in" and not isinstance(
                c.get("allowed"), list
            ):
                _reject(tenant, "constraint 'contained_in' requires allowed[]")
    gate = doc.get("row_gate")
    if gate is not None:
        if not isinstance(gate, dict) or not isinstance(
            gate.get("columns"), list
        ) or not gate["columns"]:
            _reject(tenant, "row_gate requires a non-empty columns[] list")
        for col in gate["columns"]:
            if not isinstance(col, dict) or not col.get("name"):
                _reject(tenant, "row_gate columns[] entries require a name")
            if col.get("type", "string") not in _ROW_GATE_TYPES:
                _reject(
                    tenant,
                    f"row_gate column {col.get('name')!r}: unknown type "
                    f"{col.get('type')!r}",
                )
            if col.get("matches") is not None:
                try:
                    re.compile(col["matches"])
                except re.error as err:
                    _reject(
                        tenant,
                        f"row_gate column {col.get('name')!r}: invalid "
                        f"regex {col['matches']!r} ({err})",
                    )
    for watch in doc.get("watches", ()):
        if not isinstance(watch, dict) or not isinstance(
            watch.get("analyzer"), dict
        ):
            _reject(tenant, "watches[] entries require an analyzer object")
        akind = watch["analyzer"].get("kind")
        if akind not in _WATCH_ANALYZERS:
            _reject(tenant, f"unknown watch analyzer kind {akind!r}")
        if akind != "size" and not (
            watch["analyzer"].get("column") or watch["analyzer"].get("columns")
        ):
            _reject(tenant, f"watch analyzer {akind!r} requires a column")
        strategy = watch.get("strategy")
        if strategy is not None and strategy.get("kind") not in _WATCH_STRATEGIES:
            _reject(
                tenant, f"unknown watch strategy {strategy.get('kind')!r}"
            )
    from .drift import DRIFT_POLICIES

    policy = doc.get("drift_policy", "reject")
    if policy not in DRIFT_POLICIES:
        _reject(tenant, f"drift_policy must be one of {DRIFT_POLICIES}")
    if doc.get("priority", "normal") not in ("high", "normal", "low"):
        _reject(tenant, f"unknown priority {doc.get('priority')!r}")
    quotas = doc.get("quotas")
    if quotas is not None:
        if not isinstance(quotas, dict):
            _reject(tenant, "quotas must be an object")
        for key, value in quotas.items():
            if key not in ("rows_per_s", "bytes_per_s", "queue_share"):
                _reject(tenant, f"unknown quota {key!r}")
            if not isinstance(value, (int, float)) or value <= 0:
                _reject(tenant, f"quota {key!r} must be a positive number")
        if quotas.get("queue_share", 0.5) > 1:
            _reject(tenant, "queue_share is a fraction in (0, 1]")
    retention = doc.get("retention")
    if retention is not None and not isinstance(retention, dict):
        _reject(tenant, "retention must be an object")
    session = doc.get("session")
    if session is not None and not isinstance(session, dict):
        _reject(tenant, "session must be an object")
    return doc


def build_checks(tenant: str, doc: Dict[str, Any]) -> List[Any]:
    """Document ``checks`` -> live :class:`~deequ_tpu.checks.Check`
    objects via the fluent builders (the same constraint machinery every
    in-process caller uses — documents are a FRONTEND, not a fork)."""
    from ..checks import Check, CheckLevel

    out = []
    for spec in doc.get("checks", ()):
        level = (
            CheckLevel.WARNING if spec.get("level") == "warning"
            else CheckLevel.ERROR
        )
        check = Check(level, spec.get("name", f"{tenant}-check"))
        for c in spec.get("constraints", ()):
            kind = c["kind"]
            col = c.get("column")
            assertion = _bound_assertion(c.get("min"), c.get("max"))
            if kind == "size":
                check = check.has_size(assertion)
            elif kind == "complete":
                check = check.is_complete(col)
            elif kind == "completeness":
                check = check.has_completeness(col, assertion)
            elif kind == "unique":
                check = check.is_unique(col)
            elif kind == "uniqueness":
                check = check.has_uniqueness(
                    list(c["columns"]), _bound_assertion(c.get("min"), None)
                )
            elif kind == "distinctness":
                check = check.has_distinctness(
                    list(c["columns"]), _bound_assertion(c.get("min"), None)
                )
            elif kind == "entropy":
                check = check.has_entropy(col, assertion)
            elif kind == "min":
                check = check.has_min(col, assertion)
            elif kind == "max":
                check = check.has_max(col, assertion)
            elif kind == "mean":
                check = check.has_mean(col, assertion)
            elif kind == "sum":
                check = check.has_sum(col, assertion)
            elif kind == "standard_deviation":
                check = check.has_standard_deviation(col, assertion)
            elif kind == "min_length":
                check = check.has_min_length(col, assertion)
            elif kind == "max_length":
                check = check.has_max_length(col, assertion)
            elif kind == "approx_count_distinct":
                check = check.has_approx_count_distinct(col, assertion)
            elif kind == "pattern":
                check = check.has_pattern(col, c["pattern"])
            elif kind == "non_negative":
                check = check.is_non_negative(col)
            elif kind == "positive":
                check = check.is_positive(col)
            elif kind == "contained_in":
                check = check.is_contained_in(col, list(c["allowed"]))
            else:  # pragma: no cover - validate_document pins the set
                raise CatalogError(tenant, f"unbuildable constraint {kind!r}")
        out.append(check)
    return out


def build_row_gate_schema(doc: Dict[str, Any]):
    """Document ``row_gate`` -> a
    :class:`~deequ_tpu.schema.RowLevelSchema` (None when the document
    declares no gate)."""
    gate = doc.get("row_gate")
    if gate is None:
        return None
    from ..schema import RowLevelSchema

    schema = RowLevelSchema()
    for col in gate["columns"]:
        kind = col.get("type", "string")
        nullable = bool(col.get("nullable", True))
        if kind == "string":
            schema = schema.with_string_column(
                col["name"], is_nullable=nullable,
                min_length=col.get("min_length"),
                max_length=col.get("max_length"),
                matches=col.get("matches"),
            )
        elif kind == "int":
            schema = schema.with_int_column(
                col["name"], is_nullable=nullable,
                min_value=col.get("min_value"),
                max_value=col.get("max_value"),
            )
        elif kind == "decimal":
            schema = schema.with_decimal_column(
                col["name"], int(col.get("precision", 10)),
                int(col.get("scale", 0)), is_nullable=nullable,
            )
        else:
            schema = schema.with_timestamp_column(
                col["name"], col.get("mask", "yyyy-MM-dd HH:mm:ss"),
                is_nullable=nullable,
            )
    return schema


def build_quota(doc: Dict[str, Any]):
    """Document ``quotas`` -> a
    :class:`~deequ_tpu.service.scheduler.TenantQuota` (None when the
    document declares none)."""
    quotas = doc.get("quotas")
    if quotas is None:
        return None
    from .scheduler import TenantQuota

    return TenantQuota(
        rows_per_s=quotas.get("rows_per_s"),
        bytes_per_s=quotas.get("bytes_per_s"),
        queue_share=quotas.get("queue_share"),
    )


def build_priority(doc: Dict[str, Any]):
    from .scheduler import Priority

    return {
        "high": Priority.HIGH, "low": Priority.LOW,
    }.get(doc.get("priority", "normal"), Priority.NORMAL)


def build_watches(doc: Dict[str, Any]) -> List[Tuple[Any, Any]]:
    """Document ``watches`` -> ``[(analyzer, strategy)]`` pairs ready for
    :meth:`~deequ_tpu.service.fleetwatch.FleetWatch.watch`."""
    from ..analyzers import (
        ApproxCountDistinct,
        Completeness,
        Distinctness,
        Entropy,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
        Sum,
        Uniqueness,
    )
    from ..anomalydetection import (
        AbsoluteChangeStrategy,
        OnlineNormalStrategy,
        SimpleThresholdStrategy,
    )

    single = {
        "completeness": Completeness, "mean": Mean, "minimum": Minimum,
        "maximum": Maximum, "sum": Sum,
        "standard_deviation": StandardDeviation,
        "approx_count_distinct": ApproxCountDistinct, "entropy": Entropy,
    }
    multi = {"uniqueness": Uniqueness, "distinctness": Distinctness}
    out = []
    for watch in doc.get("watches", ()):
        spec = watch["analyzer"]
        kind = spec["kind"]
        if kind == "size":
            analyzer = Size()
        elif kind in multi:
            analyzer = multi[kind](
                list(spec.get("columns") or [spec["column"]])
            )
        else:
            analyzer = single[kind](spec["column"])
        sspec = watch.get("strategy") or {}
        skind = sspec.get("kind", "online_normal")
        if skind == "simple_threshold":
            strategy = SimpleThresholdStrategy(
                upper_bound=float(sspec.get("upper_bound", float("inf"))),
                lower_bound=float(sspec.get("lower_bound", float("-inf"))),
            )
        elif skind == "absolute_change":
            strategy = AbsoluteChangeStrategy(
                max_rate_decrease=sspec.get("max_rate_decrease"),
                max_rate_increase=sspec.get("max_rate_increase"),
            )
        else:
            strategy = OnlineNormalStrategy(
                lower_deviation_factor=float(
                    sspec.get("lower_deviation_factor", 3.0)
                ),
                upper_deviation_factor=float(
                    sspec.get("upper_deviation_factor", 3.0)
                ),
            )
        out.append((analyzer, strategy))
    return out


# -- the store ---------------------------------------------------------------


def describe_catalog_series(metrics) -> None:
    """Register HELP text for every export-plane series the catalog plane
    increments (idempotent; literal calls for the statlint export check)."""
    metrics.describe(
        "deequ_service_catalog_loads_total",
        "Tenant catalog documents loaded (registration reads, first-"
        "ingest materializations and hot-reload polls that re-read).",
    )
    metrics.describe(
        "deequ_service_catalog_reloads_total",
        "Hot reloads APPLIED to live sessions at fold boundaries after a "
        "catalog edit (no restart).",
    )
    metrics.describe(
        "deequ_service_catalog_quarantined_total",
        "Corrupt or invalid catalog document versions quarantined "
        "content-addressed; the tenant kept serving its last-good "
        "version.",
    )
    metrics.describe(
        "deequ_service_catalog_evictions_total",
        "Hot tenants evicted to the cold tier after their idle TTL "
        "(session closed + flushed; the document stays registered).",
    )


def _tenant_dirname(tenant: str) -> str:
    from urllib.parse import quote

    return "t-" + quote(str(tenant), safe="")


_VERSION_DIGITS = 8


class TenantCatalog:
    """The versioned document store. Thread-safe; every mutation is an
    atomic whole-file write, so concurrent registers at worst interleave
    version numbers (each version is still internally consistent)."""

    def __init__(self, path: str, metrics=None):
        self.path = str(path)
        self.metrics = metrics
        if metrics is not None:
            describe_catalog_series(metrics)
        self._lock = threading.Lock()
        #: tenant -> last GOOD TenantDocument served by load(): what a
        #: tenant keeps serving when every on-disk version goes bad
        #: mid-flight (disk loss after a successful load)
        self._last_good: Dict[str, TenantDocument] = {}
        #: version paths already quarantined by this process — dedupes
        #: the counter bump when the original could not be removed (a
        #: read-only store re-walks the same bad file every load)
        self._quarantined_paths: set = set()

    # -- paths ---------------------------------------------------------------

    def _tenant_dir(self, tenant: str) -> str:
        return dio.join(self.path, _tenant_dirname(tenant))

    def _versions(self, tenant: str) -> List[int]:
        out = []
        for name in dio.list_files(self._tenant_dir(tenant)):
            if name.startswith("v") and name.endswith(".json"):
                try:
                    out.append(int(name[1:-5]))
                except ValueError:
                    continue
        return sorted(out)

    # -- registration --------------------------------------------------------

    def register(self, tenant: str, doc: Dict[str, Any]) -> TenantDocument:
        """Validate ``doc`` and write it as the tenant's next version.
        Raises typed :class:`CatalogError` on an invalid document —
        NOTHING is written, the tenant's current version is untouched."""
        validate_document(tenant, doc)
        # exercise the full builder path at registration time: a document
        # that validates structurally but cannot BUILD (a regex that does
        # not compile) must bounce here, not on the ingest path
        try:
            build_checks(tenant, doc)
            build_row_gate_schema(doc)
            build_quota(doc)
            build_watches(doc)
        except CatalogError:
            raise
        except Exception as exc:  # noqa: BLE001 - rebuilt typed
            raise CatalogError(
                str(tenant), f"document does not build: {exc}"
            ) from exc
        with self._lock:
            versions = self._versions(tenant)
            version = (versions[-1] + 1) if versions else 1
            payload = {
                "tenant": str(tenant), "version": version, "doc": doc,
            }
            from ..integrity import checksum_json

            payload["checksum"] = checksum_json(payload)
            dio.makedirs(self._tenant_dir(tenant))
            dio.write_text_atomic(
                dio.join(
                    self._tenant_dir(tenant),
                    f"v{version:0{_VERSION_DIGITS}d}.json",
                ),
                json.dumps(payload, sort_keys=True),
            )
        return TenantDocument(str(tenant), version, doc)

    def registered(self, tenant: str) -> bool:
        return dio.exists(self._tenant_dir(tenant))

    def tenants(self) -> List[str]:
        from urllib.parse import unquote

        return [
            unquote(name[2:]) for name in dio.list_dirs(self.path)
            if name.startswith("t-")
        ]

    def registered_count(self) -> int:
        return sum(
            1 for name in dio.list_dirs(self.path) if name.startswith("t-")
        )

    def current_version(self, tenant: str) -> int:
        """The newest on-disk version number (0 = unregistered) — a pure
        listing, no parse: the hot-reload poll's cheap staleness probe."""
        versions = self._versions(tenant)
        return versions[-1] if versions else 0

    # -- loading -------------------------------------------------------------

    def load(self, tenant: str) -> TenantDocument:
        """The newest GOOD document version for ``tenant``. Walks versions
        newest-first; a version that is torn, fails its checksum, or fails
        validation is quarantined content-addressed + counted, and the
        walk continues to the previous version (LAST-GOOD semantics: a
        bad edit can never drop a live tenant). Raises
        :class:`CatalogError` only when NO version is servable and no
        last-good is cached."""
        tenant = str(tenant)
        versions = self._versions(tenant)
        for version in reversed(versions):
            path = dio.join(
                self._tenant_dir(tenant),
                f"v{version:0{_VERSION_DIGITS}d}.json",
            )
            try:
                from ..reliability.faults import fault_point

                # chaos site: a `corrupt` fault here stands in for a
                # torn/garbled on-disk document — quarantined exactly
                # like the real thing, last-good keeps serving
                fault_point("catalog_load", tag=tenant)
                with dio.open_file(path, "r") as fh:
                    payload = json.load(fh)
                from ..integrity import verify_json_checksum

                verify_json_checksum(
                    {k: v for k, v in payload.items() if k != "checksum"},
                    payload.get("checksum", ""),
                    "tenant catalog document", path,
                )
                doc = validate_document(tenant, payload["doc"])
                loaded = TenantDocument(tenant, version, doc)
                with self._lock:
                    self._last_good[tenant] = loaded
                if self.metrics is not None:
                    self.metrics.inc(
                        "deequ_service_catalog_loads_total", tenant=tenant
                    )
                return loaded
            except Exception as exc:  # noqa: BLE001 - quarantine + walk on
                self._quarantine_version(tenant, path, exc)
        with self._lock:
            cached = self._last_good.get(tenant)
        if cached is not None:
            _logger.warning(
                "tenant %s has no servable on-disk catalog version; "
                "serving the cached last-good v%d", tenant, cached.version,
            )
            return cached
        raise CatalogError(
            tenant,
            "no servable document version"
            if versions else "tenant is not registered",
        )

    def _quarantine_version(
        self, tenant: str, path: str, exc: BaseException
    ) -> None:
        """MOVE one bad document version into the content-addressed
        sidecar (the partition store's ``.quarantine`` convention) +
        exactly one typed counter bump. The move (copy, then remove the
        original) is what makes the bump exactly-once: a quarantined
        version leaves the tenant's listing, so the next load — and the
        hot-reload poll — never walk past it again. Best-effort on every
        step: an unwritable store must not turn a survivable bad edit
        into a crash, and an unremovable original degrades to a counted
        re-quarantine (deduped in-process), never a lost tenant."""
        from ..integrity import checksum_bytes
        from ..observability import trace as _trace

        with self._lock:
            if path in self._quarantined_paths:
                return
            self._quarantined_paths.add(path)
        payload = b""
        try:
            with dio.open_file(path, "rb") as fh:
                payload = fh.read()
        except Exception:  # noqa: BLE001 - the version may not even exist
            pass
        if payload:
            import os

            side_dir = self.path + ".quarantine"
            name = f"{os.path.basename(path)}-{checksum_bytes(payload)}"
            try:
                dio.makedirs(side_dir)
                with dio.open_file(dio.join(side_dir, name), "wb") as fh:
                    fh.write(payload)
            except Exception:  # noqa: BLE001 - best-effort preservation
                pass
            else:
                # content is preserved in the sidecar: complete the move
                # so the bad version stops shadowing last-good in the
                # listing (evidence is never deleted before it is copied)
                try:
                    dio.remove_file(path)
                except Exception:  # noqa: BLE001 - dedupe set covers this
                    pass
        if self.metrics is not None:
            self.metrics.inc(
                "deequ_service_catalog_quarantined_total", tenant=tenant
            )
        _trace.add_event(
            "catalog_version_quarantined", tenant=tenant, source=path,
            reason=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        _logger.warning(
            "quarantined bad catalog document %s for tenant %s: %s",
            path, tenant, exc,
        )


# -- the hot tier ------------------------------------------------------------


@dataclass
class _HotTenant:
    """Per-(tenant, dataset) hot-tier bookkeeping."""

    version: int
    last_seen: float
    last_poll: float
    watch_keys: Tuple[Tuple[str, str], ...] = ()


class CatalogPlane:
    """Binds a :class:`TenantCatalog` to a live
    :class:`~deequ_tpu.service.VerificationService`: materializes
    sessions (+ row gate + quotas + watches) from documents on first
    ingest, hot-reloads them at fold boundaries when the document
    changes, and evicts idle tenants back to the cold tier on TTL."""

    def __init__(
        self,
        service,
        catalog: TenantCatalog,
        *,
        hot_ttl_s: Optional[float] = None,
        poll_s: Optional[float] = None,
    ):
        self.service = service
        self.catalog = catalog
        if catalog.metrics is None:
            catalog.metrics = service.metrics
        self.hot_ttl_s = (
            catalog_hot_ttl_s() if hot_ttl_s is None else float(hot_ttl_s)
        )
        self.poll_s = catalog_poll_s() if poll_s is None else float(poll_s)
        self._lock = threading.Lock()
        self._hot: Dict[Tuple[str, str], _HotTenant] = {}
        describe_catalog_series(service.metrics)
        service.metrics.set_gauge_fn(
            "deequ_service_catalog_hot_tenants",
            lambda: len(self._hot),
            "Catalog tenants currently materialized on the hot tier "
            "(live session + watch state).",
        )
        service.metrics.set_gauge_fn(
            "deequ_service_catalog_registered_tenants",
            self.catalog.registered_count,
            "Tenants registered in the catalog (hot or cold).",
        )

    # -- materialization -----------------------------------------------------

    def ensure_session(self, tenant: str, dataset: str):
        """Get-or-materialize the streaming session for a catalog-
        registered tenant. A live session is returned as-is (with the
        debounced hot-reload poll applied); a cold tenant materializes
        its whole suite — session with document checks/policy/priority,
        row gate, admission quotas, anomaly watches — from the CURRENT
        document. Raises :class:`CatalogError` for unregistered tenants
        (the endpoint's 404 contract stays intact)."""
        session = self.service.get_session(tenant, dataset)
        if session is not None:
            self.on_fold_boundary(session)
            return session
        document = self.catalog.load(tenant)
        doc = document.doc
        session_kw = dict(doc.get("session") or {})
        gate = self._build_gate(doc)
        session = self.service.session(
            tenant, dataset, build_checks(tenant, doc),
            drift_policy=doc.get("drift_policy", "reject"),
            priority=build_priority(doc),
            row_gate=gate,
            **{
                k: session_kw[k] for k in (
                    "batch_size", "keep_results", "admission_block_s",
                    "deadline_s", "max_retries",
                ) if k in session_kw
            },
        )
        quota = build_quota(doc)
        if quota is not None:
            self.service.scheduler.set_quota(tenant, quota)
        watch_keys = self._register_watches(tenant, dataset, doc)
        now = time.monotonic()
        with self._lock:
            self._hot[(tenant, dataset)] = _HotTenant(
                version=document.version, last_seen=now, last_poll=now,
                watch_keys=watch_keys,
            )
        return session

    def _build_gate(self, doc: Dict[str, Any]):
        schema = build_row_gate_schema(doc)
        if schema is None:
            return None
        from ..ingest.rowgate import QuarantineSidecar, RowGate

        root = getattr(self.service, "state_root", None) or self.catalog.path
        return RowGate(
            schema,
            sidecar=QuarantineSidecar(str(root) + ".rowgate-quarantine"),
            metrics=self.service.metrics,
        )

    def _register_watches(
        self, tenant: str, dataset: str, doc: Dict[str, Any]
    ) -> Tuple[Tuple[str, str], ...]:
        """Materialize the document's anomaly watches on the service's
        fleet watch, grouped per strategy (one watch key per strategy so
        differently-parameterized strategies coexist)."""
        pairs = build_watches(doc)
        fleetwatch = getattr(self.service, "fleetwatch", None)
        if not pairs or fleetwatch is None:
            return ()
        from ..repository import InMemoryMetricsRepository

        keys = []
        for i, (analyzer, strategy) in enumerate(pairs):
            # dataset-qualified watch key: each declared watch gets its
            # own slot so re-registration replaces exactly itself
            wdataset = f"{dataset}#w{i}"
            fleetwatch.watch(
                tenant, InMemoryMetricsRepository(), [analyzer],
                strategy=strategy, dataset=wdataset,
            )
            keys.append((tenant, wdataset))
        return tuple(keys)

    # -- hot reload ----------------------------------------------------------

    def on_fold_boundary(self, session) -> None:
        """The fold-boundary hook (the ingest endpoint calls this per
        POST): touch the hot entry's idle clock and — debounced by
        ``poll_s`` — poll the document version, re-materializing the
        session's checks/policy/gate/quotas in place when it changed. A
        corrupt edit never reaches here as a new version: ``load`` serves
        last-good (same version, no reload) and the quarantine counter is
        the only trace."""
        key = (session.tenant, session.dataset)
        now = time.monotonic()
        with self._lock:
            hot = self._hot.get(key)
            if hot is None:
                hot = self._hot[key] = _HotTenant(
                    version=self.catalog.current_version(session.tenant),
                    last_seen=now, last_poll=now,
                )
                return
            hot.last_seen = now
            if self.poll_s and now - hot.last_poll < self.poll_s:
                return
            hot.last_poll = now
            known = hot.version
        if self.catalog.current_version(session.tenant) == known:
            return
        try:
            document = self.catalog.load(session.tenant)
        except CatalogError:
            return  # no servable version: keep running the live config
        if document.version == known:
            # the newer version(s) were corrupt: load already quarantined
            # them and served last-good — nothing to apply
            return
        doc = document.doc
        session.reconfigure(
            checks=build_checks(session.tenant, doc),
            drift_policy=doc.get("drift_policy", "reject"),
            priority=build_priority(doc),
            row_gate=self._build_gate(doc),
        )
        quota = build_quota(doc)
        if quota is not None:
            self.service.scheduler.set_quota(session.tenant, quota)
        else:
            self.service.scheduler.clear_quota(session.tenant)
        with self._lock:
            hot = self._hot.get(key)
            old_watch_keys = hot.watch_keys if hot is not None else ()
        fleetwatch = getattr(self.service, "fleetwatch", None)
        if fleetwatch is not None:
            for wtenant, wdataset in old_watch_keys:
                fleetwatch.unwatch(wtenant, wdataset)
        new_keys = self._register_watches(
            session.tenant, session.dataset, doc
        )
        with self._lock:
            hot = self._hot.get(key)
            if hot is not None:
                hot.version = document.version
                hot.watch_keys = new_keys
        self.service.metrics.inc(
            "deequ_service_catalog_reloads_total", tenant=session.tenant
        )
        from ..observability import trace as _trace

        _trace.add_event(
            "catalog_hot_reload", tenant=session.tenant,
            dataset=session.dataset, version=document.version,
        )

    # -- eviction ------------------------------------------------------------

    def sweep(self, now: Optional[float] = None) -> int:
        """Evict hot tenants idle past the TTL back to the cold tier:
        close the session (which flushes its cumulative states to the
        partition store — re-materialization adopts them), drop the watch
        state, clear the hot entry. Returns the evictions performed. The
        document stays registered: the next ingest re-materializes from
        it, which is the whole hot/cold contract (1M registered / 1k
        active costs 1k tenants)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            idle = [
                (key, hot) for key, hot in self._hot.items()
                if now - hot.last_seen >= self.hot_ttl_s
            ]
            for key, _hot in idle:
                del self._hot[key]
        fleetwatch = getattr(self.service, "fleetwatch", None)
        evicted = 0
        for (tenant, dataset), hot in idle:
            session = self.service.get_session(tenant, dataset)
            if session is not None:
                session.close()
            if fleetwatch is not None:
                for wtenant, wdataset in hot.watch_keys:
                    fleetwatch.unwatch(wtenant, wdataset)
            evicted += 1
            self.service.metrics.inc(
                "deequ_service_catalog_evictions_total", tenant=tenant
            )
        return evicted

    def hot_count(self) -> int:
        with self._lock:
            return len(self._hot)
