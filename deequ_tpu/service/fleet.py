"""Fleet scheduler: tenants packed onto disjoint sub-meshes by default.

Everything in this repo's state algebra is mergeable by construction, and
PR 7 made the multi-chip scan SURVIVE shard loss — but until this module,
production traffic still landed on one chip: a `VerificationService` built
without an explicit ``mesh=`` never sharded anything. This module closes
ROADMAP item 2's promotion: the mesh becomes the default service path.

- **Default-on sharding.** When the process sees a multi-device
  accelerator mesh, every batch verification job's row stream shards
  across it by default (naive leading-axis batch sharding with replicated
  small states — the one-axis data-mesh pattern of SNIPPETS [2], executed
  through the existing pjit'd explicit-sharding programs of [1]/[3] and
  the host-partial `sharded_ingest_fold`), riding the `ElasticMeshFold`
  ladder for loss recovery. ``DEEQU_TPU_FLEET=0`` is the escape hatch:
  single-chip routing, byte-for-byte the pre-fleet path.

- **Disjoint sub-mesh packing.** Independent tenants do not share chips:
  the :class:`FleetScheduler` partitions the healthy device set into
  power-of-two slices (8 -> 4+4 for two tenants, 2-device slices for
  three or four, single chips beyond) and leases each tenant its own
  slice, so one tenant's scan cannot contend with another's — the
  acceptance property the multi-tenant soak measures. More tenants than
  chips wrap around (slices shared round-robin, still bounded).

- **Elastic re-packing.** A shard dropping out of the ladder (dead
  collective, heartbeat miss, injected ``mesh_loss``) marks its device
  unhealthy fleet-wide — the elastic layer's loss notification feeds
  :meth:`FleetScheduler.note_shard_loss` — and the next lease packs
  tenants over the survivors. In-flight jobs keep recovering through
  their own ladder; future jobs never see the dead chip.

Warmth interplay: warmth keys are MESH-SHAPE-QUALIFIED
(`placement.shape_qualified_signature` carries the device count), so a
battery warmed for an 8-device program is COLD for the 4-device sub-mesh
a re-pack hands the tenant — it recompiles (cheaply, via the persistent
XLA cache) instead of silently reusing a program whose collective layout
no longer matches the mesh.

Default policy: the fleet is ON when the backend is a real accelerator
with more than one chip. On the CPU backend it must be FORCED with
``DEEQU_TPU_FLEET=1`` — virtual CPU "devices" share the same host cores
(the r06 ``vs_baseline: 0.8`` lesson), so sharding over them models
nothing and slows the host paths that actually serve CPU-only boxes.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

_logger = logging.getLogger(__name__)

#: env var: "0" disables the fleet scheduler entirely (single-chip routing,
#: byte-for-byte the pre-fleet service path); "1" forces it on even on the
#: CPU backend (tests / virtual-device drills); unset = on iff the backend
#: is a real accelerator with >1 device.
FLEET_ENV = "DEEQU_TPU_FLEET"

#: env var: minimum micro-batch rows before a STREAMING fold shards over
#: the tenant's sub-mesh (default 65536). Below it the single-chip
#: coalesced/fast paths win outright — sharding a 4096-row delta over the
#: ICI costs more in collective latency than the fold itself.
FLEET_STREAM_MIN_ROWS_ENV = "DEEQU_TPU_FLEET_STREAM_MIN_ROWS"
DEFAULT_FLEET_STREAM_MIN_ROWS = 1 << 16


_FLEET_ENV_WARNED = False


def fleet_enabled() -> bool:
    """Whether the fleet scheduler should run in this process (see module
    docstring for the default policy). Follows the warn-and-fallback
    convention: any value other than "0"/"1" warns once and keeps the
    default policy."""
    global _FLEET_ENV_WARNED
    raw = os.environ.get(FLEET_ENV)
    if raw is not None:
        value = raw.strip()
        if value == "0":
            return False
        if value == "1":
            import jax

            if len(jax.devices()) > 1:
                return True
            if not _FLEET_ENV_WARNED:
                _FLEET_ENV_WARNED = True
                _logger.warning(
                    "%s=1 but only one device is visible — the fleet "
                    "stays off (for a CPU drill also set XLA_FLAGS="
                    "--xla_force_host_platform_device_count=N)",
                    FLEET_ENV,
                )
            return False
        if not _FLEET_ENV_WARNED:
            _FLEET_ENV_WARNED = True
            _logger.warning(
                "ignoring invalid %s=%r (expected \"0\" or \"1\"); "
                "keeping the default accelerator-only policy",
                FLEET_ENV, raw,
            )
    import jax

    try:
        devices = jax.devices()
    except Exception:  # noqa: BLE001 - no backend -> no fleet
        return False
    return len(devices) > 1 and jax.default_backend() != "cpu"


def fleet_stream_min_rows() -> int:
    # registry-resolved (env override > tuned > static 65536): boot
    # calibration re-derives the sharding floor from this substrate's
    # measured dispatch cost
    from ..tuning import knobs

    return knobs.value("fleet_stream_min_rows")


def mesh_substrate() -> Dict[str, Any]:
    """What the mesh is MADE OF — recorded beside every scaling number so
    a CPU-virtual-device point can never be misread as an accelerator
    point (the r06 ``vs_baseline: 0.8`` lesson, satellite of ISSUE 12)."""
    import jax

    devices = jax.devices()
    backend = jax.default_backend()
    return {
        "substrate": "accelerator" if backend != "cpu" else "cpu-virtual",
        "backend": backend,
        "device_kind": devices[0].device_kind if devices else "none",
        "chip_count": len(devices),
    }


class SubMeshLease:
    """One tenant's grant of a device slice: the positions (indices into
    the fleet's device table), the device objects, and the packing
    generation it was cut from. ``mesh`` builds lazily and is shared per
    device tuple fleet-wide, so two leases of the same slice reuse one
    ``jax.sharding.Mesh`` (and therefore one compiled-program cache
    line)."""

    __slots__ = ("tenant", "positions", "devices", "generation", "_fleet")

    def __init__(self, tenant, positions, devices, generation, fleet):
        self.tenant = tenant
        self.positions: Tuple[int, ...] = tuple(positions)
        self.devices = tuple(devices)
        self.generation = int(generation)
        self._fleet = fleet

    @property
    def n_dev(self) -> int:
        return len(self.devices)

    @property
    def mesh(self):
        """The slice's 1-D row mesh, or None for a single-chip slice (a
        1-device mesh would engage the quantum/collective machinery for
        no benefit — single chip IS the escape-hatch path)."""
        if self.n_dev < 2:
            return None
        return self._fleet._mesh_for(self.devices)

    def __repr__(self) -> str:  # lease lines show up in trace events
        return (
            f"SubMeshLease({self.tenant!r}, devices={self.positions}, "
            f"gen={self.generation})"
        )


class FleetScheduler:
    """The device-mesh packing plane of the service.

    Thread-safe; every public method takes the internal lock. Packing is
    recomputed whenever the ACTIVE tenant set or the healthy device set
    changes: slice size = the largest power of two that gives every
    active tenant its own slice (floor 1), tenants assigned to slices in
    arrival order, wrapping when tenants outnumber slices. Leases are
    refcounted per tenant — a tenant leaves the active set (and frees its
    slice for re-packing) when its last concurrent job releases."""

    def __init__(self, metrics=None, devices: Optional[Sequence] = None):
        import jax

        from .metrics import ServiceMetrics

        self.metrics = metrics or ServiceMetrics()
        self._lock = threading.Lock()
        #: the full device table, fixed at construction (positions in every
        #: lease / loss report index into it)
        self._devices: List[Any] = list(
            devices if devices is not None else jax.devices()
        )
        #: positions still believed healthy (losses remove, never re-add —
        #: a chip that dropped off the ICI does not quietly come back; an
        #: operator restarts the service to reclaim it)
        self._healthy: List[int] = list(range(len(self._devices)))
        #: active tenants in arrival order (the packing order)
        self._members: List[str] = []
        self._refs: Dict[str, int] = {}
        #: tenant -> monotonic time of its last acquire/release: what the
        #: idle-TTL reclamation in _pack_locked reads
        self._last_seen: Dict[str, float] = {}
        #: tenant -> healthy positions of its current slice
        self._assignment: Dict[str, List[int]] = {}
        self._generation = 0
        self.repacks = 0
        #: one Mesh per device tuple: program caches key on the exact
        #: device tuple, so reusing the Mesh object keeps warm programs
        #: warm across leases of the same slice
        self._meshes: Dict[Tuple, Any] = {}
        m = self.metrics
        m.describe(
            "deequ_service_fleet_leases_total",
            "Sub-mesh leases granted to tenant jobs by the fleet "
            "scheduler, labeled by slice device count.",
        )
        m.describe(
            "deequ_service_fleet_repacks_total",
            "Fleet re-packings (tenant membership change or shard loss "
            "re-pack over the surviving devices).",
        )
        m.describe(
            "deequ_service_fleet_shard_losses_total",
            "Devices marked unhealthy fleet-wide after a shard dropped "
            "out of the elastic ladder.",
        )
        m.set_gauge_fn(
            "deequ_service_fleet_healthy_devices",
            lambda: len(self._healthy),
            "Devices the fleet scheduler still packs tenants onto.",
        )
        m.set_gauge_fn(
            "deequ_service_fleet_active_tenants",
            lambda: len(self._members),
            "Tenants currently holding at least one sub-mesh lease.",
        )
        # the elastic layer names lost devices the moment a ladder walk
        # salvages them — subscribe so re-packing does not wait for the
        # scheduler's post-job harvest. Weakly: a torn-down service's
        # fleet must unhook itself instead of living forever in the
        # listener list (and mis-marking devices for a successor fleet)
        import weakref

        from ..parallel.elastic import (
            add_shard_loss_listener,
            remove_shard_loss_listener,
        )

        ref = weakref.ref(self)

        def _listener(devices, _ref=ref):
            fleet = _ref()
            if fleet is None:
                remove_shard_loss_listener(_listener)
                return
            fleet._on_elastic_loss(devices)

        self._listener = _listener
        add_shard_loss_listener(_listener)

    def close(self) -> None:
        from ..parallel.elastic import remove_shard_loss_listener

        remove_shard_loss_listener(self._listener)

    # -- packing -------------------------------------------------------------

    def _mesh_for(self, devices: Tuple):
        with self._lock:
            mesh = self._meshes.get(devices)
            if mesh is None:
                from ..parallel import make_mesh

                mesh = make_mesh(devices=list(devices))
                self._meshes[devices] = mesh
            return mesh

    @staticmethod
    def _slice_size(n_healthy: int, n_tenants: int) -> int:
        if n_healthy <= 0:
            return 0
        per = max(1, n_healthy // max(1, n_tenants))
        size = 1
        while size * 2 <= per:
            size *= 2
        return size

    def _cut_slices_locked(self, n_tenants: int) -> List[List[int]]:
        """The slice partition a packing over ``n_tenants`` would cut
        from the current healthy set (under the lock). ONE function
        serves both the real packing and peek's prediction, so the two
        can never disagree about slice geometry."""
        healthy = list(self._healthy)
        size = self._slice_size(len(healthy), n_tenants)
        if not size:
            return []
        return [
            healthy[i: i + size]
            for i in range(0, len(healthy) - size + 1, size)
        ]

    def _pack_locked(self) -> None:
        """Recompute the tenant -> slice assignment (under the lock).

        Transition semantics: re-packing changes FUTURE leases only —
        a job already running on its leased slice keeps it, so for the
        remainder of that job a newly-arrived tenant's slice can overlap
        the old packing's devices. Disjointness is a steady-state
        guarantee (and what the drills assert); making arrivals wait for
        every in-flight lease to drain would park new tenants behind
        arbitrarily long scans."""
        import time

        self._generation += 1
        self.repacks += 1
        # EVERY re-pack reaches the export plane (membership growth and
        # loss re-packs alike): ServiceMetrics has its own lock and never
        # calls back into the fleet, so this nesting cannot invert
        self.metrics.inc("deequ_service_fleet_repacks_total")
        # membership is sticky between jobs (a streaming tenant's refs
        # drop to zero between every fold — pruning on bare zero-ref
        # would evict LIVE tenants and collapse disjointness for
        # sequential workloads), so reclaim only tenants idle past the
        # TTL, and only when the packing changes anyway: a departed
        # tenant shrinks the others' slices at most until the next
        # natural re-pack after IDLE_TTL_S
        cutoff = time.monotonic() - self.IDLE_TTL_S
        self._members = [
            t for t in self._members
            if self._refs.get(t, 0) > 0
            or self._last_seen.get(t, cutoff) > cutoff
        ]
        # _last_seen entries for pruned tenants go with them: a standing
        # service seeing a new one-off tenant name per dataset must not
        # grow this map one float per name forever
        keep = set(self._members) | {
            t for t, n in self._refs.items() if n > 0
        }
        self._last_seen = {
            t: v for t, v in self._last_seen.items() if t in keep
        }
        self._assignment = {}
        slices = self._cut_slices_locked(len(self._members))
        if not slices:
            return
        for i, tenant in enumerate(self._members):
            self._assignment[tenant] = slices[i % len(slices)]

    def _lease_locked(self, tenant: str) -> SubMeshLease:
        positions = self._assignment.get(tenant, self._healthy[:1])
        return SubMeshLease(
            tenant, positions,
            [self._devices[p] for p in positions],
            self._generation, self,
        )

    # -- tenant-facing API ---------------------------------------------------

    #: seconds a zero-ref tenant survives in the packing before a
    #: re-pack may reclaim its slice (long enough that a streaming
    #: tenant's between-fold gaps never count as departure)
    IDLE_TTL_S = 300.0

    def acquire(self, tenant: str) -> SubMeshLease:
        """Lease the tenant's sub-mesh for one job/drain; pair with
        :meth:`release`. First lease of an unseen tenant re-packs."""
        import time

        with self._lock:
            self._refs[tenant] = self._refs.get(tenant, 0) + 1
            self._last_seen[tenant] = time.monotonic()
            if tenant not in self._assignment:
                if tenant not in self._members:
                    self._members.append(tenant)
                self._pack_locked()
            lease = self._lease_locked(tenant)
        self.metrics.inc(
            "deequ_service_fleet_leases_total", devices=str(lease.n_dev)
        )
        return lease

    def release(self, tenant: str) -> None:
        """Release one lease. Membership is STICKY: a tenant keeps its
        slice between jobs (streaming drains lease per sweep — re-packing
        on every release would oscillate slice sizes and churn compiled
        mesh shapes), so re-packs happen only on membership GROWTH and on
        shard loss. :meth:`evict_idle` reclaims slices of tenants that
        stopped submitting."""
        import time

        with self._lock:
            self._last_seen[tenant] = time.monotonic()
            n = self._refs.get(tenant, 0) - 1
            if n > 0:
                self._refs[tenant] = n
            else:
                self._refs.pop(tenant, None)

    def evict_idle(self) -> int:
        """Drop zero-ref tenants from the packing and re-pack NOW (an
        operator/maintenance hook). The hot paths reclaim lazily instead:
        `_pack_locked` prunes zero-ref members whenever a membership
        change or shard loss re-packs anyway, so a departed tenant can
        shrink the others' slices only until the next natural re-pack.
        Returns how many tenants were evicted."""
        with self._lock:
            idle = [t for t in self._members if self._refs.get(t, 0) <= 0]
            for t in idle:
                self._members.remove(t)
                self._assignment.pop(t, None)
                self._last_seen.pop(t, None)
            if idle:
                self._pack_locked()
            return len(idle)

    def peek(self, tenant: str) -> SubMeshLease:
        """The slice the CURRENT packing would grant this tenant, without
        taking a lease (submit-time warmth keys and warm closures use it;
        the pickup-time lease may differ if the fleet re-packed in
        between — warmth is advisory, so the cost is one background
        compile, never wrong reuse)."""
        with self._lock:
            if tenant in self._assignment:
                return self._lease_locked(tenant)
            # predict the EXACT slice _pack_locked would grant with this
            # tenant joined: same size rule, same arrival-order slot
            # (len(members) is the new tenant's index). Predicting
            # healthy[:size] instead would warm a pjit program for the
            # FIRST slice while acquire packs every non-first tenant
            # onto a different one — a deterministically wasted warm
            # plus a cold compile on the device tier
            slices = self._cut_slices_locked(len(self._members) + 1)
            positions: Sequence[int] = []
            if slices:
                positions = slices[len(self._members) % len(slices)]
            return SubMeshLease(
                tenant, positions,
                [self._devices[p] for p in positions],
                self._generation, self,
            )

    def devices_of(self, tenant: str) -> Tuple[int, ...]:
        """Healthy positions currently assigned to the tenant (tests use
        this to assert disjointness)."""
        with self._lock:
            return tuple(self._assignment.get(tenant, ()))

    # -- elasticity ----------------------------------------------------------

    def _on_elastic_loss(self, lost_devices: Sequence) -> None:
        """ElasticMeshFold salvage named these device objects lost."""
        positions = [
            i for i, d in enumerate(self._devices) if d in tuple(lost_devices)
        ]
        if positions:
            self.mark_unhealthy(positions)

    def mark_unhealthy(self, positions: Sequence[int]) -> None:
        dropped = []
        with self._lock:
            for p in positions:
                if p in self._healthy:
                    self._healthy.remove(p)
                    dropped.append(p)
            if dropped:
                self._pack_locked()
        if dropped:
            from ..observability import trace as _trace

            self.metrics.inc(
                "deequ_service_fleet_shard_losses_total", float(len(dropped))
            )
            _trace.add_event(
                "fleet_repack", dropped=dropped,
                healthy=len(self._healthy), tenants=len(self._members),
            )
            _logger.warning(
                "fleet re-pack: devices %s marked unhealthy, %d healthy "
                "remain, %d tenants re-packed",
                dropped, len(self._healthy), len(self._members),
            )

    def note_shard_loss(self) -> None:
        """A job's monitor reported shard losses without naming devices
        (pass-level GSPMD failures): probe the full device table and drop
        whatever fails to answer. The elastic listener path usually beat
        us here; probing again is cheap and idempotent."""
        from ..parallel.health import probe_devices

        with self._lock:
            candidates = [(p, self._devices[p]) for p in self._healthy]
        if len(candidates) < 2:
            return
        dead = probe_devices([d for _, d in candidates])
        if dead:
            self.mark_unhealthy([candidates[i][0] for i in dead])

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "healthy": list(self._healthy),
                "tenants": list(self._members),
                "assignment": {
                    t: list(p) for t, p in self._assignment.items()
                },
                "generation": self._generation,
                "repacks": self.repacks,
            }
