"""Cross-session fold coalescing: amortize the per-fold fixed cost.

PR 9's ingest soak found the streaming plane's ceiling is not bandwidth:
with 1000 concurrent sessions the box completed ~65 sessions/s because
every micro-batch fold paid ~50ms of FIXED cost — an engine pass (feed
thread, watchdog, one device program launch, packed state fetch) plus
scheduler/state plumbing — while the same box folds tens of millions of
rows per second through one session. This module amortizes that fixed
cost across sessions instead of paying it per session:

- **Tiny-delta host fast path.** A micro-batch below the measured
  per-analyzer-class crossover computes its delta state with the HOST
  kernels (`Analyzer.host_partial` — the same native kernels the engine's
  host ingest tier runs) and merges it algebraically into the session's
  persisted states through the serial path's own finalize machinery
  (`analysis_runner._finalize`): no engine pass, no device dispatch for
  the delta. Valid only for batteries whose states are
  IDENTITY-MERGE-TRANSPARENT (`analyzers.states.identity_merge_transparent`
  — the partial provably IS the batch's folded state at the bit level)
  with the default ``ingest_partial``; everything else routes onward.

- **Coalesced device folds.** Pending folds whose batteries share a PR-3
  signature bundle and pow2 batch bucket are stacked along a leading
  session axis and executed as ONE fused device program (``jax.vmap`` of
  the identical per-bundle update — `engine.fold_sessions_coalesced`),
  then de-multiplexed back into per-session states: W sessions pay one
  launch + one packed fetch. Per-session serial-key FIFO, atomic fold
  semantics and retry-safe memoization are preserved (a fold executes
  exactly once, its own job consumes the memoized outcome), and a fault
  inside a coalesced launch is isolated to the owning session(s) by
  bisecting the group (≤log2 W re-launches — the group-level analog of
  the battery bisection in `reliability.isolation`).

- **Crossover router.** `CrossoverRouter` picks the tier per fold from
  measured per-analyzer-class host rates (observed on every fast fold)
  against the measured device fixed cost (observed on every coalesced
  launch); ``DEEQU_TPU_FAST_PATH_MAX_ROWS`` overrides the measurement.

Knobs (watchdog warn-and-fallback convention, documented in config.py):

- ``DEEQU_TPU_COALESCE``: "0" disables the whole plane — every ingest
  takes exactly the pre-coalescing path (the true escape hatch).
- ``DEEQU_TPU_COALESCE_MAX_WIDTH``: sessions per coalesced launch
  (default 16; widths bucket to powers of two).
- ``DEEQU_TPU_FAST_PATH_MAX_ROWS``: fixed fast-path row ceiling
  (default -1 = use the measured crossover; 0 forces the device path).

Failure semantics: a fold that fails inside a launch fails ALONE with its
typed error (bisection quarantines it); the sibling sessions' folds
commit. Drift guards, contract capture and session bookkeeping run under
each session's serial lock exactly as on the serial path. Folds carrying
a job deadline are never cross-drained (their own job must observe the
deadline), and a fold is drained only after its job was ADMITTED, so
admission control and backpressure semantics are untouched.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

#: "0" disables coalescing AND the fast path entirely (exact escape hatch)
COALESCE_ENV = "DEEQU_TPU_COALESCE"
#: max sessions stacked into one coalesced device launch (pow2-bucketed)
COALESCE_MAX_WIDTH_ENV = "DEEQU_TPU_COALESCE_MAX_WIDTH"
DEFAULT_COALESCE_MAX_WIDTH = 16
#: fixed fast-path row ceiling; -1 = measured crossover, 0 = never fast
FAST_PATH_MAX_ROWS_ENV = "DEEQU_TPU_FAST_PATH_MAX_ROWS"


def coalesce_enabled() -> bool:
    from ..utils import env_flag

    return env_flag(COALESCE_ENV, True)


def coalesce_max_width() -> int:
    # registry-resolved (env override > tuned > static 16): the boot
    # profile and the online controller can move the stack width, the
    # operator env still always wins
    from ..tuning import knobs

    return knobs.value("coalesce_max_width")


#: fast-route drains may run far wider than a device stack: they execute
#: sequentially on ONE worker (memory is one micro-batch at a time, not
#: width x bucket of stacked features), and the wider the run the fewer
#: GIL handoffs per fold — measured on the 1000-session soak: width 16 ->
#: 330 sessions/s, width 128 -> 484. Bounded so one worker's drain can
#: never hold more than this many sessions' folds at once; the device
#: stack keeps the (memory-relevant) DEEQU_TPU_COALESCE_MAX_WIDTH bound.
_FAST_DRAIN_WIDTH = 512


def fast_path_max_rows() -> int:
    from ..tuning import knobs

    return knobs.value("fast_path_max_rows")


class CrossoverRouter:
    """Fast-path vs device-path routing from MEASURED rates.

    The host fast path costs ``sum_over_analyzers(rows / host_rate[cls])``
    — per-analyzer-class rates observed on every fast fold (EWMA), seeded
    with a conservative default for classes never measured. The device
    path costs a FIXED launch+fetch overhead (observed per coalesced
    launch, amortized over its width) plus a per-row term. Below the
    crossover the host kernels win outright; above it the device's
    throughput pays for its fixed cost. ``DEEQU_TPU_FAST_PATH_MAX_ROWS``
    replaces the model with a hard ceiling (0 = always device, useful to
    force the coalesced path in tests)."""

    #: seed rows/s per analyzer class before any measurement (native block
    #: kernels measure 30-200M rows/s; seeding LOW biases early folds to
    #: the device path only for very large batches, which is safe). These
    #: class attributes mirror the registry's static defaults
    #: (tuning/knobs.py); live seeds resolve through the registry so a
    #: calibration profile replaces them with this substrate's measured
    #: rates.
    DEFAULT_HOST_ROWS_PER_S = 20e6
    #: seed device fixed seconds (PR 9 measured ~50ms/fold end to end; the
    #: launch+fetch core of it is what this models)
    DEFAULT_DEVICE_FIXED_S = 0.02
    DEFAULT_DEVICE_ROWS_PER_S = 100e6
    _ALPHA = 0.2  # EWMA weight of the newest observation

    def __init__(self):
        self._lock = threading.Lock()
        self._host_rate: Dict[type, float] = {}
        self._default_host_rate = self.DEFAULT_HOST_ROWS_PER_S
        self._device_fixed_s = self.DEFAULT_DEVICE_FIXED_S
        self._device_rows_per_s = self.DEFAULT_DEVICE_ROWS_PER_S
        self._device_measured = False
        self.reseed_from_knobs()

    def reseed_from_knobs(self) -> None:
        """Pull cost-model seeds from the tuning registry. With autotune
        off (or nothing tuned) the registry returns the class defaults —
        byte-identical behavior. A calibration profile replaces the seeds
        only; the per-class EWMAs already measured stay authoritative."""
        from ..tuning import knobs

        with self._lock:
            self._default_host_rate = knobs.value("router_host_rows_per_s")
            self._device_rows_per_s = knobs.value("router_device_rows_per_s")
            if not self._device_measured:
                # an unmeasured fixed cost re-seeds too; once live launches
                # have refined it, the EWMA wins over any profile
                self._device_fixed_s = knobs.value("router_device_fixed_s")

    def observe_host(self, cls: type, rows: int, seconds: float) -> None:
        if seconds <= 0 or rows <= 0:
            return
        rate = rows / seconds
        with self._lock:
            prev = self._host_rate.get(cls)
            self._host_rate[cls] = (
                rate if prev is None
                else prev + self._ALPHA * (rate - prev)
            )

    def observe_device(self, rows: int, seconds: float, folds: int) -> None:
        """One coalesced launch of ``folds`` sessions x ``rows`` rows each
        took ``seconds``: its per-fold fixed share updates the model."""
        if seconds <= 0 or folds <= 0:
            return
        per_fold = seconds / folds
        with self._lock:
            fixed = max(per_fold - rows / self._device_rows_per_s, 1e-4)
            self._device_fixed_s += self._ALPHA * (fixed - self._device_fixed_s)
            self._device_measured = True

    def host_seconds(self, classes, rows: int) -> float:
        with self._lock:
            return sum(
                rows / self._host_rate.get(cls, self._default_host_rate)
                for cls in classes
            )

    def device_seconds(self, rows: int) -> float:
        with self._lock:
            return (
                self._device_fixed_s + rows / self._device_rows_per_s
            )

    def crossover_rows(self, classes) -> int:
        """Rows where the modeled host cost overtakes the device cost for
        a battery of these analyzer classes (the PERF.md table's value)."""
        with self._lock:
            per_row_host = sum(
                1.0 / self._host_rate.get(cls, self._default_host_rate)
                for cls in classes
            )
            margin = per_row_host - 1.0 / self._device_rows_per_s
            if margin <= 0:
                return 1 << 62  # host never loses
            return int(self._device_fixed_s / margin)

    def route(self, plan: "FoldPlan", rows: int) -> str:
        if not plan.fast_ok:
            return "device"
        override = fast_path_max_rows()
        if override >= 0:
            return "fast" if rows <= override else "device"
        classes = [type(a) for a in plan.battery]
        if self.host_seconds(classes, rows) <= self.device_seconds(rows):
            return "fast"
        return "device"


class FoldPlan:
    """Per-(session, schema) eligibility plan: the deduped battery, its
    feature machinery and the signature half of the coalesce key. Built
    once per session schema; ``None`` from :func:`build_fold_plan` means
    the serial path must run (grouping sets, host accumulators,
    precondition failures, feature-validation failures — everything whose
    degradation semantics live in the full runner)."""

    __slots__ = (
        "battery", "columns", "fast_ok", "mesh_ok", "signatures", "_builder",
    )

    def __init__(self, battery, columns, fast_ok, signatures, mesh_ok=False):
        self.battery = battery
        self.columns = columns
        self.fast_ok = fast_ok
        #: may this battery's folds shard over a fleet sub-mesh? Requires
        #: host partials (the shard-local fold feeds `sharded_ingest_fold`
        #: with per-slice partial states)
        self.mesh_ok = mesh_ok
        self.signatures = signatures
        self._builder = None

    def orchestrator(self):
        """This battery's bundled scan program (engine-cached)."""
        from ..runners.engine import _fused_program

        return _fused_program(self.battery, None)

    def builder(self):
        from ..runners.features import FeatureBuilder

        if self._builder is None:
            self._builder = FeatureBuilder(
                [s for a in self.battery for s in a.feature_specs()]
            )
        return self._builder


def build_fold_plan(analyzers, schema) -> Optional[FoldPlan]:
    """Eligibility in one pass; mirrors the runner's split so a fold this
    plan serves computes exactly what `do_analysis_run` would."""
    import jax

    from ..analyzers.base import (
        Preconditions,
        ScanShareableAnalyzer,
    )
    from ..analyzers.grouping import GroupingAnalyzer
    from ..analyzers.states import identity_merge_transparent
    from ..runners.engine import _scan_signature
    from ..runners.features import FeatureBuilder, dry_run_batch

    battery: List[Any] = []
    seen = set()
    for a in analyzers:
        if a in seen:
            continue
        seen.add(a)
        battery.append(a)
    if not battery:
        return None
    for a in battery:
        if not isinstance(a, ScanShareableAnalyzer):
            return None
        if isinstance(a, GroupingAnalyzer):
            return None
        if getattr(a, "host_exclusive", False):
            return None
        if Preconditions.find_first_failing(schema, a.preconditions()):
            return None
    dry = dry_run_batch(schema)
    specs: List[Any] = []
    for a in battery:
        try:
            FeatureBuilder(a.feature_specs()).build(dry)
        except Exception:  # noqa: BLE001 - serial path owns degradation
            return None
        specs.extend(a.feature_specs())
    if any(spec.kind == "pred" for spec in specs):
        columns = None  # predicates may read arbitrary columns
    else:
        cols = {spec.column for spec in specs if spec.column is not None}
        columns = [c for c in schema.names if c in cols]
    fast_ok = all(
        a.supports_host_partial
        and type(a).ingest_partial is ScanShareableAnalyzer.ingest_partial
        and identity_merge_transparent(
            type(jax.eval_shape(a.init_state))
        )
        for a in battery
    )
    # the fleet's shard-local stream fold computes per-slice HOST partials
    # and folds them over the sub-mesh — any host-partial-capable battery
    # qualifies (identity-merge transparency is NOT required: the
    # butterfly merge is the same semigroup merge the engine's host tier
    # already runs under a mesh)
    mesh_ok = all(a.supports_host_partial for a in battery)
    battery = tuple(battery)
    return FoldPlan(
        battery, columns, fast_ok,
        tuple(_scan_signature(a) for a in battery),
        mesh_ok=mesh_ok,
    )


#: pending-fold states
_ENQ, _CLAIMED, _DONE = 0, 1, 2


def _job_tag(pending) -> str:
    """The stream_fold chaos-site tag: the fold's job id when known (the
    serial path's tag), else the session key."""
    handle = pending.handle
    return handle.job_id if handle is not None else (
        f"{pending.skey[0]}/{pending.skey[1]}"
    )


class _PendingFold:
    __slots__ = (
        "session", "skey", "data", "bucket", "plan", "route", "key",
        "drainable", "monitor", "done", "event", "state", "result", "error",
        "submitted", "harvested", "handle", "signature", "tuning_arm",
    )

    def __init__(self, session, data, bucket, plan, route, key, drainable):
        from ..runners.engine import RunMonitor

        self.session = session
        self.skey = (session.tenant, session.dataset)
        self.data = data
        self.bucket = bucket
        self.plan = plan
        self.route = route
        self.key = key
        self.drainable = drainable
        self.monitor = RunMonitor()
        self.done: dict = {}
        self.event = threading.Event()
        self.state = _ENQ
        self.result = None
        self.error: Optional[BaseException] = None
        self.submitted = False
        self.harvested = False
        self.handle = None      # the scheduler JobHandle, from mark_submitted
        self.signature = ()     # the job's placement signature (device route)
        self.tuning_arm = None  # knob name when shadow-routed by tuning


class FoldCoalescer:
    """The service's cross-session fold batching plane."""

    #: seconds a job waits on a fold claimed by another worker's launch
    #: before declaring the launch lost (launches always complete their
    #: claims, even on BaseException — this is a deadlock backstop)
    CLAIM_WAIT_S = 600.0

    #: sentinel distinguishing "plan computed: ineligible" from "never
    #: computed" in the shared plan cache
    _NO_PLAN = object()

    def __init__(self, service):
        from ..utils import BoundedLRU

        self.service = service
        self.router = CrossoverRouter()
        self._lock = threading.Lock()
        #: (battery tuple, schema fingerprint) -> FoldPlan | _NO_PLAN.
        #: SHARED across sessions: a 1000-session fleet running the same
        #: checks builds ONE plan, not 1000 (plan construction — dry-run
        #:  feature validation + eval_shape per analyzer — was a measured
        #: chunk of first-fold latency at fleet scale)
        self._plan_cache = BoundedLRU(512)
        #: coalesce key -> deque of device-routed pending folds, enqueue
        #: order == per-session submission order (ingest holds the
        #: session's submit lock across enqueue+submit)
        self._queues: Dict[Tuple, deque] = {}
        #: sessions with a fold currently CLAIMED: a drain never takes a
        #: second fold of a session whose previous fold is still in
        #: flight, so per-session folds execute strictly one at a time,
        #: in FIFO order (atomic fold semantics under coalescing)
        self._inflight: set = set()
        #: keys with an ACTIVE drain loop (the flat-combining discipline):
        #: while one worker sweeps a key's queue, sibling jobs for that
        #: key PARK on their fold's event instead of starting competing
        #: claims — the drainer picks their folds up on its next sweep.
        #: Restores the accumulate-and-drain rhythm that makes one busy
        #: thread faster than eight contending ones on GIL-bound
        #: micro-folds (measured: 1 worker 1100 sessions/s vs 8 workers
        #: 440 before this discipline).
        self._draining: set = set()
        #: session key -> deque of that session's DRAINABLE pendings in
        #: submission order: a cross-drain may only claim a session's
        #: HEAD fold, so per-session FIFO holds even when a session's
        #: folds land under DIFFERENT coalesce keys (varying micro-batch
        #: buckets) — a drain on key B must not execute fold #2 while
        #: fold #1 (key A) is still outstanding
        self._session_fifo: Dict[Tuple, deque] = {}
        #: session key -> count of outstanding folds a drain cannot see
        #: (serial-path folds, non-drainable pendings): while positive,
        #: the session's drainable folds execute only via their own
        #: serial-key-ordered jobs, never a cross-drain — closing the
        #: ordering hole between a queued serial fold and a later
        #: drainable one. A barrier that leaks (a deadline'd job timing
        #: out in queue without running) only degrades that session to
        #: own-job execution; it can never reorder or lose a fold.
        self._serial_barrier: Dict[Tuple, int] = {}
        m = service.metrics
        m.describe(
            "deequ_service_coalesced_folds_total",
            "Streaming folds executed inside a cross-session coalesced "
            "device launch (stacked along a leading session axis).",
        )
        m.describe(
            "deequ_service_fast_path_folds_total",
            "Streaming folds served by the tiny-delta host fast path "
            "(host-kernel delta + algebraic merge; no engine pass).",
        )
        m.describe(
            "deequ_service_fold_route_total",
            "Streaming fold routing decisions, by route "
            "(fast/device/serial).",
        )
        m.describe(
            "deequ_service_coalesce_width_total",
            "Coalesced launches by pow2 width bucket (a width histogram: "
            "width=1 launches found no peers to amortize with).",
        )
        m.describe(
            "deequ_service_coalesce_width_sum",
            "Sum of coalesced-launch widths (divide by launch count for "
            "the mean amortization factor).",
        )
        m.describe(
            "deequ_service_coalesce_quarantined_total",
            "Folds isolated to a typed failure by coalesced-launch "
            "bisection while their group siblings committed.",
        )
        m.describe(
            "deequ_service_fleet_stream_folds_total",
            "Streaming folds sharded over a fleet sub-mesh (shard-local "
            "states, butterfly merge at the drain boundary), labeled by "
            "tenant and slice device count.",
        )
        m.describe_histogram(
            "deequ_service_coalesce_flush_seconds",
            "Wall time of the coalesced drain that flushed each pending "
            "fold, per tenant and priority class (pow2 buckets, seconds).",
        )

    # -- ingest-side API -----------------------------------------------------

    def plan_for(self, analyzers, schema, fingerprint) -> Optional[FoldPlan]:
        key = (tuple(analyzers), fingerprint)
        plan = self._plan_cache.get(key)
        if plan is None:
            plan = build_fold_plan(analyzers, schema)
            self._plan_cache[key] = (
                plan if plan is not None else self._NO_PLAN
            )
        return None if plan is self._NO_PLAN else plan

    def prepare(
        self, session, data, bucket: int, *, drainable: bool = True
    ) -> Optional[_PendingFold]:
        """Route one micro-batch fold, or None -> the serial path (exact
        pre-coalescing behavior). Called with the session's submit lock
        held; the returned fold must then be `mark_submitted` after the
        scheduler admitted its job, or `abandon`-ed if admission shed."""
        if not coalesce_enabled() or self.service.mesh is not None:
            return None
        rows = int(data.num_rows)
        if rows > bucket:
            return None  # multi-batch folds keep the streaming engine path
        plan = session._coalesce_plan(data)
        if plan is None:
            self.service.metrics.inc(
                "deequ_service_fold_route_total", route="serial"
            )
            return None
        route = self.router.route(plan, rows)
        fleet_forced = False
        if route == "fast" and self._fleet_stream_eligible(
            plan, rows, tenant=session.tenant
        ):
            # the fleet's sharding contract outranks the crossover model:
            # a delta at/above DEEQU_TPU_FLEET_STREAM_MIN_ROWS must reach
            # the mesh drain path (which lives on the device route), or
            # the knob would be unreachable for exactly the fast-capable
            # batteries it was documented for
            route = "device"
            fleet_forced = True
        tuning_arm = None
        controller = getattr(self.service, "tuning_controller", None)
        if controller is not None and plan.fast_ok and not fleet_forced:
            shadow = controller.choose(rows)
            if shadow is not None:
                # this fold measures the CANDIDATE fast-path ceiling: route
                # it the way the candidate would (the fleet contract above
                # still outranks any candidate)
                tuning_arm = "fast_path_max_rows"
                route = "fast" if shadow == "host" else "device"
        key = (route,) + plan.signatures + (bucket,)
        pending = _PendingFold(
            session, data, bucket, plan, route, key, drainable
        )
        pending.tuning_arm = tuning_arm
        self.service.metrics.inc(
            "deequ_service_fold_route_total", route=route
        )
        # BOTH routes enqueue for cross-session draining: device folds
        # stack into one vmapped launch; fast folds run back-to-back on
        # the draining worker — one job pickup executes K folds while the
        # K-1 sibling jobs degenerate to memoized-result consumption.
        # Under the GIL, K tiny folds on ONE thread beat K workers
        # fighting over them (measured: 8 workers ran the 1000-session
        # soak SLOWER than 1 before draining). NON-drainable folds
        # (deadline'd) never enter the drain queue — they execute only
        # under their own job, and a job a deadline kills in the queue
        # must not leave a claimable fold behind; they raise the
        # session's serial barrier instead so later drainable folds
        # cannot overtake them.
        with self._lock:
            if pending.drainable:
                q = self._queues.get(key)
                if q is None:
                    q = deque()
                    self._queues[key] = q
                while q and q[0].state == _DONE:
                    q.popleft()  # lazily prune consumed entries
                q.append(pending)
                fifo = self._session_fifo.get(pending.skey)
                if fifo is None:
                    fifo = deque()
                    self._session_fifo[pending.skey] = fifo
                fifo.append(pending)
            else:
                self._serial_barrier[pending.skey] = (
                    self._serial_barrier.get(pending.skey, 0) + 1
                )
        return pending

    def note_serial_fold(self, session) -> bool:
        """A fold of this session is taking the SERIAL path (ineligible
        battery, multi-batch, …): raise its barrier so no later drainable
        fold of the session can be cross-drained ahead of it. Returns
        whether a barrier was raised (the caller clears it when the
        serial fold's job body runs)."""
        if not coalesce_enabled() or self.service.mesh is not None:
            return False
        skey = (session.tenant, session.dataset)
        with self._lock:
            self._serial_barrier[skey] = (
                self._serial_barrier.get(skey, 0) + 1
            )
        return True

    def clear_serial_barrier(self, skey: Tuple) -> None:
        with self._lock:
            n = self._serial_barrier.get(skey, 0) - 1
            if n > 0:
                self._serial_barrier[skey] = n
            else:
                self._serial_barrier.pop(skey, None)

    def mark_submitted(
        self, pending: _PendingFold, handle=None, signature=()
    ) -> None:
        with self._lock:
            pending.handle = handle
            pending.signature = signature
            pending.submitted = True

    def abandon(self, pending: _PendingFold) -> None:
        """Admission shed the fold's job before it was ever runnable."""
        with self._lock:
            q = self._queues.get(pending.key)
            if q is not None:
                try:
                    q.remove(pending)
                except ValueError:
                    pass
            if pending.drainable:
                self._fifo_remove_locked(pending)
            else:
                n = self._serial_barrier.get(pending.skey, 0) - 1
                if n > 0:
                    self._serial_barrier[pending.skey] = n
                else:
                    self._serial_barrier.pop(pending.skey, None)
            pending.state = _DONE

    def _fifo_remove_locked(self, pending: _PendingFold) -> None:
        fifo = self._session_fifo.get(pending.skey)
        if fifo is None:
            return
        if fifo and fifo[0] is pending:
            fifo.popleft()
        else:
            try:
                fifo.remove(pending)
            except ValueError:
                pass
        if not fifo:
            self._session_fifo.pop(pending.skey, None)

    # -- scheduler job body --------------------------------------------------

    #: how long a job parks on an active drainer before re-checking (the
    #: drainer may have exited between the check and the wait — the loop
    #: in run_fold then claims the fold itself; this is a liveness
    #: backstop, not a scheduling interval)
    _DRAIN_RECHECK_S = 0.2

    #: empty-sweep linger: how many times (x how long) a drainer waits
    #: for the feeders to refill its key before giving the drain up
    _DRAIN_LINGER_TRIES = 2
    _DRAIN_LINGER_S = 0.001

    def run_fold(self, ctx, pending: _PendingFold):
        """The job body for one pending fold: drive a drain loop over its
        key (claiming peers as they accumulate), park while another worker
        is already draining the key, or consume the outcome an earlier
        sweep produced for it."""
        if ctx.attempt > 1:
            # the scheduler decided to RETRY this fold: a memoized FAILURE
            # must re-execute (the serial path's done-dict memoizes only
            # committed results — failed attempts re-run), so re-arm the
            # fold; a memoized committed RESULT stays memoized, exactly
            # like the serial retry contract
            with self._lock:
                if pending.state == _DONE and pending.error is not None:
                    pending.state = _ENQ
                    pending.error = None
                    pending.result = None
                    pending.event.clear()
                    pending.harvested = False
                    # restore the ordering bookkeeping the failed
                    # attempt's completion released, so the retry's own
                    # completion balances it and no later fold of the
                    # session can cross-drain ahead of the retry
                    if pending.drainable:
                        fifo = self._session_fifo.get(pending.skey)
                        if fifo is None:
                            fifo = deque()
                            self._session_fifo[pending.skey] = fifo
                        fifo.appendleft(pending)
                    else:
                        self._serial_barrier[pending.skey] = (
                            self._serial_barrier.get(pending.skey, 0) + 1
                        )
        deadline = time.monotonic() + self.CLAIM_WAIT_S
        while pending.state != _DONE:
            group = None
            parked = False
            with self._lock:
                if pending.state == _ENQ:
                    if pending.key in self._draining and pending.drainable:
                        # a sibling worker is sweeping this key: park —
                        # its next sweep picks this fold up; contending
                        # with it would just shred the GIL
                        parked = True
                    else:
                        group = self._claim_group_locked(pending)
                        if pending.drainable:
                            self._draining.add(pending.key)
            if group is not None:
                if pending.drainable:
                    # while this drain runs, its key's queued jobs stay
                    # queued (the scheduler's _eligible defers them): the
                    # sweep executes their folds and finish_absorbed
                    # retires the jobs in bulk — no worker ever wakes
                    # just to read a memo
                    self.service.scheduler.defer_pickup(pending.key)
                try:
                    linger = 0
                    while group:
                        self._execute_group(group)
                        # bulk-retire the sibling jobs whose folds this
                        # sweep executed while they sat queued — they
                        # never occupy a worker (finish_absorbed)
                        self._absorb(ctx, group, skip=pending)
                        if not pending.drainable:
                            break
                        with self._lock:
                            group = self._claim_sweep_locked(pending.key)
                        if not group and linger < self._DRAIN_LINGER_TRIES:
                            # an empty sweep usually means the feeders are
                            # a millisecond behind, not done: LINGER
                            # briefly before abandoning the drain — an
                            # exiting drainer flips the key back into the
                            # many-small-claims mode whose GIL handoffs
                            # this loop exists to avoid
                            linger += 1
                            time.sleep(self._DRAIN_LINGER_S)
                            with self._lock:
                                group = self._claim_sweep_locked(pending.key)
                        if group:
                            linger = 0
                finally:
                    with self._lock:
                        self._draining.discard(pending.key)
                    if pending.drainable:
                        self.service.scheduler.resume_pickup(pending.key)
                break
            if parked or pending.state == _CLAIMED:
                pending.event.wait(self._DRAIN_RECHECK_S)
            if time.monotonic() > deadline and pending.state != _DONE:
                # a wedged drain held this fold past the liveness
                # backstop: resolve the fold itself with the typed error
                # (removing it from queue/fifo so no later sweep can
                # execute a fold the caller was told failed; execution
                # loops also skip DONE folds, so a drain that un-wedges
                # cannot double-fold it)
                self._complete(pending, error=RuntimeError(
                    f"coalesced launch holding fold for {pending.skey} "
                    f"did not complete within {self.CLAIM_WAIT_S:.0f}s"
                ))
                break
        return self._consume(ctx, pending)

    def _absorb(self, ctx, group: List[_PendingFold], skip: _PendingFold):
        """Hand the drained folds' outcomes to the scheduler so their
        still-queued jobs finish in ONE batched pass (the drainer's own
        fold is excluded — its running job returns the result itself).
        Jobs already picked up are left alone; their run consumes the
        memoized outcome, so marking ``harvested`` stays with whichever
        path actually exports the monitor. Only SUCCESS outcomes are
        absorbed: a failed fold's job must run normally so the
        scheduler's retry machinery (and the retry re-arm in run_fold)
        keeps the serial path's semantics."""
        entries = []
        for f in group:
            if (
                f is skip or f.handle is None or f.harvested
                or f.error is not None
            ):
                continue
            entries.append(
                (f.handle, f.result, f.error, f.skey[0], f.monitor,
                 f.signature, ctx.worker_id)
            )
        if entries:
            self.service.scheduler.finish_absorbed(entries)

    def _consume(self, ctx, pending: _PendingFold):
        if not pending.harvested:
            # once per fold, whichever attempt consumes it: the fold-local
            # monitor's costs reach the export plane through THIS job's
            # harvest, attributed to the tenant that submitted the fold
            pending.harvested = True
            ctx.monitor.merge_from(pending.monitor)
        if pending.error is not None:
            raise pending.error
        return pending.result

    # -- claiming ------------------------------------------------------------

    def _claim_group_locked(self, pending: _PendingFold) -> List[_PendingFold]:
        pending.state = _CLAIMED
        self._inflight.add(pending.skey)
        group = [pending]
        if pending.drainable:
            group.extend(self._drain_locked(pending.key, {pending.skey}))
        return group

    def _claim_sweep_locked(self, key: Tuple) -> List[_PendingFold]:
        """One more sweep of the drain loop: whatever accumulated for this
        key while the previous group executed."""
        return self._drain_locked(key, set())

    def _drain_locked(self, key: Tuple, seen_sessions: set) -> List[_PendingFold]:
        group: List[_PendingFold] = []
        q = self._queues.get(key)
        if not q:
            return group
        route = key[0]
        width = (
            max(_FAST_DRAIN_WIDTH, coalesce_max_width())
            if route == "fast"
            else coalesce_max_width()
        )
        keep: List[_PendingFold] = []
        already = len(seen_sessions)  # folds the caller claimed before us
        while q and already + len(group) < width:
            f = q.popleft()
            if f.state != _ENQ:
                continue  # claimed/consumed entries just drop out
            fifo = self._session_fifo.get(f.skey)
            if (
                not f.submitted
                or not f.drainable
                or f.skey in seen_sessions
                or f.skey in self._inflight
                # per-session FIFO across COALESCE KEYS: only the
                # session's oldest outstanding fold may cross-drain (an
                # older fold may sit under a different bucket's key), and
                # never past an outstanding serial-path/deadline'd fold
                # (the barrier)
                or fifo is None
                or not fifo
                or fifo[0] is not f
                or self._serial_barrier.get(f.skey, 0)
            ):
                keep.append(f)  # stays queued for a later drain
                continue
            f.state = _CLAIMED
            self._inflight.add(f.skey)
            seen_sessions.add(f.skey)
            group.append(f)
        for f in reversed(keep):
            q.appendleft(f)
        if not q:
            # a service cycling through many distinct batteries must not
            # grow the key map monotonically on empty deques
            self._queues.pop(key, None)
        return group

    def _complete_locked(
        self, pending: _PendingFold, result=None,
        error: Optional[BaseException] = None,
    ) -> bool:
        """Under ``self._lock``: resolve a fold and release its ordering
        bookkeeping; returns whether this call resolved it (False = it
        was already DONE)."""
        if pending.state == _DONE:
            return False  # a claim-wait failure already resolved it
        pending.result = result
        pending.error = error
        pending.state = _DONE
        self._inflight.discard(pending.skey)
        if pending.drainable:
            self._fifo_remove_locked(pending)
        else:
            n = self._serial_barrier.get(pending.skey, 0) - 1
            if n > 0:
                self._serial_barrier[pending.skey] = n
            else:
                self._serial_barrier.pop(pending.skey, None)
        return True

    def _complete(
        self, pending: _PendingFold, result=None,
        error: Optional[BaseException] = None,
    ) -> None:
        with self._lock:
            self._complete_locked(pending, result=result, error=error)
        pending.event.set()

    def reconcile_orphan(self, ctx, pending: _PendingFold, exc):
        """The fold's job is terminating WITHOUT run_fold having run to
        completion — a worker fault between pickup and the body, an
        infrastructure error, a queued-past-deadline kill. Make the
        fold's COMMIT and its job's FINISH atomic:

        - an UNCLAIMED fold is withdrawn (resolved failed, out of
          queue/fifo/barrier) so no later drain can execute a fold whose
          caller was told it failed — the orphan leak that broke the
          chaos soak's stream_fold_parity (a drain would commit the
          batch on the session's NEXT ingest, after the failure, and out
          of order);
        - a CLAIMED fold waits out the drain that owns it (drains always
          complete their claims) and the job ADOPTS the outcome: a
          committed fold makes the job succeed with the committed
          result.

        Returns None (nothing adopted; fail with the original error) or
        the fold's ``(result, error)`` outcome. Wired as the fold job's
        scheduler ``recover_fn``; a scheduler RETRY re-arms a withdrawn
        fold exactly like any memoized failure (run_fold's attempt>1
        path)."""
        with self._lock:
            if pending.state == _ENQ:
                self._complete_locked(pending, error=exc)
                withdrawn = True
            else:
                withdrawn = False
        if withdrawn:
            pending.event.set()
            return None
        # claimed (or already done): the drain owns the outcome
        deadline = time.monotonic() + self.CLAIM_WAIT_S
        while pending.state != _DONE and time.monotonic() < deadline:
            pending.event.wait(self._DRAIN_RECHECK_S)
        if pending.state != _DONE:
            self._complete(pending, error=RuntimeError(
                f"coalesced launch holding fold for {pending.skey} "
                f"did not complete within {self.CLAIM_WAIT_S:.0f}s"
            ))
        if not pending.harvested:
            pending.harvested = True
            if ctx is not None:
                ctx.monitor.merge_from(pending.monitor)
        return (pending.result, pending.error)

    # -- execution -----------------------------------------------------------

    def _execute_group(self, group: List[_PendingFold]) -> None:
        from ..observability import trace as _trace

        flush_t0 = time.perf_counter()
        try:
            if group[0].route == "fast":
                if len(group) > 1:
                    self._note_width(len(group))
                # ONE span per drain with a per-session child event each
                # (a full span per fold was 4 trace-ring appends per fold
                # — measurable at thousands of folds/s); the singleton
                # case keeps the same shape so traces read uniformly
                with _trace.span(
                    "fast_drain", kind="coalesce", width=len(group)
                ) as sp:
                    for f in group:
                        if f.state == _DONE:
                            continue  # claim-wait backstop resolved it
                        result, error = self._execute_fast(f, sp)
                        if f.state != _DONE:
                            self._complete(f, result=result, error=error)
            else:
                self._execute_device(group)
        except BaseException as exc:
            # backstop: a launch must ALWAYS complete its claims, or jobs
            # waiting on them would hang until the claim-wait deadline
            for f in group:
                if f.state != _DONE:
                    self._complete(f, error=exc)
            raise
        finally:
            for f in group:
                if f.state != _DONE:
                    self._complete(f, error=RuntimeError(
                        "coalesced launch dropped a claimed fold"
                    ))
            flush_s = time.perf_counter() - flush_t0
            metrics = self.service.metrics
            for f in group:
                metrics.observe(
                    "deequ_service_coalesce_flush_seconds", flush_s,
                    tenant=f.skey[0],
                    priority=getattr(
                        f.session.priority, "name", str(f.session.priority)
                    ).lower(),
                )

    def _serial_fallback(self, pending: _PendingFold, data, pending_contract):
        """A guard outcome only the full runner can honor (drift-degraded
        columns): run this fold through `do_verification_run` exactly like
        the serial path, under the session lock already held by the
        caller."""
        from ..verification import VerificationSuite

        session = pending.session
        result = VerificationSuite.do_verification_run(
            data,
            session.checks,
            session.required_analyzers,
            aggregate_with=session.provider,
            save_states_with=session.provider,
            batch_size=pending.bucket,
            monitor=pending.monitor,
            sharding=self.service.mesh,
        )
        session._commit_fold(result, data, pending_contract, pending.done)
        return result

    @staticmethod
    def _host_finalize(analyzer, delta, provider):
        """`_finalize` with the device round trip removed: the fast path's
        states are identity-merge transparent, so `states.host_merge`
        computes the same bits as the compiled merge with numpy scalar
        ops — zero device dispatch on the whole load->merge->persist->
        metric cycle."""
        from ..analyzers.states import host_merge

        try:
            loaded = provider.load(analyzer)
            state = delta if loaded is None else host_merge(loaded, delta)
            provider.persist(analyzer, state)
            return analyzer.compute_metric_from(state)
        except Exception as exc:  # noqa: BLE001 - typed Failure metric
            return analyzer.to_failure_metric(exc)

    def _finalize_states(self, pending: _PendingFold, states) -> Any:
        """Merge one coalesced-device fold's delta states into the
        session's persisted states and evaluate its checks — the serial
        path's own finalize (`_finalize`: load -> merge_states_batched ->
        persist -> metric), so cumulative state handling is identical to
        an uncoalesced run. (The fast path's finalize lives inline in
        `_execute_fast`, swapping the merge for the bit-equal numpy
        `states.host_merge`.)"""
        from ..runners.analysis_runner import _finalize
        from ..runners.context import AnalyzerContext
        from ..verification import VerificationSuite

        session = pending.session
        provider = session.provider
        with pending.monitor.timed("metric_derivation"):
            metrics = {
                a: _finalize(a, s, provider, provider)
                for a, s in zip(pending.plan.battery, states)
            }
        result = VerificationSuite.evaluate(
            session.checks, AnalyzerContext(metrics)
        )
        result.cost_by_analyzer = dict(pending.monitor.cost_by_analyzer)
        return result

    def _execute_fast(self, pending: _PendingFold, drain_span) -> None:
        from ..analyzers.base import HostBatchContext
        from ..reliability.faults import fault_point
        from ..runners.context import AnalyzerContext
        from ..verification import VerificationSuite

        session = pending.session
        mon = pending.monitor
        provider = session.provider
        try:
            # the SAME pre-mutation chaos site the serial path fires (its
            # contract: a fold fails BEFORE any state mutates), plus the
            # coalesce-specific site the bisection drills key on
            fault_point("stream_fold", tag=_job_tag(pending))
            fault_point("coalesced_fold", tag=f"{pending.skey[0]}/{pending.skey[1]}")
            fast = True
            with session._serial:
                if session._closed:
                    from .errors import SessionClosed

                    raise SessionClosed(*pending.skey)
                data, pending_contract, degraded = session._pre_fold(
                    pending.data
                )
                if degraded:
                    # the guard excluded columns: only the full runner's
                    # per-analyzer degradation can honor this fold
                    fast = False
                    self._serial_fallback(pending, data, pending_contract)
                else:
                    rows = int(data.num_rows)
                    drain_span.add_event(
                        "fast_fold", tenant=pending.skey[0],
                        dataset=pending.skey[1], rows=rows,
                    )
                    # phase times accumulate straight into the fold monitor
                    # (no per-fold phase spans: the drain span above is the
                    # trace-side record; two ring appends per fold saved)
                    t_part = time.perf_counter()
                    batch = self._micro_batch(data, pending)
                    hctx = HostBatchContext(batch, batch_index=0)
                    deltas = []
                    for a in pending.plan.battery:
                        t0 = time.perf_counter()
                        deltas.append(a.host_partial(hctx))
                        self.router.observe_host(
                            type(a), rows, time.perf_counter() - t0
                        )
                    t_fin = time.perf_counter()
                    metrics = {
                        a: self._host_finalize(a, s, provider)
                        for a, s in zip(pending.plan.battery, deltas)
                    }
                    result = VerificationSuite.evaluate(
                        session.checks, AnalyzerContext(metrics)
                    )
                    t_done = time.perf_counter()
                    controller = getattr(
                        self.service, "tuning_controller", None
                    )
                    if controller is not None:
                        # route-specific compute only (mirrors
                        # observe_host's span): the finalize/evaluate tail
                        # is paid by BOTH routes and would mask the
                        # routing signal the experiments compare
                        controller.record(
                            rows, t_fin - t_part, arm=pending.tuning_arm
                        )
                    mon.add_phase_time("host_partials", t_fin - t_part)
                    mon.add_phase_time("metric_derivation", t_done - t_fin)
                    mon.bump("passes")
                    mon.bump("batches")
                    mon.bump("fast_path_folds")
                    session._commit_fold(
                        result, data, pending_contract, pending.done
                    )
            # on_result delivery OUTSIDE the serial lock, exactly like the
            # serial path's _fold_batch -> _notify sequencing
            result = session._notify(pending.done)
            if fast:
                self.service.metrics.inc(
                    "deequ_service_fast_path_folds_total",
                    tenant=pending.skey[0],
                )
            return result, None
        except BaseException as exc:
            if not isinstance(exc, Exception):
                # KeyboardInterrupt-class injections ride out; the group
                # backstop completes every still-claimed fold
                raise
            return None, exc

    @staticmethod
    def _micro_batch(data, pending: _PendingFold):
        """The fold's single unpadded batch, memoized on the (immutable)
        Dataset: a payload broadcast to many sessions — the fleet fan-out
        the ingest cache already recognizes — materializes its columns
        once instead of once per session. Distinct-data streams see one
        materialization either way."""
        cols = pending.plan.columns
        key = (pending.bucket, None if cols is None else tuple(cols))
        cache = getattr(data, "_micro_batch_cache", None)
        if cache is None:
            cache = data._micro_batch_cache = {}
        batch = cache.get(key)
        if batch is None:
            for batch in data.batches(
                pending.bucket, columns=cols, pad_to_batch_size=False
            ):
                break
            cache[key] = batch
        return batch

    def _fleet_stream_eligible(
        self, plan, rows: int, tenant: Optional[str] = None
    ) -> bool:
        """Would a fold of this battery at this size shard over a fleet
        sub-mesh? (The routing half of `_fleet_lease`; the lease itself
        happens at drain time.) With ``tenant``, also requires the
        CURRENT packing to grant that tenant a multi-device slice — a
        fast-routed fold must not be flipped onto the device route for a
        single-chip slice the drain would never shard anyway (the
        crossover router measured fast as the winner there). A re-pack
        between this peek and the drain can still leave a rare flipped
        fold on the single-chip stack; that costs one launch, never
        correctness."""
        fleet = getattr(self.service, "fleet", None)
        if fleet is None or plan is None or not plan.mesh_ok:
            return False
        from .fleet import fleet_stream_min_rows

        if int(rows) < fleet_stream_min_rows():
            return False
        return tenant is None or fleet.peek(tenant).n_dev >= 2

    def _fleet_lease(self, f: _PendingFold):
        """Acquire the tenant's sub-mesh lease for a fleet-eligible fold,
        or None (no fleet, battery not host-partial-capable, delta below
        the sharding floor, or a single-chip slice). The caller must
        release a non-None lease."""
        fleet = getattr(self.service, "fleet", None)
        if fleet is None or not f.plan.mesh_ok:
            return None
        from .fleet import fleet_stream_min_rows

        if int(f.data.num_rows) < fleet_stream_min_rows():
            return None
        lease = fleet.acquire(f.skey[0])
        if lease.n_dev < 2:
            fleet.release(f.skey[0])
            return None
        return lease

    def _execute_device(self, group: List[_PendingFold]) -> None:
        """Guard + stage every fold, then launch the group as one vmapped
        program; bisect on launch failure so a fault inside the joint
        launch quarantines only the owning session(s). Fleet-sized folds
        peel off first: each shards over its tenant's sub-mesh (shard-
        local states, butterfly merge at this drain boundary) instead of
        joining the single-chip stack."""
        from ..reliability.faults import fault_point

        prepped = []
        for f in group:
            try:
                if f.state == _DONE:
                    continue  # claim-wait backstop resolved it
                lease = self._fleet_lease(f)
                if lease is not None:
                    fleet = self.service.fleet
                    try:
                        result, error = self._execute_mesh_fold(f, lease)
                    finally:
                        fleet.release(f.skey[0])
                        if f.monitor.shard_losses:
                            # the fold survived via the ladder; make the
                            # NEXT lease pack over the survivors
                            fleet.note_shard_loss()
                    if f.state != _DONE:
                        self._complete(f, result=result, error=error)
                    continue
                degraded = False
                fault_point("stream_fold", tag=_job_tag(f))
                with f.session._serial:
                    if f.session._closed:
                        from .errors import SessionClosed

                        raise SessionClosed(*f.skey)
                    data, pending_contract, degraded = f.session._pre_fold(
                        f.data
                    )
                    if degraded:
                        self._serial_fallback(f, data, pending_contract)
                if degraded:
                    self._complete(f, result=f.session._notify(f.done))
                    continue
                batch = None
                with f.monitor.timed("feature_build"):
                    for batch in data.batches(
                        f.bucket, columns=f.plan.columns
                    ):
                        break
                    feats = f.plan.builder().build(batch)
                prepped.append((f, data, pending_contract, feats))
            except BaseException as exc:
                self._complete(f, error=exc)
                if not isinstance(exc, Exception):
                    raise
        if prepped:
            self._launch_bisect(prepped)

    def _execute_mesh_fold(self, f: _PendingFold, lease):
        """One streaming fold sharded over the tenant's sub-mesh: the
        micro-batch row-splits into one slice per device, each slice's
        HOST partial folds into that shard's LOCAL state
        (`sharded_ingest_fold` through the `ElasticMeshFold` ladder, so a
        shard lost mid-fold salvages + re-shards exactly like a batch
        scan), and the per-shard states butterfly-merge on the ICI at
        THIS drain boundary (`collective_merge_states` inside
        ``finish()``) into the delta the session's persisted states
        absorb. Metrics/checks/drift semantics are the serial path's own
        (same `_pre_fold`/`_finalize`/`_commit_fold` machinery)."""
        import math

        from ..analyzers.base import HostBatchContext
        from ..parallel import ElasticMeshFold
        from ..reliability.faults import fault_point

        session = f.session
        mon = f.monitor
        mesh = lease.mesh
        n_dev = lease.n_dev
        sharded = True
        try:
            fault_point("stream_fold", tag=_job_tag(f))
            fault_point(
                "coalesced_fold", tag=f"{f.skey[0]}/{f.skey[1]}"
            )
            with session._serial:
                if session._closed:
                    from .errors import SessionClosed

                    raise SessionClosed(*f.skey)
                data, pending_contract, degraded = session._pre_fold(f.data)
                if degraded:
                    # drift-degraded columns: only the full runner's
                    # per-analyzer degradation can honor this fold
                    sharded = False
                    self._serial_fallback(f, data, pending_contract)
                else:
                    battery = f.plan.battery
                    rows = int(data.num_rows)
                    slice_rows = max(1, math.ceil(rows / n_dev))
                    elastic = ElasticMeshFold(battery, mesh, monitor=mon)

                    def slice_partials(wanted=None):
                        # one FRESH memo token per invocation (the
                        # engine's replay-round discipline): slices of
                        # one round may share per-pass memo work (the
                        # HLL dictionary skip — the first slice that
                        # sees an entry contributes it), but a REPLAY
                        # round must never skip an entry whose only
                        # contribution died with the lost shard
                        run_token = object()
                        out = []
                        with mon.timed("host_partials"):
                            for i, batch in enumerate(data.batches(
                                slice_rows, columns=f.plan.columns,
                                pad_to_batch_size=False,
                            )):
                                if wanted is not None and i not in wanted:
                                    continue
                                ctx = HostBatchContext(
                                    batch, batch_index=i,
                                    run_token=run_token,
                                )
                                out.append((i, tuple(
                                    a.host_partial(ctx) for a in battery
                                )))
                        return out

                    def fold_slices(slices):
                        import jax as _jax

                        group = [p for _, p in slices]
                        idx = [i for i, _ in slices]
                        if len(group) < n_dev:
                            # pad with identity partials (an empty batch's
                            # partial) so ONE compiled fold shape serves
                            # every delta size; flags skip the padding
                            from ..runners.engine import _empty_batch_like

                            ident = tuple(
                                a.host_partial(HostBatchContext(
                                    _empty_batch_like(data, f.plan.columns),
                                    batch_index=len(group),
                                ))
                                for a in battery
                            )
                            group = group + [ident] * (n_dev - len(group))
                        flags = np.zeros(len(group), dtype=bool)
                        flags[: len(idx)] = True
                        stacked = tuple(
                            _jax.tree_util.tree_map(
                                lambda *xs: np.stack(
                                    [np.asarray(x) for x in xs]
                                ),
                                *[p[i] for p in group],
                            )
                            for i in range(len(battery))
                        )
                        with mon.timed("ingest_fold"):
                            elastic.fold(stacked, flags, batch_indices=idx)

                    fold_slices(slice_partials())
                    # the drain-boundary butterfly: per-shard states merge
                    # on the ICI into ONE canonical delta per analyzer. A
                    # shard lost mid-fold (or DURING the merge itself)
                    # queues its slices for replay: recompute exactly
                    # those, re-fold on the rebuilt mesh, and re-merge —
                    # loop until a merge completes with nothing pending
                    # (the engine's own replay->finish discipline)
                    while True:
                        while elastic.pending_replay:
                            todo = set(elastic.take_lost_batches())
                            fold_slices(slice_partials(wanted=todo))
                        with mon.timed("ingest_fold"):
                            states = elastic.finish()
                        if not elastic.pending_replay:
                            break
                    result = self._finalize_states(f, states)
                    mon.bump("passes")
                    mon.bump("batches")
                    mon.bump("device_updates")
                    mon.bump("fleet_mesh_folds")
                    # "mesh", NOT "device": note_ran treats an executed
                    # placement of "device" as warmth evidence for the
                    # single-chip fused program, which this path never
                    # compiles (it runs host partials + collectives) —
                    # claiming it would send a later small fold of the
                    # same battery straight into the cold compile
                    mon.placement = "mesh"
                    session._commit_fold(
                        result, data, pending_contract, f.done
                    )
            result = session._notify(f.done)
            if sharded:
                self.service.metrics.inc(
                    "deequ_service_fleet_stream_folds_total",
                    tenant=f.skey[0], devices=str(n_dev),
                )
            return result, None
        except BaseException as exc:
            if not isinstance(exc, Exception):
                raise
            return None, exc

    def _launch_bisect(self, prepped) -> None:
        from ..observability import trace as _trace

        try:
            states_list = self._launch(prepped)
        except Exception as exc:
            if len(prepped) == 1:
                f = prepped[0][0]
                self.service.metrics.inc(
                    "deequ_service_coalesce_quarantined_total",
                    tenant=f.skey[0],
                )
                _trace.add_event(
                    "coalesce_quarantined",
                    tenant=f.skey[0], dataset=f.skey[1],
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                )
                self._complete(f, error=exc)
                return
            # the fault could belong to any member: split and re-launch —
            # ≤log2(W) extra launches isolate exactly the faulty fold(s)
            _trace.add_event("coalesce_bisect", width=len(prepped))
            mid = len(prepped) // 2
            self._launch_bisect(prepped[:mid])
            self._launch_bisect(prepped[mid:])
            return
        for (f, data, pending_contract, _), states in zip(
            prepped, states_list
        ):
            try:
                with f.session._serial:
                    result = self._finalize_states(f, states)
                    f.monitor.bump("passes")
                    f.monitor.bump("batches")
                    f.monitor.bump("device_updates")
                    f.monitor.bump("coalesced_folds")
                    f.monitor.placement = "device"
                    f.session._commit_fold(
                        result, data, pending_contract, f.done
                    )
                result = f.session._notify(f.done)
                self._complete(f, result=result)
            except BaseException as exc:
                self._complete(f, error=exc)
                if not isinstance(exc, Exception):
                    raise

    def _launch(self, prepped) -> List[Tuple]:
        from ..observability import trace as _trace
        from ..reliability.faults import fault_point
        from ..runners.engine import fold_sessions_coalesced

        width = len(prepped)
        rows = int(prepped[0][1].num_rows)
        with _trace.span(
            "coalesced_launch", kind="coalesce", width=width,
            bucket=prepped[0][0].bucket,
        ) as sp:
            for f, data, _, _ in prepped:
                # chaos site: an injected fault here aborts the joint
                # launch attempt; bisection then quarantines the session
                # the injector's tag match names
                fault_point(
                    "coalesced_fold", tag=f"{f.skey[0]}/{f.skey[1]}"
                )
                sp.add_event(
                    "coalesced_session", tenant=f.skey[0],
                    dataset=f.skey[1], rows=int(data.num_rows),
                )
            t0 = time.perf_counter()
            orchestrators = [f.plan.orchestrator() for f, _, _, _ in prepped]
            feats = [p[3] for p in prepped]
            states_list = fold_sessions_coalesced(orchestrators, feats)
            elapsed = time.perf_counter() - t0
        self.router.observe_device(rows, elapsed, width)
        share = elapsed / width
        controller = getattr(self.service, "tuning_controller", None)
        for f, _, _, _ in prepped:
            f.monitor.add_phase_time("device_dispatch", share)
            if controller is not None:
                controller.record(rows, share, arm=f.tuning_arm)
        self._note_width(width, coalesced=True)
        return states_list

    def _note_width(self, width: int, coalesced: bool = False) -> None:
        """Width-histogram accounting for one multi-fold drain (pow2
        bucket counter + sum, the mean amortization factor's numerator)."""
        bucket = 1
        while bucket < width:
            bucket *= 2
        updates = [
            ("deequ_service_coalesce_width_total", 1.0,
             {"width": str(bucket)}),
            ("deequ_service_coalesce_width_sum", float(width), {}),
        ]
        if coalesced:
            updates.append(
                ("deequ_service_coalesced_folds_total", float(width), {})
            )
        self.service.metrics.inc_many(updates)
