"""Streaming schema-drift guard: the contract every micro-batch must honor
before it may fold into a session's persisted algebraic states.

A :class:`~deequ_tpu.service.streaming.StreamingSession` folds arriving
deltas into long-lived semigroup states; the merge is only meaningful when
every batch speaks the SAME schema — folding a retyped column would
silently mix int and string hashes in one HLL, or splice a narrowed
column's overflow into a running sum, and no later batch can undo a
contaminated state. The reference sidesteps this because Spark DataFrames
carry one schema per job; a service ingesting millions of user-supplied
batches for weeks cannot assume it.

:class:`SchemaContract` is captured from the session's FIRST batch (column
names, value dtypes, dictionary-encoding) and every later batch validates
against it BEFORE the fold:

- **compatible widenings** — a batch column whose dtype is a same-family
  narrowing of the contract's (int32 arriving where int64 was promised,
  float32 where float64) — are coerced up to the contract dtype and
  counted. Values are exactly representable, states stay uniform.
- **incompatible drift** — column added, dropped, retyped across families,
  or a dictionary-encoding flip — is handled per the session's
  ``drift_policy``:

  ========= ==============================================================
  policy    behavior
  ========= ==============================================================
  reject    (default) raise typed :class:`SchemaDriftError` before the
            fold; persisted states untouched
  coerce    best-effort repair: retyped columns cast back to the contract
            dtype (safe casts only — a failed cast rejects), added columns
            dropped, encoding flips re-encoded; a DROPPED column cannot be
            conjured and always rejects
  degrade   drop the drifted columns from the batch and fold the rest;
            analyzers over the dropped columns emit typed ``Failure``
            metrics for this batch (the PR-2 isolation stance: partial
            results beat no results), persisted states of unaffected
            analyzers keep advancing
  ========= ==============================================================

Column ORDER is not part of the contract: batches materialize columns by
name, so reordering is cosmetic. Dictionary VALUES are not either —
growing a category set batch-over-batch is the normal streaming case.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

from ..exceptions import SchemaDriftError

DRIFT_POLICIES = ("reject", "coerce", "degrade")

#: same-family widening chains: a batch dtype may be coerced UP to any
#: dtype later in its chain (exact value preservation); anything else is a
#: retype. int->float is deliberately NOT a widening: it flips the
#: column's Integral/Fractional kind, which changes analyzer routing and
#: DataType profiles.
_WIDENING_CHAINS = (
    ["int8", "int16", "int32", "int64"],
    ["uint8", "uint16", "uint32", "uint64"],
    ["halffloat", "float", "double"],  # arrow names for f16/f32/f64
)


def _widens_to(batch_dtype: str, contract_dtype: str) -> bool:
    """True when ``batch_dtype`` may be losslessly coerced up to
    ``contract_dtype`` (same family, narrower or equal)."""
    if batch_dtype == contract_dtype:
        return True
    for chain in _WIDENING_CHAINS:
        if batch_dtype in chain and contract_dtype in chain:
            return chain.index(batch_dtype) < chain.index(contract_dtype)
    return False


@dataclass(frozen=True)
class ColumnContract:
    """One column's promise: its name, its VALUE dtype (dictionary
    indices are an encoding detail; the value type is the identity), and
    whether it arrives dictionary-encoded (the engine routes
    dictionary-encoded grouping/histogram columns through the device
    frequency scan, so the flag changes battery composition)."""

    name: str
    dtype: str
    dictionary: bool


@dataclass
class DriftReport:
    """What validation decided for one batch: the (possibly repaired)
    table to fold, the widening coercions applied, the columns degraded,
    and the HARD drifts the ``coerce`` policy repaired (added columns
    dropped, retypes cast back) — reported separately because a repaired
    producer-side schema change still needs operator visibility."""

    table: Any
    coercions: List[str]
    degraded: List[str]
    repaired: List[str] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.repaired is None:
            self.repaired = []


class SchemaContract:
    """The per-session schema promise; see the module docstring."""

    def __init__(self, columns: Tuple[ColumnContract, ...]):
        self.columns = tuple(columns)
        self._by_name: Dict[str, ColumnContract] = {
            c.name: c for c in self.columns
        }

    @staticmethod
    def capture(data) -> "SchemaContract":
        """Capture the contract from a Dataset's arrow schema."""
        import pyarrow as pa

        cols = []
        for field in data.arrow.schema:
            t = field.type
            if pa.types.is_dictionary(t):
                cols.append(ColumnContract(field.name, str(t.value_type), True))
            else:
                cols.append(ColumnContract(field.name, str(t), False))
        return SchemaContract(tuple(cols))

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{c.name}:{c.dtype}{'[dict]' if c.dictionary else ''}"
            for c in self.columns
        )
        return f"SchemaContract({inner})"

    # -- validation ----------------------------------------------------------

    def validate(
        self, data, *, policy: str = "reject", session: str = "<session>"
    ) -> DriftReport:
        """Check one micro-batch against the contract.

        Returns a :class:`DriftReport` whose ``table`` is safe to fold
        (``None`` when the batch needed no repair — fold the original), or
        raises :class:`SchemaDriftError` per ``policy``. Widenings never
        raise; they are coerced and recorded under ``coercions``."""
        import pyarrow as pa
        import pyarrow.compute as pc

        if policy not in DRIFT_POLICIES:
            raise ValueError(
                f"drift_policy must be one of {DRIFT_POLICIES}, got {policy!r}"
            )
        table = data.arrow
        batch: Dict[str, Any] = {}
        for field in table.schema:
            t = field.type
            if pa.types.is_dictionary(t):
                batch[field.name] = (str(t.value_type), True)
            else:
                batch[field.name] = (str(t), False)

        coercions: List[str] = []    # widenings: always repaired
        hard: List[str] = []         # incompatible drift descriptions
        repair: Dict[str, ColumnContract] = {}  # column -> conform target
        drop: List[str] = []         # columns degrade removes

        for c in self.columns:
            got = batch.get(c.name)
            if got is None:
                hard.append(f"column {c.name!r} dropped")
                continue
            got_dtype, got_dict = got
            widened = got_dtype != c.dtype and _widens_to(got_dtype, c.dtype)
            retyped = got_dtype != c.dtype and not widened
            flipped = got_dict != c.dictionary
            if retyped:
                hard.append(
                    f"column {c.name!r} retyped {c.dtype} -> {got_dtype}"
                )
                drop.append(c.name)
                if policy == "coerce":
                    repair[c.name] = c
                continue
            if flipped:
                hard.append(
                    f"column {c.name!r} "
                    + (
                        "lost its dictionary encoding"
                        if c.dictionary
                        else "became dictionary-encoded"
                    )
                )
                drop.append(c.name)
                if policy == "coerce":
                    repair[c.name] = c
                continue
            if widened:
                coercions.append(f"{c.name}: {got_dtype} -> {c.dtype}")
                repair[c.name] = c
        added = [name for name in batch if name not in self._by_name]
        for name in added:
            hard.append(f"column {name!r} added")

        if hard and policy == "reject":
            raise SchemaDriftError(session, hard)
        degraded: List[str] = []
        repaired: List[str] = []
        if hard and policy == "coerce":
            missing = [
                c.name for c in self.columns if c.name not in batch
            ]
            if missing:
                # nothing to cast a missing column FROM
                raise SchemaDriftError(
                    session,
                    [f"column {name!r} dropped" for name in missing],
                )
            # added columns are simply not folded; retypes/encodings
            # conform below — a cast that cannot represent the values
            # rejects instead of silently mangling. Either way the HARD
            # drift is reported as repaired, never consumed invisibly
            repaired = list(hard)
        if hard and policy == "degrade":
            missing = [c.name for c in self.columns if c.name not in batch]
            # ADDED columns join the degraded list too: they carry no
            # analyzers to fail, but dropping them must still surface on
            # the drift counters/warnings — an invisible schema change is
            # the exact thing this guard exists to report
            degraded = missing + drop + added
            repair = {k: v for k, v in repair.items() if k not in drop}

        if not hard and not repair:
            return DriftReport(None, coercions, [])

        def conform(col, c: ColumnContract):
            """Make one column match its contract: decode a stray
            dictionary, cast to the contract dtype (safe cast — overflow
            raises), re-encode if the contract promises a dictionary."""
            target = _arrow_type(c.dtype)
            if target is None:
                raise SchemaDriftError(
                    session,
                    [f"column {c.name!r} cannot be coerced to {c.dtype}"],
                )
            col = col.combine_chunks()
            try:
                if pa.types.is_dictionary(col.type):
                    col = col.cast(col.type.value_type)
                col = pc.cast(col, target)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as exc:
                raise SchemaDriftError(
                    session,
                    [f"column {c.name!r} cannot be coerced: {exc}"],
                ) from exc
            if c.dictionary:
                col = pc.dictionary_encode(col)
            return col

        # rebuild the batch table: contract columns only, conformed
        out_cols: Dict[str, Any] = {}
        for c in self.columns:
            if c.name not in batch or c.name in degraded:
                continue
            col = table[c.name]
            if c.name in repair:
                col = conform(col, repair[c.name])
            out_cols[c.name] = col
        return DriftReport(pa.table(out_cols), coercions, degraded, repaired)


def _arrow_type(name: str):
    """Arrow DataType from its str() name (only the types a contract can
    record: the primitive numerics/strings str() round-trips through
    `pyarrow.type_for_alias`; anything exotic compares by string only and
    never needs materializing because equal strings skip the cast)."""
    import pyarrow as pa

    try:
        return pa.type_for_alias(name)
    except ValueError:
        # timestamp[...]/decimal(...) etc: dtype strings still COMPARE
        # correctly, and unequal ones of these are never widenable, so a
        # cast target is only requested for alias-able primitives
        return None
