"""Multi-tenant job scheduler: bounded queue, priorities, deadlines, retry.

The reference deequ runs as one-shot batch jobs; a service hosting repeated
verification (the production mode of Schelter et al., VLDB 2018) needs an
admission-controlled queue between callers and the engine. Design points:

- **Bounded admission.** `submit` sheds with a typed
  :class:`ServiceOverloaded` once `max_queue_depth` jobs are pending —
  queueing unboundedly only converts an overload into a deadline storm.
  Retries of already-admitted jobs re-enter without re-admission, so the
  bound can transiently exceed by the number of jobs concurrently
  CLAIMED by workers (at most ``workers * _PICK_BATCH``): a pickup frees
  queue slots that new admissions may take before a claimed job's retry
  re-enters the delayed queue. Formally, with P = pending, A = claimed,
  every transition preserves ``P + A <= max_queue_depth + workers *
  _PICK_BATCH`` — submit requires ``P < max_queue_depth``, pickup moves
  P->A, a retry moves A->P — so sampled pending never exceeds that sum
  (pinned by the soak test).
- **Priority classes.** The ready list stays sorted by (priority,
  submission sequence): strict priority, FIFO within a class.
- **Deadlines.** Per-job wall-clock budgets, checked when a worker picks
  the job up (queued past its deadline -> typed :class:`JobTimeout`
  without wasting a run) and again at completion.
- **Typed retry with backoff.** :class:`TransientFailure` (and any
  `retry_on` types the caller registers) re-enqueues with exponential
  backoff until the retry budget or the deadline runs out; everything
  else fails fast as :class:`JobFailed`.
- **Cache-aware pickup.** Workers prefer ready jobs whose battery they
  have run before (see `placement.PlacementRouter`), falling back to the
  global head — soft affinity without starvation.

Workers are threads: every heavy phase of a run (native kernels, numpy,
pyarrow, device dispatch) releases the GIL, so N workers genuinely overlap
N jobs' host work the way the engine's own prefetch/partial pools do.
"""

from __future__ import annotations

import bisect
import enum
import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from ..observability import trace as _trace
from ..runners.engine import RunMonitor
from .errors import (
    JobFailed,
    JobTimeout,
    QuotaExceeded,
    ServiceClosed,
    ServiceError,
    ServiceOverloaded,
    TransientFailure,
)
from .metrics import ServiceMetrics
from .placement import PlacementRouter, Signature


class Priority(enum.IntEnum):
    """Lower value = served first."""

    HIGH = 0
    NORMAL = 1
    LOW = 2


def _priority_label(priority: Any) -> str:
    """Stable label value for the per-priority histogram series ("high" /
    "normal" / "low"; raw ints degrade to their str)."""
    return getattr(priority, "name", str(priority)).lower()


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant admission budget — the isolation half of multi-tenancy:
    one tenant's flood becomes ITS OWN typed :class:`QuotaExceeded` (HTTP
    429) instead of everyone's queue latency.

    ``rows_per_s`` / ``bytes_per_s`` are sustained ingest rates enforced
    by token bucket at the streaming admission edge (before anything is
    queued or folded); ``queue_share`` is the fraction of the scheduler's
    ``max_queue_depth`` this tenant's pending jobs may occupy (enforced
    inside :meth:`JobScheduler.submit`). ``None`` per field = unlimited.
    Tenants with NO quota registered are entirely unthrottled — quotas
    are opt-in per tenant (normally set from the tenant catalog's
    ``quotas`` document section)."""

    rows_per_s: Optional[float] = None
    bytes_per_s: Optional[float] = None
    queue_share: Optional[float] = None


class _TokenBucket:
    """Deficit token bucket on ``time.monotonic``: a charge is admitted
    whenever the balance is non-negative and then subtracts its FULL
    amount (the balance may go deeply negative), so any single batch size
    is admittable and the steady-state rate still converges on ``rate`` —
    a producer who sent a 1M-row frame simply owes the bucket ~1M/rate
    seconds of silence. ``charge`` returns 0.0 on admission or the
    seconds until the balance refills to zero (the caller's bounded
    backpressure wait); a refused charge consumes NOTHING."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float):
        self.rate = float(rate)
        #: accrual cap: at most one second of idle credit, so a tenant
        #: idle for an hour cannot burst an hour's budget in one frame
        self.burst = float(rate)
        self.tokens = 0.0
        self.last: Optional[float] = None

    def charge(self, amount: float, now: float) -> float:
        if self.last is not None:
            self.tokens = min(
                self.tokens + (now - self.last) * self.rate, self.burst
            )
        self.last = now
        if self.tokens < 0:
            return -self.tokens / self.rate
        self.tokens -= float(amount)
        return 0.0


@dataclass
class JobContext:
    """What a job body receives: identity, attempt number, the worker it
    landed on, the placement the router chose for this attempt, the
    tenant's leased sub-mesh (``mesh``; None = single chip — the fleet
    scheduler grants it per attempt when the job asked for one), and a
    RunMonitor the scheduler harvests into the export plane afterwards —
    also on failure, so a crashing run still reports its phase costs."""

    job_id: str
    tenant: str
    attempt: int
    worker_id: int
    placement: Optional[str]
    mesh: Optional[Any] = None
    monitor: RunMonitor = field(default_factory=RunMonitor)


class JobHandle:
    """Caller-side future for one admitted job."""

    def __init__(self, job_id: str, tenant: str, priority: Priority):
        self.job_id = job_id
        self.tenant = tenant
        self.priority = priority
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self.attempts = 0
        self.phase_seconds: Dict[str, float] = {}
        #: the job's value when it COMPLETED but past its deadline (the
        #: JobTimeout carries completed=True): the work's side effects have
        #: committed, so the result stays reachable for callers that must
        #: not re-run committed work
        self.late_value: Any = None

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def result(self, timeout: Optional[float] = None) -> Any:
        """The job's value; raises its typed ServiceError on failure and
        ``TimeoutError`` if the handle is not done within ``timeout``."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"job {self.job_id} not done after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value

    def _finish(self, value: Any, error: Optional[BaseException]) -> None:
        self._value = value
        self._error = error
        self._event.set()


class _Job:
    __slots__ = (
        "job_id", "fn", "tenant", "priority", "deadline_s", "deadline_abs",
        "submit_time", "max_retries", "retry_backoff_s", "retry_on",
        "signature", "handle", "attempts", "seq", "warm_fn", "serial_key",
        "span", "defer_key", "mesh_tenant", "recover_fn",
    )

    def __init__(self, **kw):
        self.defer_key = None
        self.mesh_tenant = None
        self.recover_fn = None
        for k, v in kw.items():
            setattr(self, k, v)
        self.attempts = 0
        #: the job's trace span: opened at admission, annotated by every
        #: attempt/retry/outcome, finished exactly once in _finish
        self.span = _trace.NULL


#: ready-queue entries a worker inspects looking for an affinity match
#: before falling back to the strict head (bounded so pickup stays O(1)-ish)
_AFFINITY_SCAN = 8

#: hard cap on entries the affinity loop may TOUCH, counting the same-key
#: siblings it skips without scanning: a deep single-session backlog (one
#: streaming session pipelining hundreds of folds) made the skip walk
#: O(queue depth) per pickup — measured ~1ms/fold of pure scan CPU at 500
#: queued folds (the streaming-knee scheduler diet)
_AFFINITY_INSPECT = 32

#: jobs a worker may claim in ONE queue-lock round-trip when the ready
#: list is deep (the batched pickup of the streaming-knee scheduler diet):
#: at thousands of micro-folds/s the per-job wake->lock->scan->unlock
#: cycle — and the GIL handoffs it forces between eight workers — was a
#: measurable slice of the fold fixed cost. Batching only engages under
#: queue PRESSURE (depth >= 2x workers), so a sparse queue keeps strict
#: one-at-a-time pickup and its latency profile.
_PICK_BATCH = 8


class JobScheduler:
    def __init__(
        self,
        workers: int = 4,
        max_queue_depth: int = 64,
        metrics: Optional[ServiceMetrics] = None,
        router: Optional[PlacementRouter] = None,
        name: str = "deequ-service",
        fleet=None,
    ):
        self.metrics = metrics or ServiceMetrics()
        self.router = router or PlacementRouter(self.metrics)
        #: the fleet scheduler (service.fleet.FleetScheduler) packing
        #: tenants onto disjoint sub-meshes; None = single-chip routing
        #: (the DEEQU_TPU_FLEET=0 escape hatch, or a single-device box).
        #: Jobs submitted with ``mesh_tenant`` lease their tenant's slice
        #: for the duration of each attempt.
        self.fleet = fleet
        self.max_queue_depth = int(max_queue_depth)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        #: (priority, seq, job) — ready to run now, kept SORTED by
        #: (priority, submission seq); _pick scans it front-to-back past
        #: serial-key-blocked entries, so a heap's pop-only discipline
        #: would not fit
        self._ready: List[Tuple[int, int, _Job]] = []
        #: submitters blocked in backpressure mode wait here; workers
        #: notify it whenever a pickup frees queue space (same lock as
        #: _cond, separate waiter set so a freed slot wakes a submitter
        #: instead of another idle worker)
        self._space = threading.Condition(self._lock)
        #: (not_before, seq, job) — backoff-delayed retries
        self._delayed: List[Tuple[float, int, _Job]] = []
        self._seq = itertools.count()
        self._active = 0
        self._closed = False
        #: serial key -> the job currently OWNING it: _pick skips ready
        #: jobs whose key another job owns, so one streaming session's
        #: pipelined folds occupy at most ONE worker (instead of parking
        #: the whole pool on a session lock) and dequeue in FIFO order per
        #: key. A retried job KEEPS its key through the backoff — releasing
        #: it would let a later-submitted sibling overtake the retry and
        #: fold out of order
        self._running_keys: Dict[Any, _Job] = {}
        #: coalesce keys under an ACTIVE drain: their jobs stay queued for
        #: bulk absorption instead of being picked (see _eligible)
        self._deferred: set = set()
        #: tenant -> TenantQuota; buckets are lazily built per (tenant,
        #: resource) and rebuilt when a quota edit changes the rate.
        #: Guarded by _quota_lock (NOT the queue lock: charge_quota's
        #: bounded sleeps must never park inside queue admission)
        self._quota_lock = threading.Lock()
        self._quotas: Dict[str, TenantQuota] = {}
        self._buckets: Dict[Tuple[str, str], _TokenBucket] = {}
        #: harvest listeners (fn(tenant)) invoked OUTSIDE the queue lock
        #: after every job harvest — the fleet watch's re-score trigger
        #: (see service.fleetwatch). Append-only; registration races at
        #: worst miss the in-flight harvest.
        self._harvest_listeners: List[Callable[[str], None]] = []
        self.metrics.describe(
            "deequ_service_jobs_submitted_total", "Jobs accepted into the queue."
        )
        self.metrics.describe(
            "deequ_service_jobs_shed_total",
            "Jobs rejected by admission control (ServiceOverloaded).",
        )
        self.metrics.describe(
            "deequ_service_quota_shed_total",
            "Admissions refused by a PER-TENANT quota (typed "
            "QuotaExceeded), by tenant and resource (rows_per_s / "
            "bytes_per_s / queue_share) — distinct from global "
            "jobs_shed_total: the tenant exceeded its OWN budget while "
            "neighbors kept their full service.",
        )
        self.metrics.describe(
            "deequ_service_jobs_completed_total",
            "Jobs that terminated, by outcome (success/failed/timeout).",
        )
        self.metrics.describe(
            "deequ_service_job_retries_total",
            "Transient-failure retries that were re-enqueued with backoff.",
        )
        self.metrics.describe(
            "deequ_service_isolation_reruns_total",
            "Battery-bisection re-passes run to isolate faulty analyzers.",
        )
        self.metrics.describe(
            "deequ_service_degraded_analyzers_total",
            "Analyzers/accumulators degraded to typed Failure metrics "
            "instead of failing their whole run.",
        )
        self.metrics.describe(
            "deequ_service_scan_stalls_total",
            "Engine passes cancelled by the scan watchdog for exceeding "
            "their deadline (hang-not-crash faults).",
        )
        self.metrics.describe(
            "deequ_service_shard_losses_total",
            "Mesh shards (devices/processes) declared lost mid-pass and "
            "absorbed by the elastic layer (salvage + re-shard).",
        )
        self.metrics.describe(
            "deequ_service_mesh_reshards_total",
            "Degraded-mesh rebuilds after shard loss, walking the "
            "8->4->2->1->host ladder (in-pass salvages and pass-level "
            "re-runs both count).",
        )
        self.metrics.describe(
            "deequ_service_salvaged_states_total",
            "Surviving per-shard algebraic states salvaged into a "
            "canonical merge after a shard loss (folded work kept, not "
            "recomputed).",
        )
        self.metrics.describe(
            "deequ_service_partitions_scanned_total",
            "Partitions the incremental delta planner scheduled a scan "
            "for (new + invalidated).",
        )
        self.metrics.describe(
            "deequ_service_partitions_reused_total",
            "Partitions served from stored algebraic states with zero "
            "data touched.",
        )
        self.metrics.describe(
            "deequ_service_partitions_invalidated_total",
            "Stored partitions that went stale (content change, "
            "fingerprint mismatch, battery growth, corruption) and were "
            "re-scanned.",
        )
        self.metrics.describe(
            "deequ_service_partitions_dropped_total",
            "Stored partitions absent from an incoming partition set — "
            "excluded from the merge by re-merge semantics.",
        )
        self.metrics.describe(
            "deequ_service_partitions_rolled_up_total",
            "Partitions served by the rollup cache (the persisted "
            "left-fold prefix) — neither data nor state blobs touched.",
        )
        self.metrics.describe(
            "deequ_service_analyzer_cost_seconds_total",
            "Per-analyzer cost attribution: each signature bundle's "
            "measured compile+dispatch seconds split across its slots, "
            "labeled by analyzer repr.",
        )
        self.metrics.set_gauge_fn(
            "deequ_service_queue_depth", self.pending,
            "Jobs admitted but not yet running.",
        )
        self.metrics.set_gauge_fn(
            "deequ_service_active_jobs", lambda: self._active,
            "Jobs currently executing on a worker.",
        )
        self.metrics.describe_histogram(
            "deequ_service_admission_wait_seconds",
            "Queue wait from submit to worker pickup, per tenant and "
            "priority class (pow2 buckets, seconds).",
        )
        self.metrics.describe_histogram(
            "deequ_service_fold_latency_seconds",
            "End-to-end streaming fold latency (submit to terminal "
            "outcome, serial-keyed jobs), per tenant and priority class "
            "(pow2 buckets, seconds).",
        )
        self._workers = [
            threading.Thread(
                target=self._worker_loop, args=(i,),
                name=f"{name}-worker-{i}", daemon=True,
            )
            for i in range(int(workers))
        ]
        for t in self._workers:
            t.start()

    # -- submission ----------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return len(self._ready) + len(self._delayed)

    def idle(self) -> bool:
        """No job queued, delayed, or EXECUTING — the only state in which
        it is safe to tear down structures a running job might still
        touch."""
        with self._lock:
            return (
                not self._ready and not self._delayed and self._active == 0
            )

    # -- tenant quotas -------------------------------------------------------

    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        """Install (or replace) ``tenant``'s admission budget. Takes
        effect on the next charge/submit; rate edits rebuild the token
        buckets (fresh balance — a quota RAISE must not inherit an hour
        of debt accrued under the old rate)."""
        tenant = str(tenant)
        with self._quota_lock:
            self._quotas[tenant] = quota
            for key in [k for k in self._buckets if k[0] == tenant]:
                del self._buckets[key]

    def clear_quota(self, tenant: str) -> None:
        tenant = str(tenant)
        with self._quota_lock:
            self._quotas.pop(tenant, None)
            for key in [k for k in self._buckets if k[0] == tenant]:
                del self._buckets[key]

    def get_quota(self, tenant: str) -> Optional[TenantQuota]:
        with self._quota_lock:
            return self._quotas.get(str(tenant))

    def charge_quota(
        self,
        tenant: str,
        rows: int = 0,
        nbytes: int = 0,
        block_s: Optional[float] = None,
    ) -> None:
        """Charge one ingest frame against ``tenant``'s rate quotas, or
        refuse it typed. Called at the streaming admission edge BEFORE
        anything queues or folds. Over-rate charges park the caller in
        bounded backpressure for up to ``block_s`` seconds (the bucket's
        own refill estimate paces the sleeps), then shed with
        :class:`QuotaExceeded` — which consumes NONE of the budget, so a
        shed flood cannot starve the tenant's own later frames. No quota
        registered: free. Never touches the queue lock."""
        tenant = str(tenant)
        with self._quota_lock:
            quota = self._quotas.get(tenant)
        if quota is None:
            return
        deadline = (
            None if not block_s else time.monotonic() + float(block_s)
        )
        for resource, rate, amount in (
            ("rows_per_s", quota.rows_per_s, rows),
            ("bytes_per_s", quota.bytes_per_s, nbytes),
        ):
            if not rate or amount <= 0:
                continue
            while True:
                now = time.monotonic()
                with self._quota_lock:
                    bucket = self._buckets.get((tenant, resource))
                    if bucket is None:
                        bucket = _TokenBucket(float(rate))
                        self._buckets[(tenant, resource)] = bucket
                    wait = bucket.charge(float(amount), now)
                    debt = -bucket.tokens
                if wait <= 0:
                    break
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is None or remaining <= 0:
                    self.metrics.inc(
                        "deequ_service_quota_shed_total",
                        tenant=tenant, resource=resource,
                    )
                    raise QuotaExceeded(
                        tenant, resource, float(rate),
                        float(debt + amount),
                    )
                time.sleep(min(wait, remaining))

    def submit(
        self,
        fn: Callable[[JobContext], Any],
        *,
        tenant: str = "default",
        priority: Priority = Priority.NORMAL,
        deadline_s: Optional[float] = None,
        max_retries: int = 2,
        retry_backoff_s: float = 0.05,
        retry_on: Tuple[Type[BaseException], ...] = (),
        signature: Signature = (),
        job_id: Optional[str] = None,
        warm_fn: Optional[Callable[[], None]] = None,
        serial_key: Optional[Any] = None,
        block_s: Optional[float] = None,
        defer_key: Optional[Any] = None,
        mesh_tenant: Optional[str] = None,
        recover_fn: Optional[Callable[[Any, BaseException], Any]] = None,
    ) -> JobHandle:
        """Admit one job, or shed it with :class:`ServiceOverloaded`.

        ``warm_fn``, if given, is what the placement router runs in the
        background when this job's battery is cold (typically a real
        1-padded-batch device run that compiles the production program).
        Jobs sharing a ``serial_key`` execute one at a time, in submission
        order within a priority class — the scheduler-level serialization
        streaming sessions need, without blocking workers on a lock.

        ``block_s`` turns admission into BACKPRESSURE for up to that many
        seconds: a full queue parks the submitter until a worker pickup
        frees a slot instead of shedding immediately — the semantics a
        streaming producer wants (slow down, don't drop), bounded so a
        wedged service still sheds typed rather than hanging the producer
        forever. ``None`` (default) keeps the shed-immediately contract.

        ``mesh_tenant`` opts the job into FLEET scheduling: each attempt
        leases that tenant's sub-mesh from the fleet scheduler (disjoint
        from other tenants' slices) and hands it to the body as
        ``ctx.mesh``; the lease releases when the attempt ends. Ignored
        when the scheduler has no fleet (single chip).

        ``recover_fn(ctx, exc)``, if given, is consulted when the job is
        about to terminate WITHOUT its body having run to completion —
        a worker fault before the body, an infrastructure error, a
        queued-past-deadline kill. It returns ``None`` (nothing to
        adopt; the job fails/times out normally) or a ``(value, error)``
        outcome the job must adopt instead — the coalescer uses this to
        keep a fold's COMMIT and its job's FINISH atomic: a drain that
        already committed the fold makes the job succeed with the
        committed result, and an unclaimed fold is withdrawn so no later
        drain can commit a batch whose caller was told it failed."""
        # per-tenant queue share (quota-opted tenants only): a tenant's
        # pending jobs may occupy at most share * max_queue_depth slots,
        # so one tenant's backlog can fill ITS slice — never the queue
        with self._quota_lock:
            quota = self._quotas.get(tenant)
        share_limit = None
        if quota is not None and quota.queue_share:
            share_limit = max(
                1, int(float(quota.queue_share) * self.max_queue_depth)
            )
        with self._cond:
            if self._closed:
                raise ServiceClosed("verification service is shut down")

            def _tenant_depth() -> int:
                return sum(
                    1 for _, _, j in self._ready if j.tenant == tenant
                ) + sum(
                    1 for _, _, j in self._delayed if j.tenant == tenant
                )

            depth = len(self._ready) + len(self._delayed)
            tdepth = _tenant_depth() if share_limit is not None else 0

            def _blocked() -> bool:
                return depth >= self.max_queue_depth or (
                    share_limit is not None and tdepth >= share_limit
                )

            if _blocked() and block_s:
                deadline = time.monotonic() + float(block_s)
                while not self._closed and _blocked():
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    # pickups free both global slots and tenant slots, so
                    # one waiter set covers both admission gates
                    self._space.wait(remaining)
                    depth = len(self._ready) + len(self._delayed)
                    tdepth = _tenant_depth() if share_limit is not None else 0
                if self._closed:
                    raise ServiceClosed("verification service is shut down")
            if depth >= self.max_queue_depth:
                self.metrics.inc("deequ_service_jobs_shed_total", tenant=tenant)
                raise ServiceOverloaded(depth, self.max_queue_depth)
            if share_limit is not None and tdepth >= share_limit:
                self.metrics.inc(
                    "deequ_service_quota_shed_total",
                    tenant=tenant, resource="queue_share",
                )
                raise QuotaExceeded(
                    tenant, "queue_share", float(share_limit), float(tdepth)
                )
            seq = next(self._seq)
            now = time.monotonic()
            jid = job_id or f"{tenant}-{seq}"
            handle = JobHandle(jid, tenant, priority)
            job = _Job(
                job_id=jid, fn=fn, tenant=tenant, priority=priority,
                deadline_s=deadline_s,
                deadline_abs=None if deadline_s is None else now + deadline_s,
                submit_time=now, max_retries=int(max_retries),
                retry_backoff_s=float(retry_backoff_s),
                retry_on=tuple(retry_on), signature=signature,
                handle=handle, seq=seq, warm_fn=warm_fn,
                serial_key=serial_key, defer_key=defer_key,
                mesh_tenant=mesh_tenant, recover_fn=recover_fn,
            )
            # the trace root of the job's whole causal chain: admission,
            # every attempt/retry, placement, the engine passes it runs
            # (children via the worker's attached context), and the
            # terminal outcome. Submitted under a caller's live span (a
            # traced streaming ingest) it joins that trace instead.
            job.span = _trace.start_span(
                f"job:{jid}", kind="job",
                attrs={"job_id": jid, "tenant": tenant,
                       "priority": int(priority)},
            )
            job.span.add_event("admitted", depth=depth, seq=seq)
            bisect.insort(self._ready, (int(priority), seq, job))
            self.metrics.inc("deequ_service_jobs_submitted_total", tenant=tenant)
            self._cond.notify()
            return handle

    # -- worker side ---------------------------------------------------------

    def _promote_due(self, now: float) -> None:
        while self._delayed and self._delayed[0][0] <= now:
            _, seq, job = heapq.heappop(self._delayed)
            bisect.insort(self._ready, (int(job.priority), seq, job))

    def _eligible(self, job: _Job) -> bool:
        """May this job run now? Its serial key must be free — or owned by
        the job itself (a promoted retry re-entering) — and its defer key
        (if any) must not be under an active coalesced drain: the drainer
        is about to execute the job's fold and retire the job straight
        from this queue (finish_absorbed), so a worker picking it up now
        would only fight the drainer for the GIL to read a memo. The
        drainer ALWAYS undefers on exit (finally + notify), so a deferred
        job is picked normally the moment no drain covers it."""
        if job.defer_key is not None and job.defer_key in self._deferred:
            return False
        if job.serial_key is None:
            return True
        owner = self._running_keys.get(job.serial_key)
        return owner is None or owner is job

    # -- coalescer coupling --------------------------------------------------

    def defer_pickup(self, key: Any) -> None:
        """A coalesced drain is active for ``key``: leave its jobs queued
        for absorption (see ``_eligible``)."""
        with self._cond:
            self._deferred.add(key)

    def resume_pickup(self, key: Any) -> None:
        with self._cond:
            self._deferred.discard(key)
            self._cond.notify_all()  # deferred jobs are pickable again

    def _pick(self, worker_id: int) -> Optional[_Job]:
        """The best ready job this worker may run, or None when every ready
        job's serial key is busy (the worker then waits instead of parking
        on a session lock). ``_ready`` is kept sorted, so this is a single
        front-to-back scan.

        An INELIGIBLE job blocks its later same-serial-key siblings from
        this scan: skipping a drain-DEFERRED job and picking its sibling
        would let the sibling's fold claim ahead of it — the serial key is
        free (neither is running), so ``_eligible`` alone cannot see the
        ordering violation. This was the cross-key commit-inversion flake:
        a session alternating micro-batch buckets had fold N deferred
        under key A's active drain while fold N+1 (key B) was picked and
        committed first."""
        first = None
        blocked_keys: set = set()
        for i, entry in enumerate(self._ready):
            job_i = entry[2]
            key = job_i.serial_key
            if (
                key is not None
                and key in blocked_keys
                # the key's OWNER is exempt: a promoted retry re-enters
                # with a LATER seq than its queued sibling, and blocking
                # it behind that (ineligible) sibling would deadlock the
                # key — the owner is by definition the ordering head
                and self._running_keys.get(key) is not job_i
            ):
                continue
            if self._eligible(job_i):
                first = i
                break
            if key is not None:
                blocked_keys.add(key)
        if first is None:
            return None
        # soft affinity: among the best few eligible entries of the same
        # priority class, prefer one whose battery this worker has run
        # (its device working set is hot). An entry whose serial key
        # already appeared earlier in the scan is NEVER promoted — affinity
        # must not reorder same-key siblings (FIFO per key).
        chosen = first
        scanned = 0
        inspected = 0
        # seed with the keys the first-eligible scan blocked: a job whose
        # earlier same-key sibling is deferred must not be AFFINITY-
        # promoted either, or the promotion re-opens the cross-key
        # commit-inversion hole the blocked_keys rule closes
        keys_seen: set = set(blocked_keys)
        for j in range(first, len(self._ready)):
            entry = self._ready[j]
            inspected += 1
            if (
                entry[0] != self._ready[first][0]
                or scanned >= _AFFINITY_SCAN
                or inspected > _AFFINITY_INSPECT
            ):
                break
            job_j = entry[2]
            if job_j.serial_key is not None:
                if job_j.serial_key in keys_seen:
                    continue  # an earlier same-key sibling goes first
                keys_seen.add(job_j.serial_key)
            if not self._eligible(job_j):
                continue
            scanned += 1
            # signatureless jobs (fast-path streaming folds) have no
            # device working set to be affine to — skip the router-lock
            # round-trip the preferred_workers probe would cost per
            # scanned entry (the streaming-knee scheduler diet)
            if job_j.signature and worker_id in self.router.preferred_workers(
                job_j.signature
            ):
                chosen = j
                break
        job = self._ready.pop(chosen)[2]
        if job.serial_key is not None:
            self._running_keys[job.serial_key] = job
        return job

    def _worker_loop(self, worker_id: int) -> None:
        while True:
            with self._cond:
                jobs: List[_Job] = []
                while not jobs:
                    now = time.monotonic()
                    self._promote_due(now)
                    job = self._pick(worker_id)
                    if job is not None:
                        jobs.append(job)
                        # batched pickup: under queue pressure, claim more
                        # eligible jobs in the SAME lock round-trip — the
                        # worker then runs them back-to-back instead of
                        # re-entering the wake/lock/scan cycle per job
                        if len(self._ready) >= 2 * len(self._workers):
                            while len(jobs) < _PICK_BATCH:
                                extra = self._pick(worker_id)
                                if extra is None:
                                    break
                                jobs.append(extra)
                        break
                    if self._closed and not self._delayed and not self._ready:
                        return
                    timeout = None
                    if self._delayed:
                        timeout = max(self._delayed[0][0] - now, 0.0)
                    # a finishing job notifies, releasing its serial key
                    self._cond.wait(timeout)
                self._active += len(jobs)
                # the pickups freed queue slots: wake as many blocked
                # backpressure submitters
                self._space.notify(len(jobs))
            for job in jobs:
                self._run_one(job, worker_id)

    def _run_one(self, job: _Job, worker_id: int) -> None:
        retried = False
        try:
            retried = self._execute(job, worker_id)
        except BaseException as exc:  # noqa: BLE001 - defense in depth:
            # an error OUTSIDE the job body (router, metrics, harvest)
            # must neither kill the worker thread nor leave the handle
            # unresolved forever — "every job terminates with a result
            # or a typed error" includes scheduler-infrastructure bugs
            if not job.handle.done():
                adopted = self._recover(job, None, exc)
                if adopted is not None and adopted[1] is None:
                    self._finish(job, adopted[0], None, outcome="success")
                else:
                    self._finish(
                        job, None, JobFailed(job.job_id, job.attempts, exc),
                        outcome="failed",
                    )
        finally:
            with self._cond:
                self._active -= 1
                # a retried job keeps OWNING its serial key through the
                # backoff: releasing it would let a later-submitted
                # sibling overtake the retry and execute out of order
                if job.serial_key is not None and not retried:
                    self._running_keys.pop(job.serial_key, None)
                # ONE completion makes at most ONE blocked job newly
                # eligible (the finished job's serial-key sibling), and
                # this worker loops straight back into _pick itself —
                # notify_all here was a thundering herd that woke every
                # idle worker per job (measured on the streaming knee:
                # 8 workers x thousands of folds/s of spurious
                # wake-scan-sleep cycles under the queue lock).
                # Shutdown wake-everyone stays notify_all in shutdown().
                self._cond.notify()

    def _execute(self, job: _Job, worker_id: int) -> bool:
        """Run one job attempt under the job's trace context; returns True
        iff the job was RE-ENQUEUED for retry (the worker then keeps its
        serial key owned — releasing it would let a later sibling overtake
        the retry)."""
        with _trace.attach(job.span):
            return self._execute_attempt(job, worker_id)

    def _execute_attempt(self, job: _Job, worker_id: int) -> bool:
        now = time.monotonic()
        if job.deadline_abs is not None and now > job.deadline_abs:
            # don't waste a run on a job that already missed its budget
            job.span.add_event(
                "queued_past_deadline", waited_s=now - job.submit_time
            )
            timeout = JobTimeout(
                job.job_id, job.deadline_s, now - job.submit_time
            )
            # a deadline-killed fold job never ran its body: withdraw the
            # pending fold (releasing the session's serial barrier) so it
            # cannot linger claimable after its caller was told timeout
            self._recover(job, None, timeout)
            self._finish(job, None, timeout, outcome="timeout")
            return False
        job.attempts += 1
        job.span.add_event(
            "picked_up", worker=worker_id, attempt=job.attempts
        )
        if job.attempts == 1:
            # first pickup only: retries measure backoff, not admission
            self.metrics.observe(
                "deequ_service_admission_wait_seconds",
                now - job.submit_time, tenant=job.tenant,
                priority=_priority_label(job.priority),
            )
        # fleet: lease the tenant's sub-mesh for THIS attempt — disjoint
        # from other tenants' slices, re-packed over survivors when a
        # shard dropped out of the ladder since the last attempt. The
        # release lives in a finally so no path out of the attempt —
        # including a raising router.decide — can leak the refcount (a
        # leaked ref would pin a phantom tenant into every future
        # packing)
        lease = None
        if job.mesh_tenant is not None and self.fleet is not None:
            lease = self.fleet.acquire(job.mesh_tenant)
        try:
            ctx = JobContext(
                job_id=job.job_id, tenant=job.tenant, attempt=job.attempts,
                worker_id=worker_id,
                placement=self.router.decide(job.signature, job.warm_fn),
                mesh=lease.mesh if lease is not None else None,
            )
            job.span.add_event(
                "placement", decision=ctx.placement or "auto",
                attempt=job.attempts,
                **({"fleet_devices": lease.n_dev}
                   if lease is not None else {}),
            )
            try:
                from ..reliability.faults import fault_point

                # chaos site: a WorkerCrash here simulates the worker
                # dying mid-job (executor loss); the job must still
                # terminate typed
                fault_point("worker", tag=str(worker_id))
                value = job.fn(ctx)
            except BaseException as exc:  # noqa: BLE001 - routed into
                # the taxonomy below
                # commit/job-finish atomicity: before failing a job whose
                # BODY may not have run (an injected worker fault fires
                # between pickup and fn), let its recover_fn reconcile —
                # a coalesced drain that already committed the job's fold
                # makes the job SUCCEED with the committed result, and an
                # unclaimed fold is withdrawn so no later drain can
                # commit work the caller was told failed (the chaos
                # soak's stream_fold_parity invariant)
                adopted = self._recover(job, ctx, exc)
                self._harvest(job, ctx)
                if adopted is not None and adopted[1] is None:
                    self._finish(job, adopted[0], None, outcome="success")
                    return False
                if adopted is not None:
                    exc = adopted[1]
                if self._maybe_retry(job, exc):
                    return True  # worker keeps the serial key (FIFO)
                if isinstance(exc, ServiceError) and not isinstance(
                    exc, TransientFailure
                ):
                    self._finish(job, None, exc, outcome="failed")
                else:
                    self._finish(
                        job, None,
                        JobFailed(job.job_id, job.attempts, exc),
                        outcome="failed",
                    )
                return False
            self._harvest(job, ctx)
        finally:
            if lease is not None:
                self.fleet.release(job.mesh_tenant)
        # the monitor records the placement the engine actually RESOLVED
        # (None for jobs that never touched the engine)
        self.router.note_ran(job.signature, worker_id, ctx.monitor.placement)
        end = time.monotonic()
        if job.deadline_abs is not None and end > job.deadline_abs:
            # the work COMPLETED, just late — its side effects (streaming
            # state folds, repository saves) have committed, so the result
            # stays reachable on the handle (late_value) while the caller
            # gets the typed timeout; discarding it would bait callers into
            # re-running committed work
            job.span.add_event(
                "completed_late", waited_s=end - job.submit_time
            )
            job.handle.late_value = value
            self._finish(
                job, None,
                JobTimeout(
                    job.job_id, job.deadline_s, end - job.submit_time,
                    completed=True,
                ),
                outcome="timeout",
            )
            return False
        self._finish(job, value, None, outcome="success")
        return False

    def _recover(self, job: _Job, ctx, exc: BaseException):
        """Consult the job's recover_fn (see `submit`); defensive — a
        raising recover_fn must not mask the original failure."""
        if job.recover_fn is None:
            return None
        try:
            return job.recover_fn(ctx, exc)
        except BaseException:  # noqa: BLE001 - keep the original error
            import logging

            logging.getLogger(__name__).warning(
                "recover_fn for job %s raised; keeping the original "
                "failure", job.job_id, exc_info=True,
            )
            return None

    def finish_absorbed(self, absorbed) -> None:
        """Resolve jobs whose WORK was already executed by a coalesced
        drain while they sat in the ready queue: each is removed from the
        queue (one lock round-trip for the whole batch) and finished with
        its fold's outcome — it never occupies a worker. This is the
        batched-harvest half of the streaming-knee scheduler diet: a
        512-fold drain retires up to 511 sibling jobs without 511
        wake/pick/execute/finish cycles.

        ``absorbed``: iterable of ``(handle, value, error, tenant,
        monitor, signature, worker_id)``. Entries whose job was already
        picked up (or retried) are skipped — the running job consumes the
        fold's memoized outcome itself. Only deadline-FREE jobs are ever
        absorbed (the coalescer never drains deadline'd folds), so the
        queued-past-deadline contract is untouched."""
        entries = list(absorbed)
        if not entries:
            return
        handles = {e[0] for e in entries}
        found: Dict[Any, _Job] = {}
        with self._cond:
            kept = []
            for entry in self._ready:
                job = entry[2]
                if job.handle in handles:
                    found[job.handle] = job
                else:
                    kept.append(entry)
            if found:
                self._ready = kept
                # the absorptions freed queue slots: wake as many blocked
                # backpressure submitters
                self._space.notify(len(found))
        updates: list = []
        for handle, value, error, tenant, monitor, signature, worker_id in entries:
            job = found.get(handle)
            if job is None:
                continue
            job.attempts = 1  # the drain WAS the attempt
            job.span.add_event("absorbed_by_drain")
            self._harvest_monitor(
                tenant, monitor, job.handle, signature, updates=updates
            )
            if error is None:
                self.router.note_ran(signature, worker_id, monitor.placement)
                self._finish(job, value, None, outcome="success")
            elif isinstance(error, ServiceError) and not isinstance(
                error, TransientFailure
            ):
                self._finish(job, None, error, outcome="failed")
            else:
                self._finish(
                    job, None, JobFailed(job.job_id, 1, error),
                    outcome="failed",
                )
        if updates:
            self.metrics.inc_many(updates)

    def _harvest(self, job: _Job, ctx: JobContext) -> None:
        self._harvest_monitor(
            job.tenant, ctx.monitor, job.handle, job.signature
        )

    def _harvest_monitor(
        self, tenant: str, monitor: RunMonitor, handle: JobHandle, signature,
        updates: Optional[list] = None,
    ) -> None:
        # ONE batched metrics-lock round-trip for the whole harvest: at
        # thousands of folds/s the previous per-series inc() calls (phase
        # map + cost table + up to 8 reliability series, each taking the
        # export-plane lock) were a measurable slice of the per-fold fixed
        # cost the coalescing plane exists to kill. A caller-provided
        # ``updates`` list defers the flush — finish_absorbed batches a
        # whole drain's harvests into ONE round-trip.
        flush = updates is None
        if flush:
            updates = []
        updates += [
            ("deequ_service_phase_seconds_total", seconds, {"phase": phase})
            for phase, seconds in monitor.phase_seconds.items()
        ]
        for phase, seconds in monitor.phase_seconds.items():
            handle.phase_seconds[phase] = (
                handle.phase_seconds.get(phase, 0.0) + seconds
            )
        tenant_label = {"tenant": tenant}
        updates.extend(
            ("deequ_service_analyzer_cost_seconds_total", seconds,
             {"analyzer": analyzer, "tenant": tenant})
            for analyzer, seconds in dict(monitor.cost_by_analyzer).items()
        )
        if monitor.stalls:
            # every stall surfaces on the export plane; only DEVICE-tier
            # stalls feed probation below (pinning a battery to the host
            # tier because the HOST hung would probation it onto the sick
            # tier)
            updates.append(
                ("deequ_service_scan_stalls_total", float(monitor.stalls),
                 tenant_label)
            )
        # mesh elasticity on the export plane: every shard loss, every
        # re-shard (in-pass or pass-level) and every salvaged state is
        # countable per tenant — the acceptance signal that a loss was
        # absorbed rather than fatal
        if monitor.shard_losses:
            updates.append(
                ("deequ_service_shard_losses_total",
                 float(monitor.shard_losses), tenant_label)
            )
        if monitor.mesh_reshards:
            updates.append(
                ("deequ_service_mesh_reshards_total",
                 float(monitor.mesh_reshards), tenant_label)
            )
        if monitor.salvaged_states:
            updates.append(
                ("deequ_service_salvaged_states_total",
                 float(monitor.salvaged_states), tenant_label)
            )
        if monitor.isolation_reruns:
            updates.append(
                ("deequ_service_isolation_reruns_total",
                 float(monitor.isolation_reruns), tenant_label)
            )
        # incremental verification: the delta planner's per-run partition
        # decisions, per tenant — the export-plane record of how much data
        # the state reuse actually saved
        for field_name, series in (
            ("partitions_scanned", "deequ_service_partitions_scanned_total"),
            ("partitions_reused", "deequ_service_partitions_reused_total"),
            ("partitions_invalidated",
             "deequ_service_partitions_invalidated_total"),
            ("partitions_dropped", "deequ_service_partitions_dropped_total"),
            ("partitions_rolled_up",
             "deequ_service_partitions_rolled_up_total"),
        ):
            value = getattr(monitor, field_name)
            if value:
                updates.append((series, float(value), tenant_label))
        if monitor.degraded:
            updates.append(
                ("deequ_service_degraded_analyzers_total",
                 float(len(monitor.degraded)), tenant_label)
            )
        if flush:
            self.metrics.inc_many(updates)
        if (
            monitor.device_failovers
            or monitor.batch_bisections
            or monitor.device_stalls
            or monitor.shard_losses
        ):
            # the engine survived a device-tier fault under this battery:
            # teach the router to keep the battery on the host tier for a
            # probation window (also fires on failed attempts, so a retry
            # lands on the healthy tier immediately)
            self.router.note_device_failure(signature)
        if monitor.shard_losses and self.fleet is not None:
            # a shard dropped out of the ladder during this job: make sure
            # the fleet packing reflects it (the elastic loss listener
            # usually already did — this probe-and-repack is the backstop
            # for pass-level GSPMD failures that never named a device)
            self.fleet.note_shard_loss()
        for listener in self._harvest_listeners:
            # the fleet watch's trigger: a completed job means this tenant
            # may have committed fresh metrics. Defensive — a raising
            # listener must not take the harvested job down with it, and
            # listeners run outside every scheduler lock (they typically
            # re-enter submit())
            try:
                listener(tenant)
            except Exception:  # noqa: BLE001 - observability only
                import logging

                logging.getLogger(__name__).warning(
                    "harvest listener failed for tenant %s", tenant,
                    exc_info=True,
                )

    def add_harvest_listener(self, fn: Callable[[str], None]) -> None:
        """Register ``fn(tenant)`` to run after every job harvest (outside
        the queue lock; exceptions are swallowed with a warning). The
        fleet watch uses this as its standing re-score trigger."""
        self._harvest_listeners.append(fn)

    def _maybe_retry(self, job: _Job, exc: BaseException) -> bool:
        from ..exceptions import ScanStallError

        # an ESCAPED stall (both tiers hung, or the battery could not fail
        # over) is retryable by construction: the watchdog already killed
        # the pass, the worker is free, and the placement router has moved
        # the battery onto probation — requeueing gives the job its healthy
        # tier instead of failing it outright
        retryable = (
            isinstance(exc, (TransientFailure, ScanStallError))
            or (job.retry_on and isinstance(exc, job.retry_on))
        )
        if not retryable or job.attempts > job.max_retries:
            return False
        delay = job.retry_backoff_s * (2 ** (job.attempts - 1))
        not_before = time.monotonic() + delay
        if job.deadline_abs is not None and not_before > job.deadline_abs:
            return False  # the backoff alone would blow the deadline
        job.span.add_event(
            "retry", attempt=job.attempts, delay_s=delay,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        self.metrics.inc("deequ_service_job_retries_total", tenant=job.tenant)
        with self._cond:
            heapq.heappush(self._delayed, (not_before, next(self._seq), job))
            self._cond.notify()
        return True

    def _finish(
        self, job: _Job, value: Any, error: Optional[BaseException], outcome: str
    ) -> None:
        self.metrics.inc(
            "deequ_service_jobs_completed_total",
            tenant=job.tenant, outcome=outcome,
        )
        if job.serial_key is not None:
            # serial-keyed jobs ARE the streaming folds; this single
            # terminal site covers serial, coalesced, and drain-absorbed
            # completions alike (all funnel through _finish)
            self.metrics.observe(
                "deequ_service_fold_latency_seconds",
                time.monotonic() - job.submit_time, tenant=job.tenant,
                priority=_priority_label(job.priority),
            )
        job.span.add_event(
            "outcome", outcome=outcome, attempts=job.attempts,
            **({"error": f"{type(error).__name__}: {str(error)[:200]}"}
               if error is not None else {}),
        )
        # finishing the job span closes the trace's unit of work — this is
        # also what releases any pending flight-recorder dump for it
        job.span.finish("ok" if error is None else "error")
        job.handle.attempts = job.attempts
        job.handle._finish(value, error)

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self, wait: bool = True, timeout: Optional[float] = None) -> None:
        """Stop intake; workers drain every pending job, then exit."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            # backpressure submitters parked on a full queue must wake to
            # their typed ServiceClosed instead of out-waiting block_s
            self._space.notify_all()
        if wait:
            deadline = None if timeout is None else time.monotonic() + timeout
            for t in self._workers:
                left = (
                    None if deadline is None
                    else max(deadline - time.monotonic(), 0.0)
                )
                t.join(left)
        self.router.close()
