"""The export plane: service observability for an operator.

`ServiceMetrics` aggregates what the per-run :class:`RunMonitor` phase
timers already measure (`runners/engine.py`) with scheduler-level counters
(queue depth, retries, sheds, timeouts) and placement-cache hit rates, and
renders them as either a Prometheus text exposition or a JSON snapshot.
`MetricsExporter` serves both over HTTP from a background thread — the
subsystem the one-shot CLI mode never needed and a long-lived service
cannot run without.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, Optional, Tuple

_LabelKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def _labels_key(name: str, labels: Dict[str, str]) -> _LabelKey:
    return name, tuple(sorted(labels.items()))


def _escape_snapshot_value(value: str) -> str:
    """JSON-snapshot series keys join labels with ','/'='; escape those in
    the value so distinct label sets cannot collide on one key."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace(",", "\\,")
        .replace("=", "\\=")
    )


def _escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: one odd tenant name must not
    poison the whole exposition (scrapers reject the entire payload)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """Prometheus HELP-line escaping (backslash and newline only)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


class ServiceMetrics:
    """Thread-safe counter/gauge registry with Prometheus + JSON export.

    Counters are monotonic floats keyed by (name, sorted label items);
    gauges are CALLABLES evaluated at export time, so queue depth and
    session counts are always live rather than sampled. Phase timings
    accumulate under ``deequ_service_phase_seconds_total{phase=...}``
    straight from each job's ``RunMonitor.phase_seconds``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[_LabelKey, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._help: Dict[str, str] = {}
        self.describe(
            "deequ_service_export_errors_total",
            "Gauge callables that raised during an exposition; the series "
            "was skipped so the rest of the scrape kept serving.",
        )
        self.describe(
            "deequ_service_phase_seconds_total",
            "Engine phase wall-clock accumulated across runs, by phase "
            "(straight from each job's RunMonitor.phase_seconds).",
        )

    # -- registration / update ----------------------------------------------

    def describe(self, name: str, help_text: str) -> None:
        with self._lock:
            self._help[name] = help_text

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = _labels_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def inc_many(self, updates) -> None:
        """Batched counter increments under ONE lock round-trip.
        ``updates``: iterable of ``(name, value, labels_dict)``. The
        scheduler's per-job harvest and the streaming commit path bump up
        to a dozen series per fold; at thousands of folds per second the
        per-``inc`` lock traffic was measurable (the streaming-knee
        scheduler diet)."""
        with self._lock:
            counters = self._counters
            for name, value, labels in updates:
                key = _labels_key(name, labels)
                counters[key] = counters.get(key, 0.0) + value

    def counter_value(self, name: str, **labels: str) -> float:
        """Current value of one counter series (0.0 when never touched)."""
        with self._lock:
            if labels:
                return self._counters.get(_labels_key(name, labels), 0.0)
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def set_gauge_fn(
        self, name: str, fn: Callable[[], float], help_text: Optional[str] = None
    ) -> None:
        with self._lock:
            self._gauges[name] = fn
            if help_text:
                self._help[name] = help_text

    def observe_phases(self, phase_seconds: Dict[str, float]) -> None:
        """Fold one run's ``RunMonitor.phase_seconds`` into the plane
        (one lock round-trip for the whole phase map)."""
        self.inc_many([
            ("deequ_service_phase_seconds_total", seconds, {"phase": phase})
            for phase, seconds in phase_seconds.items()
        ])

    # -- export --------------------------------------------------------------

    def _eval_gauges(self) -> Dict[str, float]:
        """Evaluate every registered gauge. A RAISING gauge must not kill
        the whole exposition: its series is SKIPPED for this scrape (a NaN
        placeholder would poison recording rules; absence is the honest
        signal) and the failure is counted under
        ``deequ_service_export_errors_total{gauge=...}`` so the breakage
        itself is monitorable."""
        out = {}
        with self._lock:  # snapshot: a scrape must not race set_gauge_fn
            gauges = list(self._gauges.items())
        failed = []
        for name, fn in gauges:
            try:
                out[name] = float(fn())
            except Exception:  # noqa: BLE001 - skip, count, keep serving
                failed.append(name)
        for name in failed:
            self.inc("deequ_service_export_errors_total", gauge=name)
        return out

    def json_snapshot(self) -> Dict[str, Any]:
        """One JSON-able dict of everything the plane knows right now.
        Non-finite gauge readings (a dead gauge) become ``None`` — a bare
        NaN token would make the whole payload unparseable to strict JSON
        parsers."""
        import math
        # evaluate gauges BEFORE snapshotting counters: a raising gauge
        # increments the export-error counter, and this snapshot should
        # already show that increment (mirrors prometheus_text)
        gauge_values = self._eval_gauges()
        with self._lock:
            counters = dict(self._counters)
        series: Dict[str, Any] = {}
        for (name, labels), value in sorted(counters.items()):
            if labels:
                # escape the joiners so arbitrary caller strings (tenant
                # names) cannot produce ambiguous or colliding series keys
                series.setdefault(name, {})[
                    ",".join(
                        f"{k}={_escape_snapshot_value(v)}" for k, v in labels
                    )
                ] = value
            else:
                series[name] = value
        gauges = {
            name: (value if math.isfinite(value) else None)
            for name, value in gauge_values.items()
        }
        return {"counters": series, "gauges": gauges}

    def json_text(self) -> str:
        return json.dumps(self.json_snapshot(), sort_keys=True)

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4. EVERY series gets its
        ``# HELP`` and ``# TYPE`` lines — scrapers and ``promtool check
        metrics`` expect them; an undescribed series gets a generated
        placeholder rather than a bare sample."""
        # evaluate gauges FIRST: a raising gauge increments the export-error
        # counter, and this scrape should already show that increment
        gauges = self._eval_gauges()
        with self._lock:
            counters = dict(self._counters)
            help_texts = dict(self._help)

        def help_line(name: str) -> str:
            text = help_texts.get(name, f"{name} (no description registered).")
            return f"# HELP {name} {_escape_help(text)}"

        lines = []
        seen_header = set()
        for (name, labels), value in sorted(counters.items()):
            if name not in seen_header:
                seen_header.add(name)
                lines.append(help_line(name))
                lines.append(f"# TYPE {name} counter")
            lines.append(f"{name}{_render_labels(labels)} {_format(value)}")
        for name, value in sorted(gauges.items()):
            lines.append(help_line(name))
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_format(value)}")
        return "\n".join(lines) + "\n"


def _format(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == float("inf"):
        return "+Inf"  # int(inf) raises; Prometheus accepts the literal
    if value == float("-inf"):
        return "-Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


class MetricsExporter:
    """Serves ``/metrics`` (Prometheus text), ``/metrics.json``, the
    trace plane — ``/trace`` (Chrome trace-event / Perfetto-loadable JSON
    of the flight-recorder ring) and ``/trace.jsonl`` (the span journal) —
    and, when constructed with an ``ingest`` endpoint, the Arrow IPC
    ingestion frontend (``POST /ingest/v1/<tenant>/<dataset>``, see
    `deequ_tpu.ingest.endpoint`) — from a daemon thread. Binds to an
    ephemeral port by default (``port=0``); the bound port is on
    ``.port``."""

    def __init__(
        self,
        metrics: ServiceMetrics,
        host: str = "127.0.0.1",
        port: int = 0,
        ingest: Optional[Any] = None,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        plane = metrics
        ingest_endpoint = ingest

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if ingest_endpoint is None or not ingest_endpoint.matches(
                    self.path
                ):
                    self.send_error(404)
                    return
                from ..ingest.endpoint import render_response

                status, body_dict = ingest_endpoint.handle_post(
                    self.path, self.headers, self.rfile
                )
                body = render_response(status, body_dict)
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # a producer that died mid-stream cannot read its
                    # error; the fold report already landed on the
                    # counters and flight record
                    self.close_connection = True

            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if self.path.startswith("/metrics.json"):
                    body = plane.json_text().encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    body = plane.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/trace.jsonl"):
                    from ..observability import export as _obs_export

                    body = _obs_export.spans_to_jsonl().encode()
                    ctype = "application/jsonl"
                elif self.path.startswith("/trace"):
                    from ..observability import export as _obs_export

                    body = _obs_export.chrome_trace_text().encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            # a producer that stops sending mid-body must not pin a
            # handler thread forever: the socket read times out and the
            # ingest path records a typed disconnect
            timeout = 30

            def log_message(self, *args):  # quiet: the plane IS the log
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="deequ-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
