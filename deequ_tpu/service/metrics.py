"""The export plane: service observability for an operator.

`ServiceMetrics` aggregates what the per-run :class:`RunMonitor` phase
timers already measure (`runners/engine.py`) with scheduler-level counters
(queue depth, retries, sheds, timeouts) and placement-cache hit rates, and
renders them as either a Prometheus text exposition or a JSON snapshot.
`MetricsExporter` serves both over HTTP from a background thread — the
subsystem the one-shot CLI mode never needed and a long-lived service
cannot run without.
"""

from __future__ import annotations

import json
import threading
from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Tuple

_LabelKey = Tuple[str, Tuple[Tuple[str, str], ...]]

#: fixed pow2 bucket edges (seconds) shared by EVERY histogram family:
#: 2^-20 s (~0.95 µs) .. 2^6 s (64 s), 27 finite buckets + the +Inf
#: overflow slot. FIXED edges are the whole design: two snapshots of the
#: same family — from two scrapes, two tenants, or two HOSTS — merge by
#: plain vector add (the same algebra as the sketch states), which is what
#: makes per-host histograms aggregable into fleet-level quantiles.
HISTOGRAM_EDGES: Tuple[float, ...] = tuple(2.0 ** e for e in range(-20, 7))


class _HistCell:
    """One (family, label set) histogram: bucket counts + sum + count."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self) -> None:
        self.counts = [0] * (len(HISTOGRAM_EDGES) + 1)
        self.sum = 0.0
        self.count = 0

    def state(self) -> Dict[str, Any]:
        return {"counts": list(self.counts), "sum": self.sum,
                "count": self.count}


def merge_histogram_states(*states: Dict[str, Any]) -> Dict[str, Any]:
    """Vector-add histogram states (the mergeable-bucket algebra). All
    states share :data:`HISTOGRAM_EDGES`, so the merge is associative and
    commutative — fold per-host snapshots in any order."""
    counts = [0] * (len(HISTOGRAM_EDGES) + 1)
    total_sum = 0.0
    total_count = 0
    for state in states:
        for i, c in enumerate(state["counts"]):
            counts[i] += c
        total_sum += state["sum"]
        total_count += state["count"]
    return {"counts": counts, "sum": total_sum, "count": total_count}


def histogram_quantile(state: Dict[str, Any], q: float) -> Optional[float]:
    """Upper-edge quantile estimate from bucket counts (what a scraper
    computes from the ``_bucket`` lines): the smallest bucket edge whose
    cumulative count covers ``q`` of the observations. ``None`` for an
    empty histogram; ``inf`` when the quantile lands in the overflow
    bucket."""
    total = state["count"]
    if total <= 0:
        return None
    target = max(q, 0.0) * total
    cumulative = 0
    for i, c in enumerate(state["counts"]):
        cumulative += c
        if cumulative >= target and cumulative > 0:
            if i < len(HISTOGRAM_EDGES):
                return HISTOGRAM_EDGES[i]
            return float("inf")
    return float("inf")


def histogram_fraction_le(state: Dict[str, Any], threshold: float) -> float:
    """Fraction of observations ``<= threshold`` (resolved at bucket
    granularity: buckets whose upper edge fits under the threshold). The
    SLO evaluator's achieved-fraction primitive. 1.0 on an empty state —
    no traffic violates no objective."""
    total = state["count"]
    if total <= 0:
        return 1.0
    good = sum(
        c for i, c in enumerate(state["counts"])
        if i < len(HISTOGRAM_EDGES) and HISTOGRAM_EDGES[i] <= threshold
    )
    return good / total


def _labels_key(name: str, labels: Dict[str, str]) -> _LabelKey:
    return name, tuple(sorted(labels.items()))


def _escape_snapshot_value(value: str) -> str:
    """JSON-snapshot series keys join labels with ','/'='; escape those in
    the value so distinct label sets cannot collide on one key."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace(",", "\\,")
        .replace("=", "\\=")
    )


def _escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: one odd tenant name must not
    poison the whole exposition (scrapers reject the entire payload)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """Prometheus HELP-line escaping (backslash and newline only)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


class ServiceMetrics:
    """Thread-safe counter/gauge registry with Prometheus + JSON export.

    Counters are monotonic floats keyed by (name, sorted label items);
    gauges are CALLABLES evaluated at export time, so queue depth and
    session counts are always live rather than sampled. Phase timings
    accumulate under ``deequ_service_phase_seconds_total{phase=...}``
    straight from each job's ``RunMonitor.phase_seconds``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[_LabelKey, float] = {}
        self._gauges: Dict[_LabelKey, Callable[[], float]] = {}
        self._histograms: Dict[_LabelKey, _HistCell] = {}
        self._help: Dict[str, str] = {}
        self.describe(
            "deequ_service_export_errors_total",
            "Gauge callables that raised during an exposition; the series "
            "was skipped so the rest of the scrape kept serving.",
        )
        self.describe(
            "deequ_service_phase_seconds_total",
            "Engine phase wall-clock accumulated across runs, by phase "
            "(straight from each job's RunMonitor.phase_seconds).",
        )

    # -- registration / update ----------------------------------------------

    def describe(self, name: str, help_text: str) -> None:
        with self._lock:
            self._help[name] = help_text

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = _labels_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def inc_many(self, updates) -> None:
        """Batched counter increments under ONE lock round-trip.
        ``updates``: iterable of ``(name, value, labels_dict)``. The
        scheduler's per-job harvest and the streaming commit path bump up
        to a dozen series per fold; at thousands of folds per second the
        per-``inc`` lock traffic was measurable (the streaming-knee
        scheduler diet)."""
        with self._lock:
            counters = self._counters
            for name, value, labels in updates:
                key = _labels_key(name, labels)
                counters[key] = counters.get(key, 0.0) + value

    def counter_value(self, name: str, **labels: str) -> float:
        """Current value of one counter series (0.0 when never touched)."""
        with self._lock:
            if labels:
                return self._counters.get(_labels_key(name, labels), 0.0)
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def set_gauge_fn(
        self, name: str, fn: Callable[[], float],
        help_text: Optional[str] = None, **labels: str,
    ) -> None:
        with self._lock:
            self._gauges[_labels_key(name, labels)] = fn
            if help_text:
                self._help[name] = help_text

    # -- histograms ----------------------------------------------------------

    def describe_histogram(self, name: str, help_text: str) -> None:
        """Register a histogram family's HELP text. Every family MUST be
        described (the export-HELP statlint check enforces it, exactly as
        for counters)."""
        with self._lock:
            self._help[name] = help_text

    def observe(self, name: str, value: float, **labels: str) -> None:
        """Record one observation (seconds) into the family's pow2
        buckets. NaN observations are dropped — they cannot be bucketed
        and would poison ``_sum``."""
        if value != value:  # NaN
            return
        key = _labels_key(name, labels)
        idx = bisect_left(HISTOGRAM_EDGES, value)
        with self._lock:
            cell = self._histograms.get(key)
            if cell is None:
                cell = self._histograms[key] = _HistCell()
            cell.counts[idx] += 1
            cell.sum += value
            cell.count += 1

    def histogram_state(
        self, name: str, **labels: str
    ) -> Optional[Dict[str, Any]]:
        """Snapshot of ONE (family, label set) cell, or None if never
        observed."""
        with self._lock:
            cell = self._histograms.get(_labels_key(name, labels))
            return cell.state() if cell is not None else None

    def histogram_cells(
        self, name: str
    ) -> List[Tuple[Tuple[Tuple[str, str], ...], Dict[str, Any]]]:
        """All (label items, state) cells of a family — the input to
        cross-label merges (fleet quantiles, SLO achieved fractions)."""
        with self._lock:
            return [
                (labels, cell.state())
                for (n, labels), cell in sorted(self._histograms.items())
                if n == name
            ]

    def histogram_merged(self, name: str, **labels: str) -> Dict[str, Any]:
        """Merge every cell of a family whose labels contain ``labels`` as
        a subset (no filter = the whole family) — vector-add algebra."""
        wanted = set(labels.items())
        states = [
            state for cell_labels, state in self.histogram_cells(name)
            if wanted.issubset(set(cell_labels))
        ]
        return merge_histogram_states(*states)

    def observe_phases(self, phase_seconds: Dict[str, float]) -> None:
        """Fold one run's ``RunMonitor.phase_seconds`` into the plane
        (one lock round-trip for the whole phase map)."""
        self.inc_many([
            ("deequ_service_phase_seconds_total", seconds, {"phase": phase})
            for phase, seconds in phase_seconds.items()
        ])

    # -- export --------------------------------------------------------------

    def _eval_gauges(self) -> Dict[str, float]:
        """Evaluate every registered gauge. A RAISING gauge must not kill
        the whole exposition: its series is SKIPPED for this scrape (a NaN
        placeholder would poison recording rules; absence is the honest
        signal) and the failure is counted under
        ``deequ_service_export_errors_total{gauge=...}`` so the breakage
        itself is monitorable."""
        out: Dict[_LabelKey, float] = {}
        with self._lock:  # snapshot: a scrape must not race set_gauge_fn
            gauges = list(self._gauges.items())
        failed = []
        for key, fn in gauges:
            try:
                out[key] = float(fn())
            except Exception:  # noqa: BLE001 - skip, count, keep serving
                failed.append(key[0])
        for name in failed:
            self.inc("deequ_service_export_errors_total", gauge=name)
        return out

    def json_snapshot(self) -> Dict[str, Any]:
        """One JSON-able dict of everything the plane knows right now.
        Non-finite gauge readings (a dead gauge) become ``None`` — a bare
        NaN token would make the whole payload unparseable to strict JSON
        parsers."""
        import math
        # evaluate gauges BEFORE snapshotting counters: a raising gauge
        # increments the export-error counter, and this snapshot should
        # already show that increment (mirrors prometheus_text)
        gauge_values = self._eval_gauges()
        with self._lock:
            counters = dict(self._counters)
            hists = {
                key: cell.state() for key, cell in self._histograms.items()
            }

        def label_join(labels) -> str:
            # escape the joiners so arbitrary caller strings (tenant
            # names) cannot produce ambiguous or colliding series keys
            return ",".join(
                f"{k}={_escape_snapshot_value(v)}" for k, v in labels
            )

        series: Dict[str, Any] = {}
        for (name, labels), value in sorted(counters.items()):
            if labels:
                series.setdefault(name, {})[label_join(labels)] = value
            else:
                series[name] = value
        gauges: Dict[str, Any] = {}
        for (name, labels), value in sorted(gauge_values.items()):
            clean = value if math.isfinite(value) else None
            if labels:
                # labeled gauges nest like labeled counters; UNLABELED
                # ones keep the flat name -> value shape callers rely on
                gauges.setdefault(name, {})[label_join(labels)] = clean
            else:
                gauges[name] = clean
        histograms: Dict[str, Any] = {}
        for (name, labels), state in sorted(hists.items()):
            histograms.setdefault(name, {})[label_join(labels)] = state
        return {"counters": series, "gauges": gauges,
                "histograms": histograms}

    def json_text(self) -> str:
        return json.dumps(self.json_snapshot(), sort_keys=True)

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4. EVERY series gets its
        ``# HELP`` and ``# TYPE`` lines — scrapers and ``promtool check
        metrics`` expect them; an undescribed series gets a generated
        placeholder rather than a bare sample."""
        # evaluate gauges FIRST: a raising gauge increments the export-error
        # counter, and this scrape should already show that increment
        gauges = self._eval_gauges()
        with self._lock:
            counters = dict(self._counters)
            help_texts = dict(self._help)
            hists = {
                key: cell.state() for key, cell in self._histograms.items()
            }

        def help_line(name: str) -> str:
            text = help_texts.get(name, f"{name} (no description registered).")
            return f"# HELP {name} {_escape_help(text)}"

        lines = []
        seen_header = set()
        for (name, labels), value in sorted(counters.items()):
            if name not in seen_header:
                seen_header.add(name)
                lines.append(help_line(name))
                lines.append(f"# TYPE {name} counter")
            lines.append(f"{name}{_render_labels(labels)} {_format(value)}")
        for (name, labels), value in sorted(gauges.items()):
            if name not in seen_header:
                seen_header.add(name)
                lines.append(help_line(name))
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{_render_labels(labels)} {_format(value)}")
        for (name, labels), state in sorted(hists.items()):
            if name not in seen_header:
                seen_header.add(name)
                lines.append(help_line(name))
                lines.append(f"# TYPE {name} histogram")
            # Prometheus histogram convention: CUMULATIVE le buckets
            # (every bucket includes all smaller ones, +Inf == _count),
            # then the _sum/_count pair
            cumulative = 0
            for i, edge in enumerate(HISTOGRAM_EDGES):
                cumulative += state["counts"][i]
                bucket_labels = labels + (("le", _format(edge)),)
                lines.append(
                    f"{name}_bucket{_render_labels(bucket_labels)} "
                    f"{cumulative}"
                )
            cumulative += state["counts"][-1]
            inf_labels = labels + (("le", "+Inf"),)
            lines.append(
                f"{name}_bucket{_render_labels(inf_labels)} {cumulative}"
            )
            lines.append(
                f"{name}_sum{_render_labels(labels)} {_format(state['sum'])}"
            )
            lines.append(
                f"{name}_count{_render_labels(labels)} {state['count']}"
            )
        return "\n".join(lines) + "\n"


class SloEvaluator:
    """Objective (latency target + achieved-fraction goal) over a sliding
    window -> current burn rate, fed straight from the histogram buckets.

    ``burn rate`` follows the multiwindow-alert convention: the ratio of
    the error budget consumed per unit time to the budget the objective
    allows — ``(1 - achieved) / (1 - objective)`` over the window. 1.0
    means burning exactly at budget; >1 means the objective will be missed
    if the window's behavior continues; 0 means no violations at all.

    The evaluator keeps a ring of (monotonic time, good count, total
    count) samples per objective: evaluating takes a fresh histogram
    snapshot, appends it, and differences against the oldest sample still
    inside the window — so the burn rate reflects the WINDOW, not the
    process's whole life.
    """

    def __init__(self, metrics: ServiceMetrics):
        self._metrics = metrics
        self._lock = threading.Lock()
        self._objectives: Dict[str, Dict[str, Any]] = {}

    def add_objective(
        self,
        slug: str,
        histogram: str,
        threshold_s: float,
        objective: float = 0.99,
        window_s: float = 300.0,
        **labels: str,
    ) -> None:
        """Register one objective: fraction ``objective`` of observations
        in ``histogram`` (filtered to cells whose labels contain
        ``labels``) must land at or under ``threshold_s`` seconds."""
        objective = min(max(float(objective), 0.0), 0.9999)
        with self._lock:
            self._objectives[slug] = {
                "histogram": histogram, "threshold_s": float(threshold_s),
                "objective": objective, "window_s": float(window_s),
                "labels": dict(labels), "samples": [],
            }

    def objectives(self) -> List[str]:
        with self._lock:
            return sorted(self._objectives)

    def _good_total(self, spec: Dict[str, Any]) -> Tuple[float, float]:
        state = self._metrics.histogram_merged(
            spec["histogram"], **spec["labels"]
        )
        good = histogram_fraction_le(state, spec["threshold_s"]) * state[
            "count"
        ]
        return good, float(state["count"])

    def burn_rate(self, slug: str, now: Optional[float] = None) -> float:
        """Current burn rate for one objective (0.0 when the window saw no
        traffic — idle tenants are not on fire)."""
        import time as _time

        if now is None:
            now = _time.monotonic()
        with self._lock:
            spec = self._objectives.get(slug)
            if spec is None:
                raise KeyError(slug)
        good, total = self._good_total(spec)
        with self._lock:
            samples = spec["samples"]
            samples.append((now, good, total))
            horizon = now - spec["window_s"]
            # keep ONE sample at or before the horizon so the window
            # delta spans the full window, drop everything staler
            while len(samples) > 1 and samples[1][0] <= horizon:
                samples.pop(0)
            base_t, base_good, base_total = samples[0]
            delta_total = total - base_total
            delta_good = good - base_good
            objective = spec["objective"]
        if delta_total <= 0:
            return 0.0
        achieved = min(max(delta_good / delta_total, 0.0), 1.0)
        return (1.0 - achieved) / (1.0 - objective)

    def burn_rates(self, now: Optional[float] = None) -> Dict[str, float]:
        return {slug: self.burn_rate(slug, now) for slug in self.objectives()}


def _format(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == float("inf"):
        return "+Inf"  # int(inf) raises; Prometheus accepts the literal
    if value == float("-inf"):
        return "-Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


class MetricsExporter:
    """Serves ``/metrics`` (Prometheus text), ``/metrics.json``, the
    trace plane — ``/trace`` (Chrome trace-event / Perfetto-loadable JSON
    of the flight-recorder ring) and ``/trace.jsonl`` (the span journal) —
    the unified ops snapshot (``/statusz``, when constructed with a
    ``statusz`` callable; see `deequ_tpu.service.statusz`) and, when
    constructed with an ``ingest`` endpoint, the Arrow IPC ingestion
    frontend (``POST /ingest/v1/<tenant>/<dataset>``, see
    `deequ_tpu.ingest.endpoint`) — from a daemon thread. Binds to an
    ephemeral port by default (``port=0``); the bound port is on
    ``.port``."""

    def __init__(
        self,
        metrics: ServiceMetrics,
        host: str = "127.0.0.1",
        port: int = 0,
        ingest: Optional[Any] = None,
        statusz: Optional[Callable[[], Dict[str, Any]]] = None,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        plane = metrics
        ingest_endpoint = ingest
        statusz_fn = statusz

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if ingest_endpoint is None or not ingest_endpoint.matches(
                    self.path
                ):
                    self.send_error(404)
                    return
                from ..ingest.endpoint import render_response

                status, body_dict = ingest_endpoint.handle_post(
                    self.path, self.headers, self.rfile
                )
                body = render_response(status, body_dict)
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # a producer that died mid-stream cannot read its
                    # error; the fold report already landed on the
                    # counters and flight record
                    self.close_connection = True

            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                if self.path.startswith("/metrics.json"):
                    body = plane.json_text().encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    body = plane.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/statusz"):
                    if statusz_fn is None:
                        self.send_error(404)
                        return
                    body = json.dumps(statusz_fn(), sort_keys=True).encode()
                    ctype = "application/json"
                elif self.path.startswith("/trace.jsonl"):
                    from ..observability import export as _obs_export

                    body = _obs_export.spans_to_jsonl().encode()
                    ctype = "application/jsonl"
                elif self.path.startswith("/trace"):
                    from ..observability import export as _obs_export

                    body = _obs_export.chrome_trace_text().encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            # a producer that stops sending mid-body must not pin a
            # handler thread forever: the socket read times out and the
            # ingest path records a typed disconnect
            timeout = 30

            def log_message(self, *args):  # quiet: the plane IS the log
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="deequ-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
