"""xxHash64 (seed 42) — the hash the reference feeds its HLL++ registers
(reference `analyzers/catalyst/StatefulHyperloglogPlus.scala:89-115`, which
uses Spark's XxHash64 with seed 42).

The 8-byte fixed-width path (longs / doubles) is fully vectorized in numpy
uint64 modular arithmetic; variable-length strings go through the native C++
batch kernel when available (`deequ_tpu/native`) with a pure-Python scalar
fallback.
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

_P1 = np.uint64(11400714785074694791)
_P2 = np.uint64(14029467366897019727)
_P3 = np.uint64(1609587929392839161)
_P4 = np.uint64(9650029242287828579)
_P5 = np.uint64(2870177450012600261)

_MASK = (1 << 64) - 1
DEFAULT_SEED = 42


def _rotl(x: np.ndarray, r: int) -> np.ndarray:
    r = np.uint64(r)
    return (x << r) | (x >> (np.uint64(64) - r))


def xxhash64_u64(values: np.ndarray, seed=DEFAULT_SEED) -> np.ndarray:
    """Vectorized xxHash64 of 8-byte little-endian inputs (one u64 per row).
    ``seed`` may be a scalar or a per-row u64 array (broadcast)."""
    values = np.ascontiguousarray(values, dtype=np.uint64)
    with np.errstate(over="ignore"):
        h = np.asarray(seed, dtype=np.uint64) + _P5 + np.uint64(8)
        k = _rotl(values * _P2, 31) * _P1
        h = h ^ k
        h = _rotl(h, 27) * _P1 + _P4
        # avalanche
        h ^= h >> np.uint64(33)
        h *= _P2
        h ^= h >> np.uint64(29)
        h *= _P3
        h ^= h >> np.uint64(32)
    return h


def _rotl_i(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK


def xxhash64_bytes(data: bytes, seed: int = DEFAULT_SEED) -> int:
    """Scalar xxHash64 over arbitrary bytes (reference algorithm, public spec)."""
    p1, p2, p3, p4, p5 = (int(_P1), int(_P2), int(_P3), int(_P4), int(_P5))
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + p1 + p2) & _MASK
        v2 = (seed + p2) & _MASK
        v3 = seed & _MASK
        v4 = (seed - p1) & _MASK
        while i + 32 <= n:
            for vi in range(4):
                (lane,) = struct.unpack_from("<Q", data, i + 8 * vi)
                v = (v1, v2, v3, v4)[vi]
                v = (_rotl_i((v + lane * p2) & _MASK, 31) * p1) & _MASK
                if vi == 0:
                    v1 = v
                elif vi == 1:
                    v2 = v
                elif vi == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (_rotl_i(v1, 1) + _rotl_i(v2, 7) + _rotl_i(v3, 12) + _rotl_i(v4, 18)) & _MASK
        for v in (v1, v2, v3, v4):
            k = (_rotl_i((v * p2) & _MASK, 31) * p1) & _MASK
            h = ((h ^ k) * p1 + p4) & _MASK
    else:
        h = (seed + p5) & _MASK
    h = (h + n) & _MASK
    while i + 8 <= n:
        (lane,) = struct.unpack_from("<Q", data, i)
        k = (_rotl_i((lane * p2) & _MASK, 31) * p1) & _MASK
        h = ((_rotl_i(h ^ k, 27) * p1) + p4) & _MASK
        i += 8
    if i + 4 <= n:
        (lane,) = struct.unpack_from("<I", data, i)
        h = ((_rotl_i(h ^ ((lane * p1) & _MASK), 23) * p2) + p3) & _MASK
        i += 4
    while i < n:
        h = (_rotl_i(h ^ ((data[i] * p5) & _MASK), 11) * p1) & _MASK
        i += 1
    h ^= h >> 33
    h = (h * p2) & _MASK
    h ^= h >> 29
    h = (h * p3) & _MASK
    h ^= h >> 32
    return h


def as_object_array(values) -> np.ndarray:
    """Materialize a possibly-arrow string source into an object array —
    the SINGLE null-preserving arrow→object conversion shared by every
    pure-python string fallback (hashing here; classify/lengths in
    runners.features import it)."""
    if isinstance(values, np.ndarray):
        return values
    vals = values.to_numpy(zero_copy_only=False)
    return vals if vals.dtype == object else vals.astype(object)


def xxhash64_strings(values: np.ndarray, seed: int = DEFAULT_SEED) -> np.ndarray:
    """xxHash64 of a numpy object array of str/None. Nulls hash to the seed
    constant (they are masked out downstream anyway)."""
    from ..native import native_xxhash64_strings

    if native_xxhash64_strings is not None:
        return native_xxhash64_strings(values, seed)
    # arrow input (e.g. a lazily-kept dictionary payload): materialize to
    # python objects first — iterating the arrow array directly yields pa
    # scalars whose nulls fail the `v is None` check and stringify to
    # "None", hashing as that literal instead of the seed
    values = as_object_array(values)
    out = np.empty(len(values), dtype=np.uint64)
    for idx, v in enumerate(values):
        if v is None:
            out[idx] = seed
        else:
            out[idx] = xxhash64_bytes(str(v).encode("utf-8"), seed)
    return out


# ---------------------------------------------------------------------------
# Device-side (jnp) hashing — the frequency engine's group keys. Requires
# x64 mode (uint64 arrays); the runner gates the device frequency path on it.
# ---------------------------------------------------------------------------

#: the key value reserved for masked-out/null rows in the device frequency
#: engine: sorts AFTER every real key, so compactions and drains drop it
#: structurally. Real keys that land on it are counted exactly in the
#: state's ``sent_rows`` scalar instead.
FREQ_KEY_SENTINEL = 0xFFFFFFFFFFFFFFFF


def splitmix64_jnp(v):
    """SplitMix64 finalizer over a uint64 jnp array — a BIJECTIVE avalanche
    (Steele et al., the JDK SplittableRandom mixer). Integral/boolean
    grouping columns derive their device frequency keys through this ON
    DEVICE from the shared ``num`` feature (zero host hashing): a bijection
    has ZERO collisions, so the device frequency table's count multiset
    equals the host group-by's exactly, not just overwhelmingly-probably —
    and the avalanche spreads sequential ids uniformly, which keeps the
    host drain's radix partitions (native ``u64_value_counts``) balanced."""
    import jax.numpy as jnp

    v = v ^ (v >> jnp.uint64(30))
    v = v * jnp.uint64(0xBF58476D1CE4E5B9)
    v = v ^ (v >> jnp.uint64(27))
    v = v * jnp.uint64(0x94D049BB133111EB)
    v = v ^ (v >> jnp.uint64(31))
    return v


def splitmix64(v: np.ndarray) -> np.ndarray:
    """numpy twin of :func:`splitmix64_jnp` (bit-identical) — what parity
    tests and host-side key reconstruction fold integral columns through."""
    v = np.ascontiguousarray(v, dtype=np.uint64)
    with np.errstate(over="ignore"):
        v = v ^ (v >> np.uint64(30))
        v = v * np.uint64(0xBF58476D1CE4E5B9)
        v = v ^ (v >> np.uint64(27))
        v = v * np.uint64(0x94D049BB133111EB)
        v = v ^ (v >> np.uint64(31))
    return v


def xxhash64_u64_jnp(values, seed):
    """xxHash64 of 8-byte inputs as jnp uint64 ops — bit-identical to the
    numpy :func:`xxhash64_u64` (same constants, same rotations). ``seed``
    may be a scalar or a per-row uint64 array, which is how multi-column
    grouping sets chain their combined key: Spark's XxHash64 feeds each
    column's hash as the next column's seed
    (`catalyst/expressions/hash.scala`), and the device engine mirrors
    that so a combined key depends on every column and on column order."""
    import jax.numpy as jnp

    u = lambda x: jnp.uint64(x)  # noqa: E731
    h = seed + u(_P5) + u(8)
    k = values * u(_P2)
    k = (k << u(31)) | (k >> u(33))
    k = k * u(_P1)
    h = h ^ k
    h = ((h << u(27)) | (h >> u(37))) * u(_P1) + u(_P4)
    h = h ^ (h >> u(33))
    h = h * u(_P2)
    h = h ^ (h >> u(29))
    h = h * u(_P3)
    h = h ^ (h >> u(32))
    return h


def hash_column(values: np.ndarray, mask: np.ndarray, kind, seed: int = DEFAULT_SEED) -> np.ndarray:
    """Hash a column to u64, matching Spark's per-type byte layout:
    integrals as int64 LE, fractionals as IEEE754 double bits (with -0.0
    normalized to 0.0), booleans as int64 0/1, strings as UTF-8 bytes."""
    from ..data import ColumnKind

    if kind == ColumnKind.STRING:
        return xxhash64_strings(values, seed)
    if kind == ColumnKind.BOOLEAN:
        as_u64 = values.astype(np.int64).view(np.uint64)
        return xxhash64_u64(as_u64, seed)
    if kind == ColumnKind.INTEGRAL:
        return xxhash64_u64(values.astype(np.int64).view(np.uint64), seed)
    # fractional: double bits, normalize -0.0 and NaN. Java's
    # Double.doubleToLongBits (what Spark's XxHash64 hashes) collapses
    # every NaN payload to the canonical quiet NaN, and pandas' groupby
    # keys all NaNs as one group — so the device frequency engine's hashed
    # keys agree with the host group-by on NaN-valued rows too.
    vals = values.astype(np.float64, copy=True)
    vals[vals == 0.0] = 0.0  # -0.0 -> 0.0
    vals[np.isnan(vals)] = np.nan
    vals[~mask] = 0.0
    return xxhash64_u64(vals.view(np.uint64), seed)
