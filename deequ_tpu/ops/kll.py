"""Batched KLL quantile sketch as a fixed-shape TPU kernel.

The reference's KLL (`analyzers/QuantileNonSample.scala:25-305`) is a per-row
imperative update: append one item to a dynamically-sized level-0 buffer and
run a compaction cascade when full — hostile to SIMD and to XLA's static
shapes. This redesign keeps the KLL *algebra* (levelled compactors, every-2nd
subsampling with alternating offsets, weight doubling per level) but makes
every step a fixed-shape vector op:

- the level buffers are one ``float64[L, 4k]`` array padded with ``+inf``
  plus an ``int32[L]`` size vector — jit-able, donate-able, mergeable
  (4k is the fixed point of the worst-case occupancy recurrence
  ``M = 2k + M/2``: a merge appends up to 2k before the cascade runs, and a
  compaction of a 4k-full level promotes at most 2k upward);
- a whole batch is folded at once: sort the batch, stride-subsample it down
  to ≤ k items of weight ``2^h`` (equivalent to ``h`` perfect pairwise
  compactions in one step), and scatter-append at level ``h``;
- the compaction cascade is an unrolled loop over levels with masked
  ``where`` selects instead of data-dependent control flow.

Levels use uniform capacity ``k`` (the reference shrinks lower-level
capacities by ``shrinkingFactor``, `QuantileNonSample.scala:78-80`; uniform
capacity strictly dominates it in rank error at a modest constant-factor
space cost, and keeps one static shape). ``shrinking_factor`` is retained in
the API and serde for compatibility.

Rank-error behaviour is validated probabilistically in
``tests/test_kll.py`` (the `KLL/KLLProbTest.scala` analog).
"""

from __future__ import annotations

from typing import Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ..config import ACC_DTYPE, COUNT_DTYPE

#: sketch items are f32: quantile VALUE precision (1e-7 relative) is orders
#: of magnitude finer than the sketch's RANK error, and f32 sorts run on the
#: TPU's native path instead of emulated f64. min/max/count stay ACC/COUNT
#: dtype for exact parity.
#:
#: DOCUMENTED CAVEAT (persistence): the reference persists KLL items as
#: doubles (`analyzers/catalyst/KLLSketchSerializer.scala:26-121`); here the
#: state's item buffers are f32 on device and persist as f32, so a persisted
#: sketch's item VALUES can differ from a double-precision payload by up to
#: 1 ulp of f32 (~1.2e-7 relative). Round-trips through the state providers
#: are bit-exact with respect to the sketch's own contents (asserted in
#: tests/test_state_serde.py); the f32 quantisation happens once, at update
#: time, and is far inside the sketch's rank-error envelope. g_min/g_max and
#: the exact count persist at full f64/i64 precision.
ITEM_DTYPE = jnp.float32

#: defaults matching the reference (`analyzers/KLLSketch.scala:172-176`)
DEFAULT_SKETCH_SIZE = 2048
DEFAULT_SHRINKING_FACTOR = 0.64
MAXIMUM_ALLOWED_DETAIL_BINS = 100

#: number of levels: level l holds items of weight 2^l, so 32 levels cover
#: k * 2^31 ~ 4e12 rows at the default sketch size before the top level can
#: saturate
MAX_LEVELS = 32

_INF = jnp.inf


@flax.struct.dataclass
class KLLSketchState:
    """Mergeable sketch state (+ global min/max + exact count), the analog of
    the reference `KLLState` (`analyzers/KLLSketch.scala:42-55`)."""

    items: jnp.ndarray   # float64[L, 4k], +inf beyond sizes[l]
    sizes: jnp.ndarray   # int32[L]
    parity: jnp.ndarray  # int32[L], alternating compaction offsets
    ticks: jnp.ndarray   # int32, update counter (drives subsample offsets)
    count: jnp.ndarray   # int64, exact number of folded values
    g_min: jnp.ndarray   # float64
    g_max: jnp.ndarray   # float64

    sketch_size: int = flax.struct.field(pytree_node=False, default=DEFAULT_SKETCH_SIZE)

    @property
    def capacity(self) -> int:
        return self.sketch_size

    def merge(self, other: "KLLSketchState") -> "KLLSketchState":
        """Semigroup merge (delegates to :func:`kll_merge`): every *State
        class exposes the algebra uniformly so generic fold/merge paths —
        and the state-algebra invariant check — can rely on it."""
        return kll_merge(self, other)


def kll_init(sketch_size: int = DEFAULT_SKETCH_SIZE, levels: int = MAX_LEVELS) -> KLLSketchState:
    k = int(sketch_size)
    return KLLSketchState(
        items=jnp.full((levels, 4 * k), _INF, dtype=ITEM_DTYPE),
        sizes=jnp.zeros(levels, dtype=jnp.int32),
        parity=jnp.zeros(levels, dtype=jnp.int32),
        ticks=jnp.zeros((), dtype=jnp.int32),
        count=jnp.zeros((), dtype=COUNT_DTYPE),
        g_min=jnp.asarray(jnp.inf, dtype=ACC_DTYPE),
        g_max=jnp.asarray(-jnp.inf, dtype=ACC_DTYPE),
        sketch_size=k,
    )


def _append_level(
    items: jnp.ndarray, sizes: jnp.ndarray, level, values: jnp.ndarray, num_valid
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Append the valid prefix of ``values`` to ``items[level]``. Writes past
    capacity drop AND are excluded from the size accounting, so a saturated
    top level (only reachable past ~1e13 rows) loses weight instead of
    corrupting the buffer with counted padding.

    Implemented as a GATHER-based row rebuild + one dynamic row update: a
    TPU scatter of even 2k elements lowers to a serialized loop measured at
    ~9ms per call — the single hottest op in the old KLL update — while the
    equivalent ``values[j - size]`` gather + full-row select runs in the
    same fused elementwise pass as everything else."""
    buf_len = items.shape[1]
    level = jnp.asarray(level, jnp.int32)
    size = sizes[level]
    written = jnp.clip(num_valid.astype(jnp.int32), 0, buf_len - size)
    row = items[level]
    # shifted[j] = values[j - size]: a dynamic_slice of the padded values
    # (contiguous window), not a gather — indices are consecutive. INF pads
    # BOTH sides so the start index (buf_len - size, in [0, buf_len]) is
    # never clamped regardless of len(values); out-of-range reads yield INF
    # and are masked by ``take`` anyway.
    pad = jnp.full(buf_len, _INF, values.dtype)
    padded = jnp.concatenate([pad, values, pad])
    shifted = jax.lax.dynamic_slice(padded, (buf_len - size,), (buf_len,))
    rel = jnp.arange(buf_len, dtype=jnp.int32) - size
    take = (rel >= 0) & (rel < written)
    new_row = jnp.where(take, shifted, row)
    items = jax.lax.dynamic_update_slice(
        items, new_row[None, :], (level, jnp.zeros((), jnp.int32))
    )
    sizes = sizes.at[level].add(written)
    return items, sizes


def _make_compact_level(shape: Tuple[int, int]):
    """The single-level compactor: sort the level, promote every-2nd item of
    its even-length prefix to the next level with doubled weight, keep the
    odd tail (the batched analog of the reference compactor,
    `analyzers/NonSampleCompactor.scala:29-69`). Untouched levels keep their
    insertion order; every consumer (compaction itself, HostKLL,
    compactor_buffers) sorts, so only the multiset per level matters."""
    levels, buf_len = shape
    half = buf_len // 2  # max items a compaction can emit
    slots = jnp.arange(half, dtype=jnp.int32)
    buf_slots = jnp.arange(buf_len, dtype=jnp.int32)

    def compact_level(lvl, carry):
        items, sizes, parity = carry
        n = sizes[lvl]
        buf = jnp.sort(items[lvl])
        n2 = n - (n & 1)
        m_emit = n2 // 2
        off = parity[lvl]
        # promoted items: buf[off + 2j] for j < m_emit (a sorted prefix)
        emit_idx = jnp.clip(off + 2 * slots, 0, buf_len - 1)
        emitted = jnp.where(slots < m_emit, buf[emit_idx], _INF)
        # tail kept at this level: buf[n2:n] (0 or 1 items)
        tail_count = n - n2
        tail_idx = jnp.clip(n2 + buf_slots, 0, buf_len - 1)
        new_row = jnp.where(buf_slots < tail_count, buf[tail_idx], _INF)
        items = items.at[lvl].set(new_row)
        sizes = sizes.at[lvl].set(tail_count.astype(jnp.int32))
        parity = parity.at[lvl].set(1 - off)
        items, sizes = _append_level(items, sizes, lvl + 1, emitted, m_emit)
        return items, sizes, parity

    return compact_level


def _compact_cascade(items: jnp.ndarray, sizes: jnp.ndarray, parity: jnp.ndarray, k: int):
    """Full upward sweep over every level — needed after a MERGE, where all
    levels receive appends. Each level is wrapped in a ``lax.cond`` so
    levels within capacity skip their sort."""
    levels, _ = items.shape
    compact_level = _make_compact_level(items.shape)

    def body(lvl, carry):
        _items, _sizes, _parity = carry
        return jax.lax.cond(
            _sizes[lvl] > k,
            lambda c: compact_level(lvl, c),
            lambda c: c,
            carry,
        )

    # one compiled level-step instead of L-1 unrolled copies; a single
    # upward sweep suffices because level l+1 is processed after receiving
    # level l's promotions
    return jax.lax.fori_loop(0, levels - 1, body, (items, sizes, parity))


def _compact_cascade_from(
    items: jnp.ndarray, sizes: jnp.ndarray, parity: jnp.ndarray, k: int, start_level
):
    """Early-terminating cascade for a SINGLE-LEVEL append (batch update /
    sampled ingest): only ``start_level`` can overflow, each compaction can
    only overflow the level above, and the cascade dies the moment a level
    fits — so a ``while_loop`` starting at ``start_level`` visits the one or
    two levels that actually changed instead of sweeping all ~32 (measured
    ~3x faster per 1M-row fold than the full sweep on TPU; the sweep's 31
    ``cond``s each carry the 1MB item buffer through an iteration even when
    they skip)."""
    levels, _ = items.shape
    compact_level = _make_compact_level(items.shape)

    def cond(carry):
        _items, _sizes, _parity, lvl = carry
        return (lvl < levels - 1) & (_sizes[lvl] > k)

    def body(carry):
        _items, _sizes, _parity, lvl = carry
        _items, _sizes, _parity = compact_level(lvl, (_items, _sizes, _parity))
        return _items, _sizes, _parity, lvl + 1

    items, sizes, parity, _ = jax.lax.while_loop(
        cond, body, (items, sizes, parity, jnp.asarray(start_level, jnp.int32))
    )
    return items, sizes, parity


def kll_update(state: KLLSketchState, values: jnp.ndarray, valid: jnp.ndarray) -> KLLSketchState:
    """Fold one batch (fixed shape, masked) into the sketch. Pure jax; safe
    under jit/shard_map. NaNs are excluded from the sketch."""
    k = state.sketch_size
    v = values.astype(ACC_DTYPE)
    ok = valid & ~jnp.isnan(v)
    n = jnp.sum(ok).astype(jnp.int32)

    count = state.count + n.astype(COUNT_DTYPE)
    g_min = jnp.minimum(state.g_min, jnp.min(jnp.where(ok, v, jnp.inf)))
    g_max = jnp.maximum(state.g_max, jnp.max(jnp.where(ok, v, -jnp.inf)))

    # clamp to the finite ITEM_DTYPE range before the cast: a legitimate
    # |value| > 3.4e38 must saturate, not become inf and collide with the
    # padding sentinel (quantiles at such magnitudes saturate; min/max/count
    # stay exact in ACC_DTYPE)
    finfo_max = jnp.asarray(jnp.finfo(ITEM_DTYPE).max, dtype=v.dtype)
    clamped = jnp.clip(v, -finfo_max, finfo_max)
    sv = jnp.sort(jnp.where(ok, clamped, _INF).astype(ITEM_DTYPE))

    # pre-collapse the batch: stride 2^h subsampling of the sorted batch is
    # equivalent to h perfect pairwise compactions, landing ≤ k items of
    # weight 2^h directly at level h
    m_needed = jnp.maximum((n + k - 1) // k, 1)
    h = jnp.ceil(jnp.log2(m_needed.astype(jnp.float32))).astype(jnp.int32)
    stride = (1 << h).astype(jnp.int32)
    # cheap deterministic rotation of the subsample offset across updates
    r = (state.ticks.astype(jnp.uint32) * jnp.uint32(2654435761)) >> jnp.uint32(7)
    offset = (r % stride.astype(jnp.uint32)).astype(jnp.int32)

    slots = jnp.arange(k, dtype=jnp.int32)
    pos = offset + slots * stride
    sample_valid = pos < n
    samples = jnp.where(sample_valid, sv[jnp.clip(pos, 0, sv.shape[0] - 1)], _INF)
    m = jnp.sum(sample_valid).astype(jnp.int32)

    items, sizes = _append_level(state.items, state.sizes, h, samples, m)
    items, sizes, parity = _compact_cascade_from(items, sizes, state.parity, k, h)

    return KLLSketchState(
        items=items,
        sizes=sizes,
        parity=parity,
        ticks=state.ticks + 1,
        count=count,
        g_min=g_min,
        g_max=g_max,
        sketch_size=k,
    )


def kll_ingest_sampled(
    state: KLLSketchState,
    samples: jnp.ndarray,
    m: jnp.ndarray,
    h: jnp.ndarray,
    nv: jnp.ndarray,
    g_min: jnp.ndarray,
    g_max: jnp.ndarray,
) -> KLLSketchState:
    """Fold a host-side pre-sampled block into the sketch: ``samples`` is a
    sorted, +inf-padded (<=4k,) vector of ``m`` items carrying weight
    ``2^h`` each, covering ``nv`` underlying values with the given block
    min/max (the native ingest tier's `block_kll_sample` output — the
    bottom-sampler form of kll_update's batch pre-collapse, sampled up to
    two levels denser than strictly fits so compaction absorbs the surplus).
    Pure jax; runs inside the jit'd partial-fold program."""
    k = state.sketch_size
    # clamp like kll_update: legitimate huge/-inf values saturate to the
    # finite ITEM range (a -inf must stay minimum-side). Padding beyond the
    # first m slots never enters the sketch (_append_level writes m items),
    # so the +inf padding needs no special casing.
    finfo_max = jnp.asarray(jnp.finfo(ITEM_DTYPE).max, dtype=jnp.float64)
    sv = jnp.clip(
        jnp.asarray(samples, dtype=jnp.float64), -finfo_max, finfo_max
    ).astype(ITEM_DTYPE)

    items, sizes = _append_level(
        state.items, state.sizes, jnp.asarray(h, dtype=jnp.int32), sv,
        jnp.asarray(m, dtype=jnp.int32),
    )
    items, sizes, parity = _compact_cascade_from(items, sizes, state.parity, k, h)
    return KLLSketchState(
        items=items,
        sizes=sizes,
        parity=parity,
        ticks=state.ticks + 1,
        count=state.count + jnp.asarray(nv, dtype=COUNT_DTYPE),
        g_min=jnp.minimum(state.g_min, jnp.asarray(g_min, dtype=ACC_DTYPE)),
        g_max=jnp.maximum(state.g_max, jnp.asarray(g_max, dtype=ACC_DTYPE)),
        sketch_size=k,
    )


def kll_merge(a: KLLSketchState, b: KLLSketchState) -> KLLSketchState:
    """Semigroup sum: concatenate per-level buffers and re-compact
    (reference `QuantileNonSample.merge`, `analyzers/QuantileNonSample.scala:
    215-230`). Pure jax, usable inside collective tree merges."""
    assert a.sketch_size == b.sketch_size, "cannot merge sketches of different size"
    # persisted states come back as numpy pytrees; coerce for .at[] scatters
    items, sizes = jnp.asarray(a.items), jnp.asarray(a.sizes)
    for lvl in range(items.shape[0]):
        items, sizes = _append_level(items, sizes, lvl, b.items[lvl], b.sizes[lvl])
    items, sizes, parity = _compact_cascade(
        items, sizes, jnp.asarray(a.parity) ^ jnp.asarray(b.parity), a.sketch_size
    )
    return KLLSketchState(
        items=items,
        sizes=sizes,
        parity=parity,
        ticks=a.ticks + b.ticks,
        count=a.count + b.count,
        g_min=jnp.minimum(a.g_min, b.g_min),
        g_max=jnp.maximum(a.g_max, b.g_max),
        sketch_size=a.sketch_size,
    )


# ---------------------------------------------------------------------------
# host-side views
# ---------------------------------------------------------------------------


def compactor_buffers(state: KLLSketchState) -> list:
    """Per-level item lists (weights 2^level) — the `getCompactorItems`
    payload stored in BucketDistribution.data (reference
    `analyzers/KLLSketch.scala:150`)."""
    items = np.asarray(state.items)
    sizes = np.asarray(state.sizes)
    out = []
    top = 0
    for lvl in range(items.shape[0]):
        if sizes[lvl] > 0:
            top = lvl + 1
    for lvl in range(max(top, 1)):
        out.append(sorted(items[lvl][: sizes[lvl]].tolist()))
    return out
