"""Host-side KLL sketch queries (rank / quantile / CDF).

Operates on the materialized per-level compactor buffers, either straight
from a device :class:`~deequ_tpu.ops.kll.KLLSketchState` or re-materialized
from a persisted ``BucketDistribution.data`` payload (the reference's
`reconstruct` path, `analyzers/QuantileNonSample.scala:46-60`, used by
`metrics/KLLMetric.scala:24-40`).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


class HostKLL:
    """Weighted-sample view of a KLL sketch: items ``x_i`` with weights
    ``2^level``, answering rank and quantile queries
    (reference `analyzers/QuantileNonSample.scala:126-278`)."""

    def __init__(self, values: np.ndarray, weights: np.ndarray, sketch_size: int,
                 shrinking_factor: float):
        order = np.argsort(values, kind="stable")
        self.values = np.asarray(values, dtype=np.float64)[order]
        self.weights = np.asarray(weights, dtype=np.int64)[order]
        self.cum_weights = np.cumsum(self.weights)
        self.total_weight = int(self.cum_weights[-1]) if len(self.cum_weights) else 0
        self.sketch_size = sketch_size
        self.shrinking_factor = shrinking_factor

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_buffers(
        buffers: Sequence[Sequence[float]], sketch_size: int, shrinking_factor: float
    ) -> "HostKLL":
        values: List[float] = []
        weights: List[int] = []
        for level, buf in enumerate(buffers):
            w = 1 << level
            for x in buf:
                values.append(float(x))
                weights.append(w)
        return HostKLL(
            np.asarray(values, dtype=np.float64),
            np.asarray(weights, dtype=np.int64),
            sketch_size,
            shrinking_factor,
        )

    @staticmethod
    def from_state(state) -> "HostKLL":
        """From a device KLLSketchState (no copy of the padding)."""
        items = np.asarray(state.items)
        sizes = np.asarray(state.sizes)
        values: List[np.ndarray] = []
        weights: List[np.ndarray] = []
        for lvl in range(items.shape[0]):
            n = int(sizes[lvl])
            if n == 0:
                continue
            values.append(items[lvl][:n])
            weights.append(np.full(n, 1 << lvl, dtype=np.int64))
        if not values:
            return HostKLL(
                np.empty(0, dtype=np.float64), np.empty(0, dtype=np.int64),
                state.sketch_size, 0.0,
            )
        return HostKLL(
            np.concatenate(values), np.concatenate(weights), state.sketch_size, 0.0
        )

    # -- queries ------------------------------------------------------------

    @property
    def is_empty(self) -> bool:
        return self.total_weight == 0

    def rank(self, x: float) -> int:
        """Weighted count of items <= x (reference `getRank`)."""
        idx = np.searchsorted(self.values, x, side="right")
        return int(self.cum_weights[idx - 1]) if idx > 0 else 0

    def rank_exclusive(self, x: float) -> int:
        """Weighted count of items < x (reference `getRankExclusive`)."""
        idx = np.searchsorted(self.values, x, side="left")
        return int(self.cum_weights[idx - 1]) if idx > 0 else 0

    def quantile(self, q: float) -> float:
        """Smallest item whose cumulative weight reaches q * totalWeight."""
        if self.is_empty:
            return float("nan")
        q = min(max(q, 0.0), 1.0)
        target = q * self.total_weight
        idx = np.searchsorted(self.cum_weights, target, side="left")
        idx = min(idx, len(self.values) - 1)
        return float(self.values[idx])

    def quantiles(self, qs: Sequence[float]) -> List[float]:
        return [self.quantile(q) for q in qs]

    def cdf(self, xs: Sequence[float]) -> np.ndarray:
        """P[X <= x] estimates for each x."""
        if self.is_empty:
            return np.full(len(xs), np.nan)
        idx = np.searchsorted(self.values, np.asarray(xs, dtype=np.float64), side="right")
        cw = np.concatenate([[0], self.cum_weights])
        return cw[idx] / self.total_weight
