"""Device kernels (HLL, KLL, hashing) and shared TPU op scaffolding."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_key_fold(keys, pad_value, init, fold_chunk, chunk: int = 4096):
    """Fold a 1-D key array through ``fold_chunk`` in fixed-size chunks via
    ``lax.scan``: the per-chunk broadcast tile (e.g. a ``(chunk, K)``
    compare against category/register ids) stays in VMEM instead of
    materializing a ``(rows, K)`` intermediate — the pattern both the HLL
    register max and the device frequency count use, and the reason neither
    needs a TPU scatter (which lowers to a serialized loop) or a sort.

    ``keys`` is padded to a chunk multiple with ``pad_value``; callers pick
    a sentinel their fold ignores. ``fold_chunk(acc, row) -> acc`` folds one
    ``(chunk,)`` slice.
    """
    if keys.shape[0] == 0:
        return init
    c = min(chunk, keys.shape[0])
    pad = (-keys.shape[0]) % c
    if pad:
        keys = jnp.concatenate([keys, jnp.full(pad, pad_value, keys.dtype)])
    acc, _ = jax.lax.scan(
        lambda a, row: (fold_chunk(a, row), None), init, keys.reshape(-1, c)
    )
    return acc
