"""Device kernels (HLL, KLL, hashing) and shared TPU op scaffolding."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_key_fold(keys, pad_value, init, fold_chunk, chunk: int = 4096):
    """Fold a 1-D key array through ``fold_chunk`` in fixed-size chunks via
    ``lax.scan``: the per-chunk broadcast tile (e.g. a ``(chunk, K)``
    compare against category/register ids) stays in VMEM instead of
    materializing a ``(rows, K)`` intermediate — the pattern both the HLL
    register max and the device frequency count use, and the reason neither
    needs a TPU scatter (which lowers to a serialized loop) or a sort.

    ``keys`` is padded to a chunk multiple with ``pad_value``; callers pick
    a sentinel their fold ignores. ``fold_chunk(acc, row) -> acc`` folds one
    ``(chunk,)`` slice.
    """
    if keys.shape[0] == 0:
        return init
    c = min(chunk, keys.shape[0])
    pad = (-keys.shape[0]) % c
    if pad:
        keys = jnp.concatenate([keys, jnp.full(pad, pad_value, keys.dtype)])
    acc, _ = jax.lax.scan(
        lambda a, row: (fold_chunk(a, row), None), init, keys.reshape(-1, c)
    )
    return acc


def freq_compact(keys, counts, out_size: int, sentinel):
    """Sort-merge compaction of (key, count) pairs into at most ``out_size``
    sorted uniques — the device frequency engine's table maintenance, shared
    by the in-pass buffer compaction and the semigroup state merge so the
    two cannot drift.

    Scatter-free by construction (XLA scatters serialize on TPU, see
    DeviceFrequencyScan.update): one pair-sort brings equal keys adjacent,
    a cumsum over the sorted counts turns segment sums into two gathers,
    and the compaction gather indices come from searchsorted over the
    running unique rank — every step is a sort, scan or gather the TPU
    vectorizes. Entries with ``key == sentinel`` (masked rows, structural
    padding) contribute nothing and sort last.

    Returns ``(out_keys, out_counts, n_unique, kept_rows, total_rows)``:
    ``out_size`` sorted unique keys (sentinel-padded past ``n_unique``)
    with summed counts. ``n_unique`` is the RAW distinct count of the
    input, which may exceed ``out_size``: the smallest ``out_size`` uniques
    are kept, the rest are dropped, and the caller accounts
    ``max(n_unique - out_size, 0)`` groups / ``total_rows - kept_rows``
    rows as lost (the overflow tier's exact loss ledger).
    """
    import jax.numpy as jnp

    k, c = jax.lax.sort((keys, counts), num_keys=1)
    n = k.shape[0]
    # caller contract: sentinel-keyed entries carry count 0 and real keys
    # carry counts >= 1, so segment sums need no per-entry validity test
    is_start = jnp.concatenate(
        [jnp.ones(1, dtype=bool), k[1:] != k[:-1]]
    ) & (k != sentinel)
    ranks = jnp.cumsum(is_start.astype(jnp.int64))
    n_unique = ranks[-1]
    tot = jnp.cumsum(c)
    target = jnp.arange(1, out_size + 1, dtype=jnp.int64)
    pos = jnp.clip(jnp.searchsorted(ranks, target, side="left"), 0, n - 1)
    pos_next = jnp.searchsorted(ranks, target + 1, side="left")
    valid = target <= n_unique
    out_keys = jnp.where(valid, k[pos], sentinel)
    seg_end = tot[jnp.clip(pos_next - 1, 0, n - 1)]
    seg_end = jnp.where(pos_next >= n, tot[n - 1], seg_end)
    seg_begin = jnp.where(pos > 0, tot[pos - 1], 0)
    out_counts = jnp.where(valid, seg_end - seg_begin, 0)
    total_rows = tot[n - 1]
    kept_rows = jnp.sum(out_counts)
    return out_keys, out_counts, n_unique, kept_rows, total_rows
