"""HyperLogLog++ cardinality sketch (p=9, 512 registers), TPU-native.

The reference implements HLL++ as a Spark ImperativeAggregate doing per-row
register updates on a packed 52-long agg buffer (reference
`analyzers/catalyst/StatefulHyperloglogPlus.scala:89-139`, constants
`analyzers/catalyst/HLLConstants.scala:25-37`). Here the per-row work is
vectorized: the host turns xxhash64 values into (register-index,
leading-zero-count) pairs in one numpy pass, the device folds a whole batch
into the 512-register state with a chunked one-hot compare/max scan
(scatter-free — see ``ApproxCountDistinct.update``), and merge is an
elementwise register max — psum-compatible over a mesh axis
(``jax.lax.pmax``).

Register layout is kept unpacked (``int32[512]``) on device for vector
friendliness; :func:`registers_to_words` / :func:`words_to_registers` convert
to/from the reference's packed 6-bit/52-word format for state persistence
parity (reference `StatefulHyperloglogPlus.scala:170-186`).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: HLL++ precision: relativeSD = 0.05 => p = ceil(2*log2(1.106/0.05)) = 9
#: (reference `StatefulHyperloglogPlus.scala:154-161`)
RELATIVE_SD = 0.05
P = 9
M = 1 << P  # 512 registers
IDX_SHIFT = 64 - P
W_PADDING = np.uint64(1 << (P - 1))
REGISTER_SIZE = 6
REGISTERS_PER_WORD = 64 // REGISTER_SIZE  # 10
NUM_WORDS = (M + REGISTERS_PER_WORD - 1) // REGISTERS_PER_WORD  # 52
REGISTER_WORD_MASK = np.uint64((1 << REGISTER_SIZE) - 1)

#: alpha * m^2 for p >= 7 (HLL++ paper; reference `StatefulHyperloglogPlus.scala:163-168`)
ALPHA_M2 = (0.7213 / (1.0 + 1.079 / M)) * M * M

#: nearest-neighbour count used in bias interpolation
#: (reference `HLLConstants.scala:35`)
K_NEIGHBORS = 6

#: linear-counting threshold for p=9 (reference `HLLConstants.scala:37`, entry P-4)
THRESHOLD = 400.0

# Empirical bias-correction data for p=9 from the HLL++ paper's published
# appendix (Heule et al. 2013); same values the reference carries in
# `HLLConstants.scala:39-105` (row P-4). RAW_ESTIMATES are the sorted raw
# estimate anchors, BIASES the measured bias at each anchor.
RAW_ESTIMATES_P9 = np.array([
    369, 374.8294, 381.2452, 387.6698, 394.1464, 400.2024, 406.8782, 413.6598,
    420.462, 427.2826, 433.7102, 440.7416, 447.9366, 455.1046, 462.285,
    469.0668, 476.306, 483.8448, 491.301, 498.9886, 506.2422, 513.8138,
    521.7074, 529.7428, 537.8402, 545.1664, 553.3534, 561.594, 569.6886,
    577.7876, 585.65, 594.228, 602.8036, 611.1666, 620.0818, 628.0824,
    637.2574, 646.302, 655.1644, 664.0056, 672.3802, 681.7192, 690.5234,
    700.2084, 708.831, 718.485, 728.1112, 737.4764, 746.76, 756.3368,
    766.5538, 775.5058, 785.2646, 795.5902, 804.3818, 814.8998, 824.9532,
    835.2062, 845.2798, 854.4728, 864.9582, 875.3292, 886.171, 896.781,
    906.5716, 916.7048, 927.5322, 937.875, 949.3972, 958.3464, 969.7274,
    980.2834, 992.1444, 1003.4264, 1013.0166, 1024.018, 1035.0438, 1046.34,
    1057.6856, 1068.9836, 1079.0312, 1091.677, 1102.3188, 1113.4846,
    1124.4424, 1135.739, 1147.1488, 1158.9202, 1169.406, 1181.5342,
    1193.2834, 1203.8954, 1216.3286, 1226.2146, 1239.6684, 1251.9946,
    1262.123, 1275.4338, 1285.7378, 1296.076, 1308.9692, 1320.4964,
    1333.0998, 1343.9864, 1357.7754, 1368.3208, 1380.4838, 1392.7388,
    1406.0758, 1416.9098, 1428.9728, 1440.9228, 1453.9292, 1462.617, 1476.05,
    1490.2996, 1500.6128, 1513.7392, 1524.5174, 1536.6322, 1548.2584,
    1562.3766, 1572.423, 1587.1232, 1596.5164, 1610.5938, 1622.5972,
    1633.1222, 1647.7674, 1658.5044, 1671.57, 1683.7044, 1695.4142,
    1708.7102, 1720.6094, 1732.6522, 1747.841, 1756.4072, 1769.9786,
    1782.3276, 1797.5216, 1808.3186, 1819.0694, 1834.354, 1844.575,
    1856.2808, 1871.1288, 1880.7852, 1893.9622, 1906.3418, 1920.6548,
    1932.9302, 1945.8584, 1955.473, 1968.8248, 1980.6446, 1995.9598,
    2008.349, 2019.8556, 2033.0334, 2044.0206, 2059.3956, 2069.9174,
    2082.6084, 2093.7036, 2106.6108, 2118.9124, 2132.301, 2144.7628,
    2159.8422, 2171.0212, 2183.101, 2193.5112, 2208.052, 2221.3194,
    2233.3282, 2247.295, 2257.7222, 2273.342, 2286.5638, 2299.6786,
    2310.8114, 2322.3312, 2335.516, 2349.874, 2363.5968, 2373.865, 2387.1918,
    2401.8328, 2414.8496, 2424.544, 2436.7592, 2447.1682, 2464.1958,
    2474.3438, 2489.0006, 2497.4526, 2513.6586, 2527.19, 2540.7028, 2553.768,
])

BIASES_P9 = np.array([
    368, 361.8294, 355.2452, 348.6698, 342.1464, 336.2024, 329.8782,
    323.6598, 317.462, 311.2826, 305.7102, 299.7416, 293.9366, 288.1046,
    282.285, 277.0668, 271.306, 265.8448, 260.301, 254.9886, 250.2422,
    244.8138, 239.7074, 234.7428, 229.8402, 225.1664, 220.3534, 215.594,
    210.6886, 205.7876, 201.65, 197.228, 192.8036, 188.1666, 184.0818,
    180.0824, 176.2574, 172.302, 168.1644, 164.0056, 160.3802, 156.7192,
    152.5234, 149.2084, 145.831, 142.485, 139.1112, 135.4764, 131.76,
    129.3368, 126.5538, 122.5058, 119.2646, 116.5902, 113.3818, 110.8998,
    107.9532, 105.2062, 102.2798, 99.4728, 96.9582, 94.3292, 92.171,
    89.7809999999999, 87.5716, 84.7048, 82.5322, 79.875, 78.3972, 75.3464,
    73.7274, 71.2834, 70.1444, 68.4263999999999, 66.0166, 64.018,
    62.0437999999999, 60.3399999999999, 58.6856, 57.9836, 55.0311999999999,
    54.6769999999999, 52.3188, 51.4846, 49.4423999999999, 47.739,
    46.1487999999999, 44.9202, 43.4059999999999, 42.5342000000001, 41.2834,
    38.8954000000001, 38.3286000000001, 36.2146, 36.6684, 35.9946, 33.123,
    33.4338, 31.7378000000001, 29.076, 28.9692, 27.4964, 27.0998, 25.9864,
    26.7754, 24.3208, 23.4838, 22.7388000000001, 24.0758000000001,
    21.9097999999999, 20.9728, 19.9228000000001, 19.9292, 16.617, 17.05,
    18.2996000000001, 15.6128000000001, 15.7392, 14.5174, 13.6322,
    12.2583999999999, 13.3766000000001, 11.423, 13.1232, 9.51639999999998,
    10.5938000000001, 9.59719999999993, 8.12220000000002, 9.76739999999995,
    7.50440000000003, 7.56999999999994, 6.70440000000008, 6.41419999999994,
    6.71019999999999, 5.60940000000005, 4.65219999999999, 6.84099999999989,
    3.4072000000001, 3.97859999999991, 3.32760000000007, 5.52160000000003,
    3.31860000000006, 2.06940000000009, 4.35400000000004, 1.57500000000005,
    0.280799999999999, 2.12879999999996, -0.214799999999968,
    -0.0378000000000611, -0.658200000000079, 0.654800000000023,
    -0.0697999999999865, 0.858400000000074, -2.52700000000004,
    -2.1751999999999, -3.35539999999992, -1.04019999999991,
    -0.651000000000067, -2.14439999999991, -1.96659999999997,
    -3.97939999999994, -0.604400000000169, -3.08260000000018,
    -3.39159999999993, -5.29640000000018, -5.38920000000007,
    -5.08759999999984, -4.69900000000007, -5.23720000000003,
    -3.15779999999995, -4.97879999999986, -4.89899999999989,
    -7.48880000000008, -5.94799999999987, -5.68060000000014,
    -6.67180000000008, -4.70499999999993, -7.27779999999984,
    -4.6579999999999, -4.4362000000001, -4.32139999999981,
    -5.18859999999995, -6.66879999999992, -6.48399999999992,
    -5.1260000000002, -4.4032000000002, -6.13500000000022,
    -5.80819999999994, -4.16719999999987, -4.15039999999999,
    -7.45600000000013, -7.24080000000004, -9.83179999999993,
    -5.80420000000004, -8.6561999999999, -6.99940000000015,
    -10.5473999999999, -7.34139999999979, -6.80999999999995,
    -6.29719999999998, -6.23199999999997,
])


def _clz64(x: np.ndarray) -> np.ndarray:
    """Vectorized count-leading-zeros over uint64 (exact: works on 32-bit
    halves so float rounding can never flip a bit)."""
    x = x.astype(np.uint64)
    hi = (x >> np.uint64(32)).astype(np.uint32)
    lo = (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)

    def clz32(v: np.ndarray) -> np.ndarray:
        # float64 represents every uint32 exactly, so log2 is exact enough:
        # bit_length = floor(log2(v)) + 1 for v > 0
        out = np.full(v.shape, 32, dtype=np.int32)
        nz = v != 0
        out[nz] = 31 - np.floor(np.log2(v[nz].astype(np.float64))).astype(np.int32)
        return out

    hi_clz = clz32(hi)
    return np.where(hi != 0, hi_clz, 32 + clz32(lo)).astype(np.int32)


def hll_features(hashes: np.ndarray) -> np.ndarray:
    """(2, B) int32: register indices and leading-zero counts per hash.

    Mirrors the per-row math of the reference `update`
    (`StatefulHyperloglogPlus.scala:93-114`): idx = top P bits of the hash,
    pw = clz((hash << P) | 2^(P-1)) + 1.
    """
    h = np.ascontiguousarray(hashes, dtype=np.uint64)
    idx = (h >> np.uint64(IDX_SHIFT)).astype(np.int32)
    w = (h << np.uint64(P)) | W_PADDING
    pw = _clz64(w) + 1
    return np.stack([idx, pw.astype(np.int32)])


def hll_pack_features(hashes: np.ndarray, mask: Optional[np.ndarray] = None) -> np.ndarray:
    """uint16 (idx << 6) | pw per hash — the wire format of the HLL feature
    (2 bytes/row instead of 8; pw <= 57 fits 6 bits, idx < 512 fits 10).
    Masked rows pack as 0, which never wins a register max. numpy fallback
    for the native C++ single-pass kernels (native/lib.py hll_pack_*)."""
    pairs = hll_features(hashes)
    packed = ((pairs[0].astype(np.uint16) << np.uint16(6))
              | pairs[1].astype(np.uint16))
    if mask is not None:
        packed = np.where(mask, packed, np.uint16(0))
    return packed


def estimate_cardinality(registers: np.ndarray) -> float:
    """HLL++ estimate with linear counting + bias correction
    (reference `StatefulHyperloglogPlus.count`, `:210-257`)."""
    regs = np.asarray(registers, dtype=np.int64)
    z_inverse = np.sum(np.ldexp(1.0, -regs))
    v = float(np.count_nonzero(regs == 0))

    e = ALPHA_M2 / z_inverse
    if e < 5.0 * M:
        e_corrected = e - _estimate_bias(e)
    else:
        e_corrected = e

    if v > 0:
        h = M * np.log(M / v)
        estimate = h if h <= THRESHOLD else e_corrected
    else:
        estimate = e_corrected
    return round_half_up(estimate)


def round_half_up(x: float) -> float:
    """JVM ``Math.round`` semantics: floor(x + 0.5), i.e. ties round toward
    +inf (reference `StatefulHyperloglogPlus.count` returns
    `Math.round(estimate)`, `:256`). numpy's ``rint`` rounds half-to-even and
    diverges on exact .5 boundaries."""
    return float(np.floor(x + 0.5))


def _estimate_bias(e: float) -> float:
    """K-nearest-neighbour interpolation into the empirical bias table
    (reference `StatefulHyperloglogPlus.estimateBias`, `:259-297`)."""
    estimates = RAW_ESTIMATES_P9
    n = len(estimates)
    nearest = int(np.searchsorted(estimates, e, side="left"))
    low = max(nearest - K_NEIGHBORS + 1, 0)
    high = min(low + K_NEIGHBORS, n)

    def distance(i: int) -> float:
        d = e - estimates[i]
        return d * d

    while high < n and distance(high) < distance(low):
        low += 1
        high += 1
    return float(np.mean(BIASES_P9[low:high]))


def registers_to_words(registers: np.ndarray) -> np.ndarray:
    """Pack int32[512] registers into the reference's uint64[52] word layout
    (6 bits per register, 10 registers per word, little-endian within word)."""
    regs = np.asarray(registers, dtype=np.uint64)
    words = np.zeros(NUM_WORDS, dtype=np.uint64)
    for i in range(M):
        word_offset = i // REGISTERS_PER_WORD
        shift = np.uint64(REGISTER_SIZE * (i % REGISTERS_PER_WORD))
        words[word_offset] |= (regs[i] & REGISTER_WORD_MASK) << shift
    return words


def words_to_registers(words: np.ndarray) -> np.ndarray:
    """Inverse of :func:`registers_to_words`."""
    words = np.asarray(words, dtype=np.uint64)
    regs = np.zeros(M, dtype=np.int32)
    for i in range(M):
        word = words[i // REGISTERS_PER_WORD]
        shift = np.uint64(REGISTER_SIZE * (i % REGISTERS_PER_WORD))
        regs[i] = int((word >> shift) & REGISTER_WORD_MASK)
    return regs
