"""Observability: end-to-end tracing, flight recorder, trace exporters.

- `trace` — thread-safe span tree (trace_id/span_id/parent_id, monotonic
  timestamps, typed events) with explicit cross-thread propagation;
  default-on, ``DEEQU_TPU_TRACE`` samples/disables.
- `recorder` — bounded process-global ring of finished spans
  (``DEEQU_TPU_TRACE_RING``); typed failures dump their correlated trace
  snippet as JSONL post-mortem artifacts (``DEEQU_TPU_FLIGHT_DIR``).
- `export` — Chrome trace-event / Perfetto JSON + JSONL journal, served
  from the ``/trace`` endpoint on `service.MetricsExporter` and written
  per-stage by ``bench.py``.

See README "Observability" for the span model and operator contract.
"""

from . import export, trace
from .recorder import FlightRecorder, record_failure, recorder
from .trace import (
    NULL,
    SPAN_KINDS,
    TRACE_ENV,
    TRACE_HEADER,
    TRACE_RING_ENV,
    Span,
    TraceContext,
    add_event,
    attach,
    capture,
    current_span,
    enabled,
    extract,
    inject,
    sampled_trace,
    span,
    start_span,
)

__all__ = [
    "trace", "export",
    "Span", "NULL", "span", "start_span", "attach", "capture",
    "current_span", "add_event", "enabled",
    "TraceContext", "inject", "extract", "sampled_trace",
    "FlightRecorder", "recorder", "record_failure",
    "TRACE_ENV", "TRACE_RING_ENV", "TRACE_HEADER", "SPAN_KINDS",
]
