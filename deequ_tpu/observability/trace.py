"""Dapper-style span tracing for the verification service.

Every layer of the stack already *counts* what happened (`RunMonitor`
fields, `ServiceMetrics` series) — but counters cannot answer "which job
failed over, why, and what did it cost?". This module adds the causal
layer (Sigelman et al., 2010): a thread-safe span tree with
``trace_id``/``span_id``/``parent_id``, monotonic timestamps and typed
events, threaded through *job submit -> scheduler admit/retry -> placement
decision -> engine pass -> bundle compile+dispatch -> batch fold -> state
fetch -> metric derivation -> constraint evaluation*, so a degraded run
reads as ONE connected tree instead of disjoint counter bumps.

Design constraints that shaped the implementation:

- **Default-on, near-zero overhead.** Tracing guards the hot per-batch
  phase timers, so span creation is a slot-object + two ``perf_counter_ns``
  reads that the timer already pays. ``DEEQU_TPU_TRACE=0`` disables
  everything (spans become a shared no-op singleton); a float in (0, 1)
  samples that fraction of root traces deterministically.
- **Explicit cross-thread propagation.** Python thread pools do not
  inherit context: every pool this codebase owns (scheduler workers,
  engine prefetch, host-tier partials, watchdog daemon threads) captures
  the submitting thread's span with :func:`capture` and re-enters it with
  :func:`attach` — a span started on a worker is still a child of the job
  that queued it.
- **No wall-clock in span math.** Timestamps are ``perf_counter_ns``
  (process-monotonic, shared across threads); one wall-clock anchor is
  recorded per process so exporters can map to absolute time without any
  span ever depending on a settable clock.

Spans are lightweight records, not RAII handles over locks: ``finish`` is
idempotent, events append under the GIL, and finished spans flow into the
process-global flight-recorder ring (`recorder.py`).
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

#: env var: "0" disables tracing entirely; a float in (0, 1] samples that
#: fraction of ROOT traces (children always follow their root's decision);
#: unset / "1" traces everything.
TRACE_ENV = "DEEQU_TPU_TRACE"

#: env var: capacity of the flight-recorder ring of finished spans
#: (default 4096; see recorder.py).
TRACE_RING_ENV = "DEEQU_TPU_TRACE_RING"

#: HTTP header / RPC field carrying a serialized trace context across
#: process boundaries (front -> worker hops, the Arrow ingest wire).
TRACE_HEADER = "X-Deequ-Trace"

#: the closed set of span ``kind`` literals; statlint's span-kind-registry
#: check reads this frozenset, so a new kind MUST be registered here before
#: any ``span(..., kind=...)`` call site can use it.
SPAN_KINDS = frozenset({
    "span", "phase", "job", "verification", "analysis", "engine",
    "ingest", "stall", "cluster", "tuning", "incremental", "fleetwatch",
    "coalesce", "rpc",
})

#: wall-clock anchor: epoch seconds at (approximately) perf-counter zero,
#: recorded once per process so exporters can place the monotonic span
#: timestamps on an absolute axis.
EPOCH_ANCHOR_S = time.time() - time.perf_counter()


#: warn-once latch for an unparseable DEEQU_TPU_TRACE value
_ENV_WARNED = False


def sample_rate() -> float:
    raw = os.environ.get(TRACE_ENV)
    if raw is None or raw == "":
        return 1.0
    try:
        value = float(raw)
    except ValueError:
        # an operator who set "off"/"false" believes tracing is disabled —
        # we cannot guess intent, but silently ignoring the knob is worse:
        # warn once (the watchdog's unparseable-env convention) and keep
        # the default (tracing on)
        global _ENV_WARNED
        if not _ENV_WARNED:
            _ENV_WARNED = True
            import logging

            logging.getLogger(__name__).warning(
                "ignoring unparseable %s=%r (expected 0, 1, or a sample "
                "fraction in (0, 1)); tracing stays at the default (on)",
                TRACE_ENV, raw,
            )
        return 1.0
    return min(max(value, 0.0), 1.0)


def enabled() -> bool:
    return sample_rate() > 0.0


_IDS = itertools.count(1)
_PID = os.getpid()

_TLS = threading.local()


def _stack() -> List["Span"]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = []
        _TLS.stack = stack
    return stack


class Span:
    """One timed node of a trace tree. Mutable only through ``set_attr`` /
    ``add_event`` / ``finish``; ``finish`` is idempotent and publishes the
    span to the flight-recorder ring exactly once."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "kind",
        "start_ns", "end_ns", "status", "thread", "attrs", "events",
        "_finished",
    )

    def __init__(
        self,
        name: str,
        kind: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        start_ns: Optional[int] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = (
            start_ns if start_ns is not None else time.perf_counter_ns()
        )
        self.end_ns: Optional[int] = None
        self.status = "ok"
        self.thread = threading.get_ident()
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[Dict[str, Any]] = []
        self._finished = False

    @property
    def sampled(self) -> bool:
        return True

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        # list.append is GIL-atomic; events may arrive from threads other
        # than the span's own (the scheduler annotates a job span while a
        # worker executes it)
        self.events.append(
            {"name": name, "ts_ns": time.perf_counter_ns(), "attrs": attrs}
        )

    def finish(self, status: Optional[str] = None, end_ns: Optional[int] = None) -> None:
        if self._finished:
            return
        self._finished = True
        self.end_ns = end_ns if end_ns is not None else time.perf_counter_ns()
        if status is not None:
            self.status = status
        # import by submodule path: the package __init__ rebinds the name
        # "recorder" to the accessor function, so `from . import recorder`
        # would resolve to the function, not the module
        from .recorder import recorder as _get_recorder

        _get_recorder().on_span_finish(self)

    def duration_s(self) -> float:
        end = self.end_ns if self.end_ns is not None else time.perf_counter_ns()
        return (end - self.start_ns) / 1e9

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "status": self.status,
            "thread": self.thread,
            "attrs": self.attrs,
            "events": self.events,
        }

    def __repr__(self) -> str:  # debugging aid only
        return (
            f"Span({self.name!r}, trace={self.trace_id}, span={self.span_id}, "
            f"parent={self.parent_id}, status={self.status})"
        )


class _NullSpan:
    """Shared no-op span: returned when tracing is disabled or the root
    trace was sampled out. Attaching it SUPPRESSES descendants (a child of
    an unsampled trace must not start a fresh trace of its own)."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None
    name = "null"
    kind = "null"
    status = "ok"
    attrs: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    sampled = False

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attrs: Any) -> None:
        pass

    def finish(self, status: Optional[str] = None, end_ns: Optional[int] = None) -> None:
        pass

    def duration_s(self) -> float:
        return 0.0


NULL = _NullSpan()


def _next_trace_id() -> str:
    return f"t{_PID:x}-{next(_IDS):x}"


def _next_span_id() -> str:
    return f"s{_PID:x}-{next(_IDS):x}"


def sampled_trace(trace_id: str, rate: Optional[float] = None) -> bool:
    """The fractional sampler: a pure function of the trace_id, so EVERY
    process holding the same id reaches the same verdict — a sampled trace
    keeps all its spans across the cluster, an unsampled one keeps none
    (no RNG, no per-process counter: cross-host propagation demands the
    decision travel with the id itself)."""
    if rate is None:
        rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    digest = hashlib.sha256(trace_id.encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") / 2.0 ** 64 < rate


def _top():
    stack = _stack()
    return stack[-1] if stack else None


def current_span() -> Optional[Span]:
    """The innermost REAL span on this thread (None when untraced)."""
    top = _top()
    return top if isinstance(top, Span) else None


def capture():
    """The raw current context for cross-thread propagation: a Span, the
    NULL suppressor, or None. Hand the result to :func:`attach` inside the
    worker-thread body."""
    return _top()


def start_span(
    name: str,
    kind: str = "span",
    attrs: Optional[Dict[str, Any]] = None,
    parent: Any = "auto",
) -> Any:
    """Start (but do not attach) a span. ``parent="auto"`` inherits the
    calling thread's current context; pass an explicit Span to parent it
    elsewhere, or None to force a new root trace. Returns :data:`NULL`
    when tracing is off, the root was sampled out, or the inherited
    context is suppressed."""
    if parent == "auto":
        parent = _top()
    if parent is NULL or isinstance(parent, _NullSpan):
        return NULL
    if parent is None:
        trace_id = _next_trace_id()
        if not sampled_trace(trace_id):
            return NULL
        return Span(
            name, kind, trace_id=trace_id, span_id=_next_span_id(),
            parent_id=None, attrs=attrs,
        )
    return Span(
        name, kind, trace_id=parent.trace_id, span_id=_next_span_id(),
        parent_id=parent.span_id, attrs=attrs,
    )


@contextmanager
def span(name: str, kind: str = "span", **attrs: Any):
    """Start a child of the current context, attach it for the block, and
    finish it on exit (status "error" + exception attr if the block
    raises)."""
    sp = start_span(name, kind=kind, attrs=attrs)
    stack = _stack()
    stack.append(sp)
    try:
        yield sp
    except BaseException as exc:
        if sp is not NULL:
            sp.set_attr("error", f"{type(exc).__name__}: {exc}")
            sp.finish("error")
        raise
    else:
        sp.finish()
    finally:
        stack.pop()


@contextmanager
def attach(sp) -> Any:
    """Re-enter a captured context on THIS thread (worker pools, daemon
    threads). ``attach(None)`` is a no-op — the thread keeps whatever
    context it already has; attaching :data:`NULL` suppresses descendant
    spans (the unsampled-trace contract)."""
    if sp is None:
        yield None
        return
    stack = _stack()
    stack.append(sp)
    try:
        yield sp
    finally:
        stack.pop()


def add_event(name: str, span: Optional[Any] = None, **attrs: Any) -> None:
    """Append a typed event to ``span`` (default: the current span); no-op
    when untraced."""
    target = span if span is not None else _top()
    if target is None:
        return
    target.add_event(name, **attrs)


class TraceContext:
    """The wire form of a span's identity: just enough of a remote parent
    (``trace_id`` + parent ``span_id`` + the sampling verdict) for
    :func:`start_span` to hang a child under a trace that began in another
    process. Produced by :func:`extract`, serialized by :func:`inject`."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def to_header(self) -> str:
        return f"{self.trace_id};{self.span_id};{1 if self.sampled else 0}"

    def __repr__(self) -> str:  # debugging aid only
        return f"TraceContext({self.to_header()!r})"


def inject(span: Any = None) -> Optional[str]:
    """Serialize the current context (or ``span``) for the
    :data:`TRACE_HEADER` wire field. Three shapes, matching
    :func:`extract`'s three verdicts: a live span yields
    ``trace_id;span_id;1``; a suppressed (unsampled) context yields
    ``;;0`` so the remote side suppresses too instead of starting a fresh
    root for half a trace; no context at all yields ``None`` (send no
    header — the remote side makes its own root decision)."""
    target = span if span is not None else _top()
    if target is None:
        return None
    if target is NULL or isinstance(target, _NullSpan):
        return ";;0"
    return f"{target.trace_id};{target.span_id};1"


def extract(header: Optional[str]) -> Any:
    """Parse a :data:`TRACE_HEADER` value into something usable as the
    ``parent=`` argument of :func:`start_span`: a :class:`TraceContext`
    (sampled remote parent), :data:`NULL` (the remote root was sampled out
    — suppress descendants here too), or ``None`` (no/unparseable header —
    start a fresh root). Malformed values degrade to ``None`` rather than
    raising: a bad header must never fail the request it rode in on."""
    if not header:
        return None
    parts = str(header).split(";")
    if len(parts) != 3:
        return None
    trace_id, span_id, flag = (p.strip() for p in parts)
    if flag == "0":
        return NULL
    if flag != "1" or not trace_id or not span_id:
        return None
    return TraceContext(trace_id, span_id, sampled=True)


def record_phase(phase: str, start_ns: int, end_ns: int) -> None:
    """Publish one already-measured phase interval as a finished child span
    of the current context. This is the hot-path entry the engine's
    ``RunMonitor.timed`` phase timers call: the timestamps are the timer's
    own, so ``phase_seconds`` numbers and span durations can never
    disagree, and an untraced thread pays a single attribute read."""
    parent = _top()
    if not isinstance(parent, Span):
        return
    sp = Span(
        phase, "phase", trace_id=parent.trace_id, span_id=_next_span_id(),
        parent_id=parent.span_id, start_ns=start_ns,
    )
    sp.finish(end_ns=end_ns)
