"""Flight recorder: bounded ring of recent finished spans + post-mortem
dumps on typed failures.

An operator debugging a 3am failover cannot retroactively enable verbose
logging. The recorder keeps the last ``DEEQU_TPU_TRACE_RING`` finished
spans in memory at all times (default 4096 — a few MB at worst), and every
TYPED failure path (``DeviceFailureException``, ``ScanStallError``,
``CorruptStateError``, ``SchemaDriftError``, ...) calls
:func:`record_failure`, which

1. stamps a ``failure`` event (exception type + message) on the current
   span, so the trace tree itself explains the degradation;
2. marks the failure's ``trace_id`` dump-pending: the moment that trace's
   root (or owning job span) finishes, the correlated span snippet is
   written as a JSONL artifact under :func:`FlightRecorder.directory`
   (``DEEQU_TPU_FLIGHT_DIR``, else a per-process temp dir);
3. counts the failure kind on ``dump_counts`` regardless, so tests and the
   export plane can assert "a dump fired for every typed failure kind"
   without touching the filesystem.

Dumps are bounded (``_MAX_DUMPS``) so a pathological failure storm in a
long-lived service degrades to counting, never to unbounded artifact
growth. A failure with no live trace (tracing disabled, or a loader hit
outside any span) writes a single standalone record carrying only the
exception, so the signal is never silently dropped.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

#: env var: directory receiving flight-record JSONL artifacts; unset =
#: a lazily-created per-process temp directory (path discoverable via
#: ``recorder().directory()`` and logged on first dump).
FLIGHT_DIR_ENV = "DEEQU_TPU_FLIGHT_DIR"

#: env var: directory receiving this process's span JOURNAL — every
#: finished span teed as one JSONL line to ``spans-<host>.jsonl`` (after a
#: ``journal_header`` line carrying host/pid/epoch anchor). Unset = no
#: journal (the default: the ring alone serves single-process use; multi-
#: host soaks set this per worker so ``export.merge_journals`` can stitch
#: one cross-process Perfetto trace).
TRACE_JOURNAL_ENV = "DEEQU_TPU_TRACE_JOURNAL"

#: env var: logical host label stamped on journal lines and the journal
#: filename; unset = ``pid<os.getpid()>``.
TRACE_HOST_ENV = "DEEQU_TPU_TRACE_HOST"


def journal_host() -> str:
    from ..utils import env_str

    return env_str(TRACE_HOST_ENV) or f"pid{os.getpid()}"

#: hard cap on dump artifacts per process: beyond it, failures only count
_MAX_DUMPS = 256

#: span kinds whose finish closes a "unit of work" and releases any
#: pending dump for their trace: the job span (service path), the
#: verification/analysis roots (direct-call path — these close per run
#: even when the caller holds one long-lived outer span), and true roots
_DUMP_TRIGGER_KINDS = frozenset({"job", "verification", "analysis"})

#: bound on traces awaiting their unit-of-work close: beyond it the oldest
#: pending dump flushes immediately with whatever the ring holds (a
#: partial artifact beats a leaked entry that never dumps — e.g. a typed
#: failure recorded by a watchdog-abandoned zombie whose job already
#: finished)
_MAX_PENDING = 64

_DEFAULT_RING = 4096


def ring_capacity() -> int:
    from ..utils import env_number
    from .trace import TRACE_RING_ENV

    # clamp (not reject) below-minimum values: an operator capping trace
    # memory with a tiny ring must get the 16-entry floor, never a silent
    # fallback to the 4096 default; unparseable values warn once
    return max(16, env_number(TRACE_RING_ENV, _DEFAULT_RING, int))


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity or ring_capacity())
        #: trace_id -> [(failure kind, exception repr), ...] awaiting the
        #: trace's root/job span so the dump captures the whole tree
        self._pending: Dict[str, List[Dict[str, Any]]] = {}
        #: typed-failure counts by exception class name (monotonic; counted
        #: even when the dump itself was rate-limited away)
        self.dump_counts: Dict[str, int] = {}
        self.dump_paths: List[str] = []
        #: monotonic artifact sequence — RESERVED under the lock before the
        #: write, so two concurrent dumps with the same stem can never
        #: compute the same filename (and the _MAX_DUMPS cap counts
        #: reservations, not completed writes)
        self._dump_seq = 0
        self._dir: Optional[str] = None
        self._logged_dir = False
        #: span-journal tee: None = env not probed yet, False = off (unset
        #: or failed), else the open line-buffered file handle. Probed
        #: lazily so importing the module never touches the filesystem.
        self._journal: Any = None
        self.journal_path: Optional[str] = None

    # -- span intake ---------------------------------------------------------

    def _journal_handle(self):
        """Resolve (once) and return the journal file handle, or False.
        Caller holds ``self._lock``."""
        if self._journal is None:
            from ..utils import env_str

            directory = env_str(TRACE_JOURNAL_ENV)
            if not directory:
                self._journal = False
            else:
                from .trace import EPOCH_ANCHOR_S

                try:
                    os.makedirs(directory, exist_ok=True)
                    host = journal_host()
                    path = os.path.join(directory, f"spans-{host}.jsonl")
                    # line-buffered: each span line hits the fd as it is
                    # written, so a SIGKILLed worker's journal still holds
                    # everything it finished (the kill-one drill reads it)
                    fh = open(path, "a", buffering=1)
                    fh.write(json.dumps({
                        "journal_header": True, "host": host,
                        "pid": os.getpid(),
                        "epoch_anchor_s": EPOCH_ANCHOR_S,
                    }) + "\n")
                    self._journal = fh
                    self.journal_path = path
                except Exception:  # noqa: BLE001 - journal is advisory
                    import logging

                    logging.getLogger(__name__).warning(
                        "could not open span journal under %s=%r",
                        TRACE_JOURNAL_ENV, directory, exc_info=True,
                    )
                    self._journal = False
        return self._journal

    def on_span_finish(self, span) -> None:
        dump_for: Optional[List[Dict[str, Any]]] = None
        with self._lock:
            self._ring.append(span)
            journal = self._journal_handle()
            if journal is not False:
                try:
                    journal.write(
                        json.dumps(span.to_dict(), default=str) + "\n"
                    )
                except Exception:  # noqa: BLE001 - journal is advisory
                    self._journal = False
            # a unit-of-work span closing releases the trace's pending
            # dump: the job span (service path), verification/analysis
            # (direct-call path — a caller's long-lived outer span may
            # never close while the service runs, and waiting for it would
            # both delay the artifact past ring eviction and pin the
            # pending entry), or a true root
            if span.trace_id in self._pending and (
                span.parent_id is None or span.kind in _DUMP_TRIGGER_KINDS
            ):
                dump_for = self._pending.pop(span.trace_id)
        if dump_for is not None:
            self._dump_trace(span.trace_id, dump_for)

    def spans(self) -> List[Any]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def drain(self) -> List[Any]:
        """Snapshot AND clear the ring (per-stage artifact writers)."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
            return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pending.clear()
            self.dump_counts.clear()
            self.dump_paths.clear()
            self._dump_seq = 0
            # re-probe the journal env on next span: tests (and soaks that
            # re-point DEEQU_TPU_TRACE_JOURNAL between stages) rely on
            # clear() being a full reset of the singleton
            if self._journal not in (None, False):
                try:
                    self._journal.close()
                except Exception:  # noqa: BLE001
                    pass
            self._journal = None
            self.journal_path = None

    # -- failure intake ------------------------------------------------------

    def note_failure(
        self, kind: str, trace_id: Optional[str], detail: str
    ) -> None:
        with self._lock:
            self.dump_counts[kind] = self.dump_counts.get(kind, 0) + 1
        if trace_id is not None:
            overflow = None
            with self._lock:
                self._pending.setdefault(trace_id, []).append(
                    {"kind": kind, "detail": detail}
                )
                if len(self._pending) > _MAX_PENDING:
                    # a trace whose unit of work already closed (zombie
                    # failure after its job finished) would otherwise pin
                    # its entry forever: flush the OLDEST pending trace
                    # now with whatever the ring still holds
                    oldest = next(iter(self._pending))
                    overflow = (oldest, self._pending.pop(oldest))
            if overflow is not None:
                self._dump_trace(*overflow)
            return
        # no live trace: write a standalone record so the failure still
        # leaves an artifact behind
        self._write_dump(
            f"flight-untraced-{kind}",
            [{"flight_record": True, "kind": kind, "detail": detail,
              "trace_id": None}],
        )

    # -- dumping -------------------------------------------------------------

    def directory(self) -> str:
        from ..utils import env_str

        env = env_str(FLIGHT_DIR_ENV)
        if env:
            os.makedirs(env, exist_ok=True)
            return env
        if self._dir is None:
            import tempfile

            self._dir = tempfile.mkdtemp(prefix="deequ-tpu-flight-")
        return self._dir

    def _dump_trace(self, trace_id: str, failures: List[Dict[str, Any]]) -> None:
        with self._lock:
            spans = [s for s in self._ring if s.trace_id == trace_id]
        from .trace import EPOCH_ANCHOR_S

        header = {
            "flight_record": True,
            "trace_id": trace_id,
            "failures": failures,
            "spans": len(spans),
            # span timestamps are process-monotonic perf_counter_ns; add
            # the anchor so a post-mortem can place them on wall clock
            # (absolute seconds ~= epoch_anchor_s + start_ns / 1e9)
            "epoch_anchor_s": EPOCH_ANCHOR_S,
        }
        self._write_dump(
            f"flight-{trace_id}",
            [header] + [s.to_dict() for s in spans],
        )

    def _write_dump(self, stem: str, records: List[Dict[str, Any]]) -> None:
        with self._lock:
            if self._dump_seq >= _MAX_DUMPS:
                return
            seq = self._dump_seq
            self._dump_seq += 1
        try:
            path = os.path.join(self.directory(), f"{stem}-{seq}.jsonl")
            with open(path, "w") as fh:
                for record in records:
                    fh.write(json.dumps(record) + "\n")
        except Exception:  # noqa: BLE001 - post-mortem capture is advisory
            import logging

            logging.getLogger(__name__).warning(
                "could not write flight record %s", stem, exc_info=True
            )
            return
        with self._lock:
            self.dump_paths.append(path)
        if not self._logged_dir:
            self._logged_dir = True
            import logging

            logging.getLogger(__name__).info(
                "flight records land in %s", os.path.dirname(path)
            )


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> FlightRecorder:
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


def record_failure(exc: BaseException, span=None) -> None:
    """The one call every typed failure path makes: event on the current
    span + flight-recorder dump scheduling + kind counting. Safe (and
    still counted) when tracing is disabled."""
    from . import trace

    target = span if span is not None else trace.current_span()
    kind = type(exc).__name__
    detail = str(exc)[:500]
    if target is not None:
        target.add_event("failure", type=kind, message=detail)
    recorder().note_failure(
        kind, target.trace_id if target is not None else None, detail
    )
