"""Trace exporters: Chrome trace-event JSON (Perfetto-loadable) + JSONL.

The Chrome trace-event format is the lowest-common-denominator viewer
contract (``chrome://tracing``, Perfetto, speedscope all read it): one
``traceEvents`` list of complete-duration events (``"ph": "X"``) with
microsecond timestamps, plus instant events (``"ph": "i"``) for the typed
span events (failovers, stalls, drift rejections). Span identity rides in
``args`` (``trace_id``/``span_id``/``parent_id``) so tooling — including
``tools/trace_summarize.py`` — can rebuild the tree from the artifact
alone. The JSONL journal is the lossless form: one span dict per line,
exactly what the flight recorder dumps.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional


def _span_dict(span: Any) -> Dict[str, Any]:
    return span if isinstance(span, dict) else span.to_dict()


def _chrome_events(
    span_dicts: Iterable[Dict[str, Any]],
    pid: int,
    offset_ns: float = 0.0,
    extra_args: Optional[Dict[str, Any]] = None,
) -> List[Dict[str, Any]]:
    """Convert span dicts to Chrome events under one ``pid`` track, with
    an optional monotonic-clock offset (journal merging rebases each
    process's perf-counter axis onto a shared one)."""
    events: List[Dict[str, Any]] = []
    for d in span_dicts:
        start_ns = d["start_ns"]
        end_ns = d["end_ns"] if d["end_ns"] is not None else start_ns
        args = dict(d.get("attrs") or {})
        if extra_args:
            args.update(extra_args)
        args.update(
            trace_id=d["trace_id"], span_id=d["span_id"],
            parent_id=d["parent_id"], status=d.get("status", "ok"),
        )
        events.append(
            {
                "name": d["name"],
                "cat": d.get("kind", "span"),
                "ph": "X",
                "ts": (start_ns + offset_ns) / 1e3,
                "dur": max(end_ns - start_ns, 0) / 1e3,
                "pid": pid,
                "tid": d.get("thread", 0),
                "args": args,
            }
        )
        for ev in d.get("events", ()):
            ev_args = dict(ev.get("attrs") or {})
            ev_args.update(trace_id=d["trace_id"], span_id=d["span_id"])
            events.append(
                {
                    "name": ev["name"],
                    "cat": "event",
                    "ph": "i",
                    "s": "t",
                    "ts": (ev["ts_ns"] + offset_ns) / 1e3,
                    "pid": pid,
                    "tid": d.get("thread", 0),
                    "args": ev_args,
                }
            )
    return events


def spans_to_chrome(spans: Iterable[Any]) -> Dict[str, Any]:
    """Chrome trace-event JSON object for ``spans`` (Span objects or their
    dicts). Timestamps convert ns -> µs; unfinished spans export with zero
    duration rather than being dropped (a crash artifact should still show
    what was in flight)."""
    events = _chrome_events((_span_dict(s) for s in spans), pid=os.getpid())
    from .trace import EPOCH_ANCHOR_S

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        # ts values are process-monotonic µs (perf_counter); the anchor
        # maps them to wall clock for log/Prometheus correlation:
        # absolute seconds ~= epoch_anchor_s + ts / 1e6
        "otherData": {"epoch_anchor_s": EPOCH_ANCHOR_S},
    }


def load_journal(path: str):
    """Read one span-journal JSONL file. Returns ``(header, spans,
    skipped)``: the ``journal_header`` record (or None for headerless /
    flight-dump files), the span dicts in file order, and the count of
    lines that did not parse (a SIGKILLed writer legitimately leaves a
    torn final line — skipped, counted, never fatal)."""
    header: Optional[Dict[str, Any]] = None
    spans: List[Dict[str, Any]] = []
    skipped = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(record, dict):
                skipped += 1
                continue
            if record.get("journal_header"):
                if header is None:
                    header = record
                continue
            if record.get("flight_record"):
                continue
            if "span_id" not in record or "start_ns" not in record:
                skipped += 1
                continue
            spans.append(record)
    return header, spans, skipped


def merge_journals(
    paths: Iterable[str], out_path: Optional[str] = None
) -> Dict[str, Any]:
    """Merge per-host span journals into ONE Chrome/Perfetto trace object.

    Each journal's timestamps are that process's ``perf_counter_ns`` axis;
    its header's ``epoch_anchor_s`` places the axis on wall clock. The
    merge rebases every file onto the earliest anchor, so spans from
    different hosts line up on one timeline, one ``pid`` track per journal
    (named after its host label). Cross-process causality needs no clock
    at all — it rides the shared ``trace_id``/``parent_id`` in ``args``.
    """
    journals = []
    for path in sorted(paths):
        header, spans, skipped = load_journal(path)
        if header is None:
            header = {
                "host": os.path.basename(path), "pid": 0,
                "epoch_anchor_s": 0.0,
            }
        journals.append((header, spans, skipped, path))
    anchors = [h.get("epoch_anchor_s", 0.0) or 0.0 for h, _, _, _ in journals]
    base_anchor = min(anchors) if anchors else 0.0
    events: List[Dict[str, Any]] = []
    meta = []
    for track, (header, spans, skipped, path) in enumerate(journals, 1):
        host = str(header.get("host") or f"journal{track}")
        anchor = header.get("epoch_anchor_s", 0.0) or 0.0
        offset_ns = (anchor - base_anchor) * 1e9
        events.append(
            {"name": "process_name", "ph": "M", "pid": track, "tid": 0,
             "args": {"name": host}}
        )
        events.extend(
            _chrome_events(spans, pid=track, offset_ns=offset_ns,
                           extra_args={"host": host})
        )
        meta.append(
            {"host": host, "path": path, "spans": len(spans),
             "skipped_lines": skipped, "epoch_anchor_s": anchor}
        )
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"epoch_anchor_s": base_anchor, "journals": meta},
    }
    if out_path is not None:
        _write_atomic(out_path, json.dumps(doc))
    return doc


def chrome_trace_text(spans: Optional[Iterable[Any]] = None) -> str:
    if spans is None:
        from .recorder import recorder

        spans = recorder().spans()
    return json.dumps(spans_to_chrome(spans))


def spans_to_jsonl(spans: Optional[Iterable[Any]] = None) -> str:
    if spans is None:
        from .recorder import recorder

        spans = recorder().spans()
    return "".join(json.dumps(_span_dict(s)) + "\n" for s in spans)


def write_chrome_trace(path: str, spans: Optional[Iterable[Any]] = None) -> str:
    """Write the Chrome artifact (default: the flight-recorder ring);
    returns ``path``."""
    text = chrome_trace_text(spans)
    _write_atomic(path, text)
    return path


def write_jsonl(path: str, spans: Optional[Iterable[Any]] = None) -> str:
    _write_atomic(path, spans_to_jsonl(spans))
    return path


def _write_atomic(path: str, text: str) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)
