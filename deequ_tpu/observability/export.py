"""Trace exporters: Chrome trace-event JSON (Perfetto-loadable) + JSONL.

The Chrome trace-event format is the lowest-common-denominator viewer
contract (``chrome://tracing``, Perfetto, speedscope all read it): one
``traceEvents`` list of complete-duration events (``"ph": "X"``) with
microsecond timestamps, plus instant events (``"ph": "i"``) for the typed
span events (failovers, stalls, drift rejections). Span identity rides in
``args`` (``trace_id``/``span_id``/``parent_id``) so tooling — including
``tools/trace_summarize.py`` — can rebuild the tree from the artifact
alone. The JSONL journal is the lossless form: one span dict per line,
exactly what the flight recorder dumps.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional


def _span_dict(span: Any) -> Dict[str, Any]:
    return span if isinstance(span, dict) else span.to_dict()


def spans_to_chrome(spans: Iterable[Any]) -> Dict[str, Any]:
    """Chrome trace-event JSON object for ``spans`` (Span objects or their
    dicts). Timestamps convert ns -> µs; unfinished spans export with zero
    duration rather than being dropped (a crash artifact should still show
    what was in flight)."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    for raw in spans:
        d = _span_dict(raw)
        start_ns = d["start_ns"]
        end_ns = d["end_ns"] if d["end_ns"] is not None else start_ns
        args = dict(d.get("attrs") or {})
        args.update(
            trace_id=d["trace_id"], span_id=d["span_id"],
            parent_id=d["parent_id"], status=d.get("status", "ok"),
        )
        events.append(
            {
                "name": d["name"],
                "cat": d.get("kind", "span"),
                "ph": "X",
                "ts": start_ns / 1e3,
                "dur": max(end_ns - start_ns, 0) / 1e3,
                "pid": pid,
                "tid": d.get("thread", 0),
                "args": args,
            }
        )
        for ev in d.get("events", ()):
            ev_args = dict(ev.get("attrs") or {})
            ev_args.update(trace_id=d["trace_id"], span_id=d["span_id"])
            events.append(
                {
                    "name": ev["name"],
                    "cat": "event",
                    "ph": "i",
                    "s": "t",
                    "ts": ev["ts_ns"] / 1e3,
                    "pid": pid,
                    "tid": d.get("thread", 0),
                    "args": ev_args,
                }
            )
    from .trace import EPOCH_ANCHOR_S

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        # ts values are process-monotonic µs (perf_counter); the anchor
        # maps them to wall clock for log/Prometheus correlation:
        # absolute seconds ~= epoch_anchor_s + ts / 1e6
        "otherData": {"epoch_anchor_s": EPOCH_ANCHOR_S},
    }


def chrome_trace_text(spans: Optional[Iterable[Any]] = None) -> str:
    if spans is None:
        from .recorder import recorder

        spans = recorder().spans()
    return json.dumps(spans_to_chrome(spans))


def spans_to_jsonl(spans: Optional[Iterable[Any]] = None) -> str:
    if spans is None:
        from .recorder import recorder

        spans = recorder().spans()
    return "".join(json.dumps(_span_dict(s)) + "\n" for s in spans)


def write_chrome_trace(path: str, spans: Optional[Iterable[Any]] = None) -> str:
    """Write the Chrome artifact (default: the flight-recorder ring);
    returns ``path``."""
    text = chrome_trace_text(spans)
    _write_atomic(path, text)
    return path


def write_jsonl(path: str, spans: Optional[Iterable[Any]] = None) -> str:
    _write_atomic(path, spans_to_jsonl(spans))
    return path


def _write_atomic(path: str, text: str) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)
