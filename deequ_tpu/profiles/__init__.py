"""Column profiling: generic stats, numeric stats and low-cardinality
histograms (reference `profiles/ColumnProfiler.scala:69-712`,
`profiles/ColumnProfile.scala`, `profiles/ColumnProfilerRunner.scala`).

The reference needs 3 scans of the data (header comment
`ColumnProfiler.scala:57-68`). Here passes 1 and 3 run the same machinery,
and because the engine folds host-accumulated histograms into the SAME
single pass as the device scan, a full profile touches the data at most
twice: pass 1 (generic stats) and pass 2 (numeric stats on the casted view
+ exact histograms). When no string column needs casting, the engine could
do it in one; the two-pass split is kept because pass 2's analyzer set
depends on pass 1's inferred types.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..analyzers import (
    ApproxCountDistinct,
    Completeness,
    DataType,
    Histogram,
    KLLParameters,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from ..data import ColumnKind, Dataset
from ..metrics import BucketDistribution, Distribution, HistogramMetric
from ..runners.analysis_runner import AnalysisRunner

DEFAULT_CARDINALITY_THRESHOLD = 120  # reference `ColumnProfiler.scala:71`

#: inferred/known type names (reference `DataTypeInstances`)
UNKNOWN, FRACTIONAL, INTEGRAL, BOOLEAN, STRING = (
    "Unknown", "Fractional", "Integral", "Boolean", "String",
)


def determine_type(dist: Distribution) -> str:
    """Decision tree over the type histogram
    (reference `analyzers/DataType.scala:116-143`)."""

    def ratio_of(key: str) -> float:
        return dist.values[key].ratio if key in dist.values else 0.0

    if ratio_of(UNKNOWN) == 1.0:
        return UNKNOWN
    if ratio_of(STRING) > 0.0 or (
        ratio_of(BOOLEAN) > 0.0 and (ratio_of(INTEGRAL) > 0.0 or ratio_of(FRACTIONAL) > 0.0)
    ):
        return STRING
    if ratio_of(BOOLEAN) > 0.0:
        return BOOLEAN
    if ratio_of(FRACTIONAL) > 0.0:
        return FRACTIONAL
    return INTEGRAL


@dataclass(frozen=True)
class ColumnProfile:
    """(reference `profiles/ColumnProfile.scala:24-45`)."""

    column: str
    completeness: float
    approximate_num_distinct_values: int
    data_type: str
    is_data_type_inferred: bool
    type_counts: Dict[str, int] = field(default_factory=dict)
    histogram: Optional[Distribution] = None


@dataclass(frozen=True)
class StandardColumnProfile(ColumnProfile):
    pass


@dataclass(frozen=True)
class NumericColumnProfile(ColumnProfile):
    """(reference `profiles/ColumnProfile.scala:47-61`)."""

    mean: Optional[float] = None
    maximum: Optional[float] = None
    minimum: Optional[float] = None
    sum: Optional[float] = None
    std_dev: Optional[float] = None
    approx_percentiles: Optional[List[float]] = None
    kll: Optional[BucketDistribution] = None


class ColumnProfiles:
    """(reference `profiles/ColumnProfile.scala` ColumnProfiles + toJson)."""

    def __init__(self, profiles: Dict[str, ColumnProfile], num_records: int):
        self.profiles = profiles
        self.num_records = num_records

    def __getitem__(self, column: str) -> ColumnProfile:
        return self.profiles[column]

    def to_json(self) -> str:
        columns = []
        for profile in self.profiles.values():
            entry: Dict[str, Any] = {
                "column": profile.column,
                "dataType": profile.data_type,
                "isDataTypeInferred": str(profile.is_data_type_inferred).lower(),
                "completeness": profile.completeness,
                "approximateNumDistinctValues": profile.approximate_num_distinct_values,
            }
            if profile.type_counts:
                entry["typeCounts"] = dict(profile.type_counts)
            if profile.histogram is not None:
                entry["histogram"] = [
                    {"value": k, "count": v.absolute, "ratio": v.ratio}
                    for k, v in profile.histogram.values.items()
                ]
            if isinstance(profile, NumericColumnProfile):
                entry.update(
                    {
                        "mean": profile.mean,
                        "maximum": profile.maximum,
                        "minimum": profile.minimum,
                        "sum": profile.sum,
                        "stdDev": profile.std_dev,
                        "approxPercentiles": profile.approx_percentiles or [],
                    }
                )
            columns.append(entry)
        return json.dumps({"columns": columns}, indent=2)


class ColumnProfiler:
    @staticmethod
    def profile(
        data: Dataset,
        restrict_to_columns: Optional[Sequence[str]] = None,
        print_status_updates: bool = False,
        low_cardinality_histogram_threshold: int = DEFAULT_CARDINALITY_THRESHOLD,
        metrics_repository=None,
        reuse_existing_results_using_key=None,
        fail_if_results_for_reusing_missing: bool = False,
        save_in_metrics_repository_using_key=None,
        kll_parameters: Optional[KLLParameters] = None,
        predefined_types: Optional[Dict[str, str]] = None,
        batch_size: Optional[int] = None,
        monitor=None,
        sharding=None,
        placement: Optional[str] = None,
    ) -> ColumnProfiles:
        """(reference `ColumnProfiler.profile`, `ColumnProfiler.scala:91-208`)."""
        predefined_types = dict(predefined_types or {})
        schema = data.schema
        if restrict_to_columns is not None:
            for name in restrict_to_columns:
                if name not in schema:
                    raise ValueError(f"Unable to find column {name}")
        relevant = [
            c.name
            for c in schema.columns
            if restrict_to_columns is None or c.name in restrict_to_columns
        ]
        run_kwargs = dict(
            metrics_repository=metrics_repository,
            reuse_existing_results_for_key=reuse_existing_results_using_key,
            fail_if_results_missing=fail_if_results_for_reusing_missing,
            save_or_append_results_with_key=save_in_metrics_repository_using_key,
            batch_size=batch_size,
            monitor=monitor,
            sharding=sharding,
            placement=placement,
        )

        # ---- PASS 1: generic statistics (reference `:122-139`) PLUS the
        # numeric statistics of columns the SCHEMA already types as numeric:
        # those don't depend on pass-1 type inference, so they share the
        # first scan (the reference always defers them to pass 2,
        # `ColumnProfiler.scala:153-171` — for an all-native-typed table this
        # build profiles in ONE data pass plus the low-card histogram scan)
        if print_status_updates:
            print("### PROFILING: Computing generic column statistics in pass (1/2)...")
        first_pass: List[Any] = [Size()]
        for name in relevant:
            first_pass.append(Completeness(name))
            first_pass.append(ApproxCountDistinct(name))
            if schema[name].kind == ColumnKind.STRING and name not in predefined_types:
                first_pass.append(DataType(name))
            elif schema[name].kind.is_numeric and predefined_types.get(
                name, INTEGRAL
            ) in (INTEGRAL, FRACTIONAL):
                # skipped when the user predefines the column as non-numeric
                first_pass += _numeric_analyzers(name, kll_parameters)
        # histograms of DICTIONARY-ENCODED columns whose dictionary is
        # already <= the cardinality threshold join pass 1 (distinct <=
        # dictionary size, so eligibility cannot be decided otherwise after
        # the scan); the reference always needs its third pass for these
        # (`ColumnProfiler.scala:181-205`). Columns the HLL estimate later
        # DISQUALIFIES (estimate error can exceed the threshold even when
        # the true cardinality is under it) are dropped below, preserving
        # reference semantics. Histograms count ORIGINAL values, so running
        # them before the numeric-string cast is exactly right.
        hist_pass1 = {
            name
            for name in relevant
            if (
                (size := data.dictionary_size(name)) is not None
                and size <= low_cardinality_histogram_threshold
            )
        }
        first_pass += [Histogram(name) for name in sorted(hist_pass1)]
        first_results = AnalysisRunner.do_analysis_run(data, first_pass, **run_kwargs)

        generic = _extract_generic_statistics(
            relevant, schema, first_results, predefined_types
        )

        # ---- PASS 2: numeric statistics on the casted view + exact
        # histograms of low-cardinality columns, ONE shared scan
        # (reference needs separate passes 2 and 3, `:153-205`) ----
        if print_status_updates:
            print(
                "### PROFILING: Computing numeric statistics + low-cardinality "
                "histograms in pass (2/2)..."
            )
        casted, casted_names = _cast_numeric_string_columns(relevant, data, generic)
        second_pass: List[Any] = []
        for name in relevant:
            if generic.type_of(name) in (INTEGRAL, FRACTIONAL) and not schema[
                name
            ].kind.is_numeric:
                # only inference-detected (casted string) columns remain;
                # schema-typed numerics already ran in pass 1
                second_pass += _numeric_analyzers(name, kll_parameters)
        histogram_columns = _find_target_columns_for_histograms(
            schema, generic, low_cardinality_histogram_threshold
        )
        # histograms must count ORIGINAL values (reference pass 3 reads the
        # raw data, `getHistogramsForThirdPass`): share pass 2 only for
        # columns the cast did not touch, else run them in an extra pass;
        # columns already histogrammed in pass 1 are done either way
        remaining_hist = [c for c in histogram_columns if c not in hist_pass1]
        shared_hist = [c for c in remaining_hist if c not in casted_names]
        extra_hist = [c for c in remaining_hist if c in casted_names]
        # pass-1 estimates prove these columns low-cardinality, so encode
        # them now (floats/ints included): their histograms then ride the
        # device frequency scan instead of a per-batch host group-by. The
        # encoded view memoizes on the source dataset so repeated profiles
        # reuse ONE arrow table (keeping the device feature cache hot).
        encodable = tuple(
            c for c in shared_hist if casted.dictionary_size(c) is None
        )
        if encodable:
            ekey = ("__profile_encoded__", tuple(sorted(casted_names)), encodable)
            encoded = data.derived_cache.get(ekey)
            if encoded is None:
                encoded = casted.with_columns_dictionary_encoded(encodable)
                data.derived_cache[ekey] = encoded
            casted = encoded
        second_pass += [Histogram(name) for name in shared_hist]
        second_results = None
        third_results = None
        extra_hist_pass = [Histogram(name) for name in extra_hist]
        if (
            second_pass
            and extra_hist_pass
            and run_kwargs.get("save_or_append_results_with_key") is None
        ):
            # the two pass-2 scans are INDEPENDENT (numeric stats over the
            # casted view vs raw-value histograms of casted columns), so
            # they overlap: one thread's state fetch rides the feed link
            # while the other's batches stream through the async device
            # queue — the 2-pass overlap the slim-fetch redesign calls for.
            # (With a repository save key the runs stay sequential: the
            # append path is read-modify-write on the shared repository.)
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="deequ-profile-pass"
            ) as pool:
                second_future = pool.submit(
                    AnalysisRunner.do_analysis_run, casted, second_pass,
                    **run_kwargs,
                )
                third_results = AnalysisRunner.do_analysis_run(
                    data, extra_hist_pass, **run_kwargs
                )
                second_results = second_future.result()
        else:
            if second_pass:
                second_results = AnalysisRunner.do_analysis_run(
                    casted, second_pass, **run_kwargs
                )
            if extra_hist_pass:
                third_results = AnalysisRunner.do_analysis_run(
                    data, extra_hist_pass, **run_kwargs
                )

        numeric_stats = _extract_numeric_statistics(first_results, second_results)
        histograms: Dict[str, Distribution] = {}
        eligible_hist = set(histogram_columns)
        for results in (first_results, second_results, third_results):
            if results is None:
                continue
            for analyzer, metric in results.metric_map.items():
                if (
                    isinstance(analyzer, Histogram)
                    and metric.value.is_success
                    and analyzer.column in eligible_hist
                ):
                    histograms[analyzer.column] = metric.value.get()

        return _create_profiles(relevant, generic, numeric_stats, histograms)


@dataclass
class _GenericColumnStatistics:
    num_records: int
    inferred_types: Dict[str, str]
    known_types: Dict[str, str]
    type_detection_histograms: Dict[str, Dict[str, int]]
    approximate_num_distincts: Dict[str, int]
    completenesses: Dict[str, float]
    predefined_types: Dict[str, str]

    def type_of(self, column: str) -> str:
        merged = {**self.inferred_types, **self.known_types, **self.predefined_types}
        return merged[column]


def _extract_generic_statistics(
    columns, schema, results, predefined_types
) -> _GenericColumnStatistics:
    """(reference `ColumnProfiler.scala:358-420`)."""
    num_records = 0
    inferred: Dict[str, str] = {}
    type_hists: Dict[str, Dict[str, int]] = {}
    distincts: Dict[str, int] = {}
    completenesses: Dict[str, float] = {}
    for analyzer, metric in results.metric_map.items():
        if isinstance(analyzer, Size) and metric.value.is_success:
            num_records = int(metric.value.get())
        elif isinstance(analyzer, DataType) and metric.value.is_success:
            if analyzer.column in predefined_types:
                continue
            dist = metric.value.get()
            inferred[analyzer.column] = determine_type(dist)
            type_hists[analyzer.column] = {
                k: v.absolute for k, v in dist.values.items()
            }
        elif isinstance(analyzer, ApproxCountDistinct) and metric.value.is_success:
            distincts[analyzer.column] = int(metric.value.get())
        elif isinstance(analyzer, Completeness) and metric.value.is_success:
            completenesses[analyzer.column] = metric.value.get()

    known: Dict[str, str] = {}
    for cs in schema.columns:
        if cs.name not in columns or cs.name in predefined_types:
            continue
        if cs.kind == ColumnKind.STRING:
            continue
        known[cs.name] = {
            ColumnKind.INTEGRAL: INTEGRAL,
            ColumnKind.FRACTIONAL: FRACTIONAL,
            ColumnKind.BOOLEAN: BOOLEAN,
            ColumnKind.TIMESTAMP: STRING,  # same TODO as the reference
        }.get(cs.kind, UNKNOWN)
    return _GenericColumnStatistics(
        num_records, inferred, known, type_hists, distincts, completenesses,
        predefined_types,
    )


def _cast_numeric_string_columns(columns, data: Dataset, generic):
    """(reference `castColumn`/`castNumericStringColumns`,
    `ColumnProfiler.scala:346-354,294-308`). Returns (dataset, casted names).
    The casted view memoizes on the source dataset (same inferred types ->
    same view), so repeated profiles share one arrow table identity."""
    names = {
        name
        for name in columns
        if data.schema[name].kind == ColumnKind.STRING
        and generic.type_of(name) in (INTEGRAL, FRACTIONAL)
    }
    if not names:
        return data, names
    key = ("__profile_casted__", tuple(sorted(names)))
    casted = data.derived_cache.get(key)
    if casted is None:
        casted = data
        for name in sorted(names):
            casted = casted.with_column_cast_to_f64(name)
        data.derived_cache[key] = casted
    return casted, names


def _find_target_columns_for_histograms(schema, generic, threshold) -> List[str]:
    """(reference `ColumnProfiler.scala:608-630`)."""
    eligible_kinds = (
        ColumnKind.STRING, ColumnKind.BOOLEAN, ColumnKind.INTEGRAL, ColumnKind.FRACTIONAL,
    )
    out = []
    for column, count in generic.approximate_num_distincts.items():
        if column not in schema or schema[column].kind not in eligible_kinds:
            continue
        if generic.type_of(column) not in (STRING, BOOLEAN, INTEGRAL, FRACTIONAL):
            continue
        if count <= threshold:
            out.append(column)
    return out


@dataclass
class _NumericColumnStatistics:
    means: Dict[str, float] = field(default_factory=dict)
    std_devs: Dict[str, float] = field(default_factory=dict)
    minima: Dict[str, float] = field(default_factory=dict)
    maxima: Dict[str, float] = field(default_factory=dict)
    sums: Dict[str, float] = field(default_factory=dict)
    kll: Dict[str, BucketDistribution] = field(default_factory=dict)
    approx_percentiles: Dict[str, List[float]] = field(default_factory=dict)


def _numeric_analyzers(name: str, kll_parameters: Optional[KLLParameters]) -> List[Any]:
    return [
        Minimum(name), Maximum(name), Mean(name),
        StandardDeviation(name), Sum(name),
        KLLSketch(name, kll_parameters),
    ]


def _extract_numeric_statistics(*result_sets) -> _NumericColumnStatistics:
    """(reference `ColumnProfiler.scala:440-520`). Accepts several analyzer
    contexts (pass 1 carries the schema-typed numeric columns, pass 2 the
    casted ones) and merges them."""
    stats = _NumericColumnStatistics()
    for results in result_sets:
        if results is not None:
            _fold_numeric_statistics(stats, results)
    return stats


def _fold_numeric_statistics(stats: _NumericColumnStatistics, results) -> None:
    for analyzer, metric in results.metric_map.items():
        if not metric.value.is_success:
            continue
        if isinstance(analyzer, Mean):
            stats.means[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, StandardDeviation):
            stats.std_devs[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, Minimum):
            stats.minima[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, Maximum):
            stats.maxima[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, Sum):
            stats.sums[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, KLLSketch):
            dist = metric.value.get()
            stats.kll[analyzer.column] = dist
            stats.approx_percentiles[analyzer.column] = sorted(dist.compute_percentiles())


def _create_profiles(columns, generic, numeric_stats, histograms) -> ColumnProfiles:
    """(reference `ColumnProfiler.scala:632-700`)."""
    out: Dict[str, ColumnProfile] = {}
    for name in columns:
        completeness = generic.completenesses.get(name, 0.0)
        approx_distinct = generic.approximate_num_distincts.get(name, 0)
        data_type = generic.type_of(name)
        # predefined types are user-asserted, not inferred (reference
        # `ColumnProfiler.scala:671`)
        inferred = name in generic.inferred_types
        type_counts = generic.type_detection_histograms.get(name, {})
        histogram = histograms.get(name)
        if data_type in (INTEGRAL, FRACTIONAL):
            out[name] = NumericColumnProfile(
                column=name,
                completeness=completeness,
                approximate_num_distinct_values=approx_distinct,
                data_type=data_type,
                is_data_type_inferred=inferred,
                type_counts=type_counts,
                histogram=histogram,
                mean=numeric_stats.means.get(name),
                maximum=numeric_stats.maxima.get(name),
                minimum=numeric_stats.minima.get(name),
                sum=numeric_stats.sums.get(name),
                std_dev=numeric_stats.std_devs.get(name),
                approx_percentiles=numeric_stats.approx_percentiles.get(name),
                kll=numeric_stats.kll.get(name),
            )
        else:
            out[name] = StandardColumnProfile(
                column=name,
                completeness=completeness,
                approximate_num_distinct_values=approx_distinct,
                data_type=data_type,
                is_data_type_inferred=inferred,
                type_counts=type_counts,
                histogram=histogram,
            )
    return ColumnProfiles(out, generic.num_records)


class ColumnProfilerRunner:
    """(reference `profiles/ColumnProfilerRunner.scala:37-113`)."""

    @staticmethod
    def on_data(data: Dataset) -> "ColumnProfilerRunBuilder":
        return ColumnProfilerRunBuilder(data)


class ColumnProfilerRunBuilder:
    """(reference `profiles/ColumnProfilerRunBuilder.scala:29+`)."""

    def __init__(self, data: Dataset):
        self.data = data
        self._columns: Optional[Sequence[str]] = None
        self._print_status_updates = False
        self._cardinality_threshold = DEFAULT_CARDINALITY_THRESHOLD
        self._kll_parameters: Optional[KLLParameters] = None
        self._predefined_types: Dict[str, str] = {}
        self._repository = None
        self._reuse_key = None
        self._fail_if_missing = False
        self._save_key = None
        self._profiles_path: Optional[str] = None
        self._batch_size: Optional[int] = None
        self._monitor = None
        self._sharding = None
        self._placement: Optional[str] = None

    def restrict_to_columns(self, columns: Sequence[str]):
        self._columns = columns
        return self

    def print_status_updates(self):
        self._print_status_updates = True
        return self

    def with_low_cardinality_histogram_threshold(self, threshold: int):
        self._cardinality_threshold = threshold
        return self

    def set_kll_parameters(self, parameters: KLLParameters):
        self._kll_parameters = parameters
        return self

    def set_predefined_types(self, types: Dict[str, str]):
        self._predefined_types = dict(types)
        return self

    def use_repository(self, repository):
        self._repository = repository
        return self

    def reuse_existing_results_for_key(self, key, fail_if_results_missing: bool = False):
        self._reuse_key = key
        self._fail_if_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key):
        self._save_key = key
        return self

    def save_column_profiles_json_to_path(self, path: str):
        self._profiles_path = path
        return self

    def with_batch_size(self, batch_size: int):
        self._batch_size = batch_size
        return self

    def with_monitor(self, monitor):
        self._monitor = monitor
        return self

    def with_sharding(self, sharding):
        self._sharding = sharding
        return self

    def with_placement(self, placement: str):
        """Force the ingest tier ("device" / "host"; default auto-probes
        the feed link)."""
        self._placement = placement
        return self

    def run(self) -> ColumnProfiles:
        profiles = ColumnProfiler.profile(
            self.data,
            restrict_to_columns=self._columns,
            print_status_updates=self._print_status_updates,
            low_cardinality_histogram_threshold=self._cardinality_threshold,
            metrics_repository=self._repository,
            reuse_existing_results_using_key=self._reuse_key,
            fail_if_results_for_reusing_missing=self._fail_if_missing,
            save_in_metrics_repository_using_key=self._save_key,
            kll_parameters=self._kll_parameters,
            predefined_types=self._predefined_types,
            batch_size=self._batch_size,
            monitor=self._monitor,
            sharding=self._sharding,
            placement=self._placement,
        )
        if self._profiles_path is not None:
            from .. import io as dio

            dio.write_text_atomic(self._profiles_path, profiles.to_json())
        return profiles
