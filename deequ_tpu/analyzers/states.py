"""Analyzer states: fixed-shape array pytrees with semigroup merge.

Each state mirrors a reference state class (`analyzers/*.scala`) but is a
flax.struct dataclass of jax scalars/arrays, so it is jit-able, donate-able,
collectively-mergeable over a mesh, and trivially serializable — the property
the reference gets from raw agg byte-buffers (`analyzers/StateProvider.scala:
187-241`).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp
import numpy as np

from ..config import ACC_DTYPE, COUNT_DTYPE


def _f(x: float) -> jnp.ndarray:
    return jnp.asarray(x, dtype=ACC_DTYPE)


def _i(x: int) -> jnp.ndarray:
    return jnp.asarray(x, dtype=COUNT_DTYPE)


def min_nan_largest(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise min under Spark's NaN-largest total order (reals < +inf <
    NaN): NaN never wins, making it the identity — and the init value — of
    MinState. The single definition serves both the device update path
    (analyzers/simple.py) and state merges, so the two cannot drift."""
    return jnp.where(jnp.isnan(a), b, jnp.where(jnp.isnan(b), a, jnp.minimum(a, b)))


@flax.struct.dataclass
class FrequencyCountsState:
    """Dense per-category counts for the device frequency path (dictionary-
    encoded grouping columns): counts[i] = rows whose code is i, plus the
    total row count the frequency semantics require (reference
    `GroupingAnalyzers.scala:53-80`: numRows counts ALL rows)."""

    counts: jnp.ndarray    # int64[num_categories]
    num_rows: jnp.ndarray  # int64

    @staticmethod
    def init(num_categories: int) -> "FrequencyCountsState":
        return FrequencyCountsState(
            jnp.zeros(num_categories, dtype=COUNT_DTYPE), _i(0)
        )

    def merge(self, other: "FrequencyCountsState") -> "FrequencyCountsState":
        return FrequencyCountsState(
            self.counts + other.counts, self.num_rows + other.num_rows
        )


@flax.struct.dataclass
class FrequencyTableState:
    """Device-resident frequency engine state for ARBITRARY-cardinality
    grouping sets (the dense ``FrequencyCountsState`` covers only small
    dictionary code spaces): a sorted fixed-shape (key, count) table plus a
    raw append buffer of per-row 64-bit group keys, all pow2-shaped so the
    trace stays shape-static and signature-bundleable.

    Tiering (ROADMAP item 3): per-batch folds APPEND hashed keys to ``buf``
    (a memcpy-speed ``dynamic_update_slice`` — no scatter, no sort on the
    hot path); when the buffer would overflow, an in-trace sort-merge
    compaction (:func:`deequ_tpu.ops.freq_compact`) folds it into the
    sorted table of ``slots`` uniques; groups that overflow even the table
    are counted exactly into ``lost_groups``/``lost_rows`` and the runner
    re-runs those grouping sets through the host accumulator (whose
    ``_SpillStore`` is thereby the LAST-RESORT tier instead of the default
    path). ``sent_rows`` counts rows whose mixed key collided with the
    sentinel — they form exactly one group, restored at drain time, so the
    bijective single-column mixes stay collision-free end to end.

    Merging (cross-batch, cross-device ``collective_merge_states``,
    cross-run) is the same compaction over both operands' tables and
    buffers — the frequency analog of the reference's outer-join merge
    (`GroupingAnalyzers.scala:128-148`) without ever leaving the device."""

    sorted_keys: jnp.ndarray    # uint64[slots], ascending, sentinel-padded
    sorted_counts: jnp.ndarray  # int64[slots]
    n_table: jnp.ndarray        # int64: occupied table entries
    buf: jnp.ndarray            # uint64[buffer_entries] raw per-row keys
    buf_fill: jnp.ndarray       # int64: appended entries (rows incl. masked)
    sent_rows: jnp.ndarray      # int64: rows whose key collided w/ sentinel
    lost_groups: jnp.ndarray    # int64: groups dropped at compactions (an
    #   upper bound: a group re-appearing after a drop counts again)
    lost_rows: jnp.ndarray      # int64: rows inside dropped groups (EXACT:
    #   any nonzero value routes the set to the host last-resort tier)
    num_rows: jnp.ndarray       # int64: ALL rows seen (grouping semantics)

    @staticmethod
    def init(slots: int, buffer_entries: int) -> "FrequencyTableState":
        from ..ops.hashing import FREQ_KEY_SENTINEL

        return FrequencyTableState(
            jnp.full(slots, FREQ_KEY_SENTINEL, dtype=jnp.uint64),
            jnp.zeros(slots, dtype=jnp.int64),
            jnp.zeros((), dtype=jnp.int64),
            jnp.zeros(buffer_entries, dtype=jnp.uint64),
            jnp.zeros((), dtype=jnp.int64),
            jnp.zeros((), dtype=jnp.int64),
            jnp.zeros((), dtype=jnp.int64),
            jnp.zeros((), dtype=jnp.int64),
            jnp.zeros((), dtype=jnp.int64),
        )

    def compacted(self) -> "FrequencyTableState":
        """Fold the raw buffer into the sorted table (buffer becomes
        empty); traced — both the in-pass overflow branch and ``merge``
        ride this."""
        from ..ops import freq_compact
        from ..ops.hashing import FREQ_KEY_SENTINEL

        sent = jnp.uint64(FREQ_KEY_SENTINEL)
        cap = self.buf.shape[0]
        slots = self.sorted_keys.shape[0]
        idx = jnp.arange(cap, dtype=jnp.int64)
        bkeys = jnp.where(idx < self.buf_fill, self.buf, sent)
        bcounts = (bkeys != sent).astype(jnp.int64)
        out_keys, out_counts, n_raw, kept, total = freq_compact(
            jnp.concatenate([self.sorted_keys, bkeys]),
            jnp.concatenate([self.sorted_counts, bcounts]),
            slots, sent,
        )
        return FrequencyTableState(
            out_keys, out_counts, jnp.minimum(n_raw, slots),
            jnp.zeros_like(self.buf), jnp.zeros_like(self.buf_fill),
            self.sent_rows,
            self.lost_groups + jnp.maximum(n_raw - slots, 0),
            self.lost_rows + (total - kept),
            self.num_rows,
        )

    def append_keys(
        self,
        keys: jnp.ndarray,
        n_sent: jnp.ndarray,
        n_rows: jnp.ndarray,
        assume_fits: bool = False,
    ) -> "FrequencyTableState":
        """Fold one batch of per-row group keys into the state (traced; the
        analyzer ``update``'s whole body). ``keys`` already carries the
        sentinel at masked/null positions AND at valid rows whose real key
        collided with it (those are counted via ``n_sent`` instead). The
        hot path is one memcpy-speed ``dynamic_update_slice`` append — no
        scatter, no sort.

        ``assume_fits=True`` is the RESIDENT trace: the planner proved the
        buffer covers every padded batch of the run, so no ``lax.cond`` is
        emitted at all — measured on CPU XLA the cond region forces the
        256MB buffer through region copies at ~0.4s/batch where the plain
        donated-carry append runs at memcpy speed (>250M rows/s). The
        conditional-compaction trace remains for runs whose rows exceed the
        buffer; its sort cost amortizes over ``buffer_entries / batch``
        batches."""
        import jax

        batch = keys.shape[0]
        cap = self.buf.shape[0]
        if batch > cap:
            raise ValueError(
                f"frequency-table buffer holds {cap} entries but the batch "
                f"carries {batch} rows; size buffer_entries >= the padded "
                "batch size (the runner guarantees this)"
            )

        def just_append(st: "FrequencyTableState") -> "FrequencyTableState":
            buf = jax.lax.dynamic_update_slice(st.buf, keys, (st.buf_fill,))
            return st.replace(buf=buf, buf_fill=st.buf_fill + batch)

        if assume_fits:
            appended = just_append(self)
        else:
            appended = jax.lax.cond(
                self.buf_fill + batch <= cap,
                just_append,
                lambda st: just_append(st.compacted()),
                self,
            )
        return appended.replace(
            sent_rows=appended.sent_rows + n_sent,
            num_rows=appended.num_rows + n_rows,
        )

    def merge(self, other: "FrequencyTableState") -> "FrequencyTableState":
        from ..ops import freq_compact
        from ..ops.hashing import FREQ_KEY_SENTINEL

        sent = jnp.uint64(FREQ_KEY_SENTINEL)
        a = self.compacted()
        b = other.compacted()
        slots = a.sorted_keys.shape[0]
        out_keys, out_counts, n_raw, kept, total = freq_compact(
            jnp.concatenate([a.sorted_keys, b.sorted_keys]),
            jnp.concatenate([a.sorted_counts, b.sorted_counts]),
            slots, sent,
        )
        return FrequencyTableState(
            out_keys, out_counts, jnp.minimum(n_raw, slots),
            jnp.zeros_like(a.buf), jnp.zeros_like(a.buf_fill),
            a.sent_rows + b.sent_rows,
            a.lost_groups + b.lost_groups + jnp.maximum(n_raw - slots, 0),
            a.lost_rows + b.lost_rows + (total - kept),
            a.num_rows + b.num_rows,
        )


@flax.struct.dataclass
class NumMatches:
    """Row-count state (reference `analyzers/Size.scala:23-29`)."""

    num_matches: jnp.ndarray

    @staticmethod
    def init() -> "NumMatches":
        return NumMatches(_i(0))

    def merge(self, other: "NumMatches") -> "NumMatches":
        return NumMatches(self.num_matches + other.num_matches)

    def metric_value(self) -> float:
        return float(self.num_matches)


@flax.struct.dataclass
class NumMatchesAndCount:
    """Ratio state (reference `analyzers/Analyzer.scala:438-449`)."""

    num_matches: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def init() -> "NumMatchesAndCount":
        return NumMatchesAndCount(_i(0), _i(0))

    def merge(self, other: "NumMatchesAndCount") -> "NumMatchesAndCount":
        return NumMatchesAndCount(
            self.num_matches + other.num_matches, self.count + other.count
        )

    def metric_value(self) -> float:
        count = float(self.count)
        if count == 0:
            return float("nan")
        return float(self.num_matches) / count


@flax.struct.dataclass
class MeanState:
    """(sum, count) (reference `analyzers/Mean.scala:25-35`)."""

    total: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def init() -> "MeanState":
        return MeanState(_f(0.0), _i(0))

    def merge(self, other: "MeanState") -> "MeanState":
        return MeanState(self.total + other.total, self.count + other.count)

    def metric_value(self) -> float:
        count = float(self.count)
        if count == 0:
            return float("nan")
        return float(self.total) / count


@flax.struct.dataclass
class SumState:
    """(sum) plus a count used only for emptiness detection
    (reference `analyzers/Sum.scala:25-33`)."""

    total: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def init() -> "SumState":
        return SumState(_f(0.0), _i(0))

    def merge(self, other: "SumState") -> "SumState":
        return SumState(self.total + other.total, self.count + other.count)

    def metric_value(self) -> float:
        return float(self.total)


@flax.struct.dataclass
class MinState:
    """(reference `analyzers/Minimum.scala:25-33`)."""

    min_value: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def init() -> "MinState":
        # NaN is the identity (top) element of the NaN-largest min order the
        # reference uses (Spark TypeUtils: reals < +inf < NaN); see
        # `min_nan_largest` below
        return MinState(_f(np.nan), _i(0))

    def merge(self, other: "MinState") -> "MinState":
        return MinState(
            min_nan_largest(self.min_value, other.min_value),
            self.count + other.count,
        )

    def metric_value(self) -> float:
        return float(self.min_value)


@flax.struct.dataclass
class MaxState:
    """(reference `analyzers/Maximum.scala:25-33`)."""

    max_value: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def init() -> "MaxState":
        return MaxState(_f(-np.inf), _i(0))

    def merge(self, other: "MaxState") -> "MaxState":
        return MaxState(jnp.maximum(self.max_value, other.max_value), self.count + other.count)

    def metric_value(self) -> float:
        return float(self.max_value)


@flax.struct.dataclass
class StandardDeviationState:
    """Welford/Chan parallel-merge moments (n, avg, m2)
    (reference `analyzers/StandardDeviation.scala:25-50`)."""

    n: jnp.ndarray
    avg: jnp.ndarray
    m2: jnp.ndarray

    @staticmethod
    def init() -> "StandardDeviationState":
        return StandardDeviationState(_f(0.0), _f(0.0), _f(0.0))

    def merge(self, other: "StandardDeviationState") -> "StandardDeviationState":
        n = self.n + other.n
        safe_n = jnp.where(n == 0, 1.0, n)
        delta = other.avg - self.avg
        avg = jnp.where(n == 0, 0.0, (self.avg * self.n + other.avg * other.n) / safe_n)
        m2 = self.m2 + other.m2 + delta * delta * self.n * other.n / safe_n
        return StandardDeviationState(n, avg, jnp.where(n == 0, 0.0, m2))

    def metric_value(self) -> float:
        # host math only: a jnp op on a fetched numpy state would dispatch a
        # device program (one ~100ms round trip per metric on tunnel links)
        n = float(self.n)
        if n == 0:
            return float("nan")
        return float(np.sqrt(float(self.m2) / n))


@flax.struct.dataclass
class CorrelationState:
    """Pairwise co-moment accumulators (n, xAvg, yAvg, ck, xMk, yMk)
    (reference `analyzers/Correlation.scala:26-60`)."""

    n: jnp.ndarray
    x_avg: jnp.ndarray
    y_avg: jnp.ndarray
    ck: jnp.ndarray
    x_mk: jnp.ndarray
    y_mk: jnp.ndarray

    @staticmethod
    def init() -> "CorrelationState":
        # distinct arrays: a shared buffer would be donated twice under jit
        return CorrelationState(_f(0.0), _f(0.0), _f(0.0), _f(0.0), _f(0.0), _f(0.0))

    def merge(self, other: "CorrelationState") -> "CorrelationState":
        n = self.n + other.n
        safe_n = jnp.where(n == 0, 1.0, n)
        dx = other.x_avg - self.x_avg
        dy = other.y_avg - self.y_avg
        frac = self.n * other.n / safe_n
        x_avg = jnp.where(n == 0, 0.0, (self.x_avg * self.n + other.x_avg * other.n) / safe_n)
        y_avg = jnp.where(n == 0, 0.0, (self.y_avg * self.n + other.y_avg * other.n) / safe_n)
        ck = self.ck + other.ck + dx * dy * frac
        x_mk = self.x_mk + other.x_mk + dx * dx * frac
        y_mk = self.y_mk + other.y_mk + dy * dy * frac
        return CorrelationState(
            n, x_avg, y_avg, jnp.where(n == 0, 0.0, ck), jnp.where(n == 0, 0.0, x_mk),
            jnp.where(n == 0, 0.0, y_mk)
        )

    def metric_value(self) -> float:
        if float(self.n) == 0:
            return float("nan")
        with np.errstate(divide="ignore", invalid="ignore"):
            return float(
                float(self.ck) / np.sqrt(float(self.x_mk) * float(self.y_mk))
            )


@flax.struct.dataclass
class DataTypeHistogram:
    """Counts of inferred value types [null, fractional, integral, boolean,
    string] (reference `analyzers/DataType.scala:32-96`)."""

    counts: jnp.ndarray  # int64[5]

    NULL_POS: int = flax.struct.field(pytree_node=False, default=0)

    @staticmethod
    def init() -> "DataTypeHistogram":
        return DataTypeHistogram(jnp.zeros(5, dtype=COUNT_DTYPE))

    def merge(self, other: "DataTypeHistogram") -> "DataTypeHistogram":
        return DataTypeHistogram(self.counts + other.counts)


@flax.struct.dataclass
class ApproxCountDistinctState:
    """HLL++ registers, unpacked int32[512] (reference packs them into 52
    longs, `analyzers/ApproxCountDistinct.scala:26-40`; see
    `deequ_tpu/ops/hll.py` for the packed-format converters)."""

    registers: jnp.ndarray  # int32[512]

    @staticmethod
    def init() -> "ApproxCountDistinctState":
        from ..ops.hll import M

        return ApproxCountDistinctState(jnp.zeros(M, dtype=jnp.int32))

    def merge(self, other: "ApproxCountDistinctState") -> "ApproxCountDistinctState":
        return ApproxCountDistinctState(jnp.maximum(self.registers, other.registers))

    def metric_value(self) -> float:
        from ..ops.hll import estimate_cardinality

        return estimate_cardinality(np.asarray(self.registers))


def to_host(state: Any) -> Any:
    """Bring a device state pytree back as numpy (for persistence/finalize).
    Uses device_get so all leaves copy in one batched round-trip."""
    import jax

    return jax.device_get(state)


#: State classes whose semigroup merge with the IDENTITY state is
#: bit-TRANSPARENT: ``merge(init(), s) == s`` leaf-for-leaf at the bit
#: level, by construction of the merge formula —
#:
#: - integer adds against 0 (NumMatches, NumMatchesAndCount,
#:   DataTypeHistogram, FrequencyCountsState counts/num_rows) are exact;
#: - float adds against +0.0 (MeanState/SumState totals) return the other
#:   operand's bits for every finite/NaN value;
#: - ``min_nan_largest(NaN, x) == x`` and ``max(-inf, x) == x`` exactly
#:   (MinState/MaxState);
#: - elementwise ``maximum(0, registers) == registers`` for the
#:   non-negative HLL registers (ApproxCountDistinctState).
#:
#: The streaming fast path (service.coalesce) relies on this: a
#: micro-batch's host-kernel partial IS the batch's folded state — no
#: identity fold needs to run, on host or device — and merging it into the
#: session's persisted states reproduces the engine host tier bit-exactly.
#: StandardDeviationState / CorrelationState are deliberately ABSENT: their
#: merges recompute ``avg = (avg*n)/n`` against the identity, which rounds
#: for ~10% of doubles (measured), so those states must fold through a real
#: program — the crossover router sends their batteries to the coalesced
#: device path instead.
IDENTITY_TRANSPARENT_STATES = frozenset({
    NumMatches,
    NumMatchesAndCount,
    MeanState,
    SumState,
    MinState,
    MaxState,
    DataTypeHistogram,
    ApproxCountDistinctState,
    FrequencyCountsState,
})


def identity_merge_transparent(state_cls: type) -> bool:
    """Whether ``merge(init(), s)`` provably returns ``s``'s exact bits for
    this state class (see :data:`IDENTITY_TRANSPARENT_STATES`)."""
    return state_cls in IDENTITY_TRANSPARENT_STATES


def _np(x) -> np.ndarray:
    # np.asarray is zero-copy for numpy leaves and completes the transfer
    # for the occasional device-resident leaf a mixed history left behind
    return np.asarray(x)


def host_merge(a: Any, b: Any) -> Any:
    """Device-free semigroup merge for the IDENTITY-TRANSPARENT state
    classes: the same formulas as each class's jnp ``merge``, evaluated
    with numpy on host leaves — every operation is a single IEEE scalar
    (or elementwise integer) op, so the result is bit-identical to the
    compiled merge, with ZERO device dispatches. This is the streaming
    fast path's merge: at thousands of folds per second the jit-dispatch
    + device_get round trip of `merge_states_batched` was ~40% of the
    whole fold (measured), for states that are a handful of scalars.

    Raises ``TypeError`` for classes outside the transparent set — their
    merges (Welford/co-moment recombinations) are only bit-reproducible
    through the one compiled program every path shares."""
    cls = type(a)
    if cls is not type(b):
        raise TypeError(f"cannot host-merge {cls.__name__} with {type(b).__name__}")
    if cls is NumMatches:
        return NumMatches(_np(a.num_matches) + _np(b.num_matches))
    if cls is NumMatchesAndCount:
        return NumMatchesAndCount(
            _np(a.num_matches) + _np(b.num_matches),
            _np(a.count) + _np(b.count),
        )
    if cls is MeanState:
        return MeanState(
            _np(a.total) + _np(b.total), _np(a.count) + _np(b.count)
        )
    if cls is SumState:
        return SumState(
            _np(a.total) + _np(b.total), _np(a.count) + _np(b.count)
        )
    if cls is MinState:
        av, bv = _np(a.min_value), _np(b.min_value)
        # NaN-largest order, the same branch structure as min_nan_largest
        mn = bv if np.isnan(av) else (av if np.isnan(bv) else np.minimum(av, bv))
        return MinState(mn, _np(a.count) + _np(b.count))
    if cls is MaxState:
        return MaxState(
            np.maximum(_np(a.max_value), _np(b.max_value)),
            _np(a.count) + _np(b.count),
        )
    if cls is DataTypeHistogram:
        return DataTypeHistogram(_np(a.counts) + _np(b.counts))
    if cls is ApproxCountDistinctState:
        return ApproxCountDistinctState(
            np.maximum(_np(a.registers), _np(b.registers))
        )
    if cls is FrequencyCountsState:
        return FrequencyCountsState(
            _np(a.counts) + _np(b.counts),
            _np(a.num_rows) + _np(b.num_rows),
        )
    raise TypeError(
        f"{cls.__name__} is not identity-merge transparent; merge it "
        "through the compiled path"
    )
