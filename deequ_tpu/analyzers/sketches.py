"""Sketch-backed analyzers: approximate distinct counts and quantiles.

The reference implements these as Spark ImperativeAggregate/UDAF kernels with
per-row imperative buffer updates (`analyzers/catalyst/*.scala`); here the
sketch updates are vectorized fixed-shape device ops that join the same fused
single-pass scan as every other analyzer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ..data import Schema
from ..expr import Predicate
from ..metrics import Entity
from .base import (
    FeatureSpec,
    Preconditions,
    StandardScanShareableAnalyzer,
    hll_feature,
    mask_feature,
    predicate_feature,
    rows_feature,
)
from .states import ApproxCountDistinctState


@dataclass(frozen=True)
class ApproxCountDistinct(StandardScanShareableAnalyzer[ApproxCountDistinctState]):
    """Approximate distinct count via HLL++ (relativeSD=0.05, p=9, 512
    registers), matching the reference's accuracy envelope and hash (xxhash64
    seed 42) bit-for-bit (reference `analyzers/ApproxCountDistinct.scala:
    26-64`, kernel `analyzers/catalyst/StatefulHyperloglogPlus.scala:89-139`).

    Device work per batch: one segment_max over 512 registers; merge is an
    elementwise register max (pmax-compatible over a mesh axis).
    """

    column: str = ""
    where: Optional[Predicate] = None
    name: str = field(default="ApproxCountDistinct", init=False)

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.has_column(self.column)]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), mask_feature(self.column), hll_feature(self.column)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    def init_state(self) -> ApproxCountDistinctState:
        return ApproxCountDistinctState.init()

    def update(self, state, features):
        from ..ops.hll import M

        pairs = features[hll_feature(self.column).key]
        idx, pw = pairs[0], pairs[1]
        mask = self._row_mask(features) & features[mask_feature(self.column).key]
        # masked-out rows contribute 0, which never wins a max against the
        # (non-negative) register values
        contrib = jnp.where(mask, pw, 0)
        batch_regs = jax.ops.segment_max(
            contrib, idx, num_segments=M, indices_are_sorted=False
        )
        batch_regs = jnp.maximum(batch_regs, 0).astype(jnp.int32)
        return ApproxCountDistinctState(jnp.maximum(state.registers, batch_regs))

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        # on empty data the estimate is 0.0, matching the reference where the
        # HLL agg buffer always exists (`ApproxCountDistinct.scala:49-56`)
        return state.metric_value()
