"""Sketch-backed analyzers: approximate distinct counts and quantiles.

The reference implements these as Spark ImperativeAggregate/UDAF kernels with
per-row imperative buffer updates (`analyzers/catalyst/*.scala`); here the
sketch updates are vectorized fixed-shape device ops that join the same fused
single-pass scan as every other analyzer.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data import Schema
from ..exceptions import EmptyStateException, IllegalAnalyzerParameterException
from ..expr import Predicate
from ..metrics import (
    BucketDistribution,
    BucketValue,
    Entity,
    Failure,
    KeyedDoubleMetric,
    KLLMetric,
    Success,
    metric_from_empty,
)
from ..ops.kll import (
    DEFAULT_SHRINKING_FACTOR,
    DEFAULT_SKETCH_SIZE,
    KLLSketchState,
    MAXIMUM_ALLOWED_DETAIL_BINS,
    compactor_buffers,
    kll_init,
    kll_merge,
    kll_update,
)
from ..ops.kll_host import HostKLL
from .base import (
    FeatureSpec,
    Preconditions,
    ScanShareableAnalyzer,
    StandardScanShareableAnalyzer,
    hll_feature,
    mask_feature,
    numeric_feature,
    predicate_feature,
    rows_feature,
)
from .states import ApproxCountDistinctState


@dataclass(frozen=True)
class ApproxCountDistinct(StandardScanShareableAnalyzer[ApproxCountDistinctState]):
    """Approximate distinct count via HLL++ (relativeSD=0.05, p=9, 512
    registers), matching the reference's accuracy envelope and hash (xxhash64
    seed 42) bit-for-bit (reference `analyzers/ApproxCountDistinct.scala:
    26-64`, kernel `analyzers/catalyst/StatefulHyperloglogPlus.scala:89-139`).

    Device work per batch: a chunked one-hot compare/max scan over the 512
    registers (see ``update`` — TPU scatters and sorts both lose to it);
    merge is an elementwise register max (pmax-compatible over a mesh axis).
    """

    column: str = ""
    where: Optional[Predicate] = None
    name: str = field(default="ApproxCountDistinct", init=False)

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.has_column(self.column)]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), mask_feature(self.column), hll_feature(self.column)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    def init_state(self) -> ApproxCountDistinctState:
        return ApproxCountDistinctState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> ApproxCountDistinctState:
        from ..data import ColumnKind
        from ..native import native_block_hll, native_block_hll_strings
        from ..ops.hashing import DEFAULT_SEED

        col = ctx.batch.column(self.column)
        mask = ctx.column_mask(self, self.column)
        if col.has_dictionary and col.codes is not None:
            # dictionary column: hash the DISTINCT values once (cached in
            # col.aux across batches), then max-scatter only the entries
            # present in this batch — O(rows) code counting + O(dict) scatter
            from ..ops.hll import M, hll_features
            from ..runners.features import dict_entry_hashes

            pairs = col.aux.get("hll_pairs")
            if pairs is None:
                # derives from the shared distinct-value hash pass
                pairs = hll_features(dict_entry_hashes(col))
                col.aux["hll_pairs"] = pairs
            num_cats = col.num_categories
            if not num_cats:
                return ApproxCountDistinctState(np.zeros(M, dtype=np.int32))
            aux = col.aux
            regs_full = aux.get("hll_regs_full")
            if regs_full is None:
                # per-DATASET artifacts: registers over the whole
                # dictionary, plus a register-sorted view of the (idx, pw)
                # pairs so per-batch folds are a vectorized reduceat, not a
                # serialized np.maximum.at ufunc loop (~2.5x at 200k
                # categories)
                idx, pw = pairs[0][:num_cats], pairs[1][:num_cats]
                regs_full = np.zeros(M, dtype=np.int32)
                np.maximum.at(regs_full, idx, pw)
                perm = np.argsort(idx, kind="stable")
                aux["hll_regs_full"] = regs_full
                aux["hll_perm"] = perm
                aux["hll_pw_sorted"] = pw[perm]
                aux["hll_starts"] = np.searchsorted(idx[perm], np.arange(M))
            if self.where is None and ctx.run_token is not None:
                # cross-batch skip: within one pass, registers are a MAX
                # fold over batch partials, so an entry only needs to reach
                # the fold through the FIRST batch that sees it — later
                # batches contribute registers of NEW entries only, and once
                # every dictionary entry has been seen the partial is the
                # O(1) "saturated" zero state (a 1M-entry comment dictionary
                # used to cost O(dict) per batch FOREVER; small dictionaries
                # saturate after one batch). The token keys the seen-set to
                # the enclosing pass. The lock only guards the epoch swap:
                # concurrent workers marking entries can at worst duplicate
                # a contribution (max-fold idempotent), never drop one — a
                # batch only SKIPS an entry another batch of the same epoch
                # already marked, and that batch contributed it.
                import threading

                lock = aux.setdefault("_hll_lock", threading.Lock())
                with lock:
                    if aux.get("hll_seen_full") is ctx.run_token:
                        return ApproxCountDistinctState(np.zeros(M, dtype=np.int32))
                    tok, seen = aux.get("hll_seen", (None, None))
                    if tok is not ctx.run_token:
                        seen = np.zeros(num_cats + 1, dtype=bool)
                        seen[num_cats] = True
                        aux["hll_seen"] = (ctx.run_token, seen)
                idx, pw = pairs[0][:num_cats], pairs[1][:num_cats]
                if num_cats > (1 << 16):
                    # large dictionary: an O(rows) seen-lookup decides
                    # cheaper than an O(rows + cats) presence bincount
                    codes = np.where(col.codes < num_cats, col.codes, num_cats)
                    unseen = ~seen[codes]
                    n_unseen = int(np.count_nonzero(unseen))
                    if n_unseen == 0:
                        return ApproxCountDistinctState(
                            np.zeros(M, dtype=np.int32)
                        )
                    if n_unseen <= len(codes) // 64:
                        # near-saturation: tiny unique + sparse scatter-max
                        new_codes = np.unique(codes[unseen])
                        seen[new_codes] = True
                        if seen.all():
                            aux["hll_seen_full"] = ctx.run_token
                        regs = np.zeros(M, dtype=np.int32)
                        np.maximum.at(regs, idx[new_codes], pw[new_codes])
                        return ApproxCountDistinctState(regs)
                # warm-up shape: presence bincount, fold only NEW entries
                counts = (
                    ctx.dict_code_counts(self.column) if ctx.row_mask_all() else None
                )
                if counts is None:
                    safe = np.where(col.codes < num_cats, col.codes, num_cats)
                    counts = np.bincount(safe[mask], minlength=num_cats + 1)
                present = counts[:num_cats] > 0
                target = present & ~seen[:num_cats]
                seen[:num_cats] |= present
                if seen.all():
                    aux["hll_seen_full"] = ctx.run_token
                if not target.any():
                    return ApproxCountDistinctState(np.zeros(M, dtype=np.int32))
                if target.all():
                    return ApproxCountDistinctState(regs_full.copy())
                return ApproxCountDistinctState(
                    self._regs_for_target(aux, pairs, target, num_cats)
                )
            shared = (
                ctx.dict_code_counts(self.column) if self.where is None else None
            )
            if shared is not None:
                # the shared one-pass native count (sentinel slot = masked)
                counts = shared[:num_cats]
            else:
                counts = np.bincount(
                    col.codes[mask], minlength=num_cats + 1
                )[:num_cats]
            present = counts > 0
            if present.all():
                # every dictionary entry occurs in this batch: the cached
                # full-dictionary registers ARE the answer (copied — states
                # must stay immutable downstream)
                return ApproxCountDistinctState(regs_full.copy())
            return ApproxCountDistinctState(
                self._regs_for_target(aux, pairs, present, num_cats)
            )
        return self._host_partial_plain(col, mask)

    def _regs_for_target(self, aux, pairs, target: np.ndarray, num_cats: int):
        """Registers over the dictionary entries selected by ``target`` —
        sparse scatter-max for few entries, register-sorted reduceat (the
        cached per-dataset view) otherwise."""
        from ..ops.hll import M

        idx, pw = pairs[0][:num_cats], pairs[1][:num_cats]
        n_target = int(np.count_nonzero(target))
        if n_target * 8 < num_cats:
            ti = np.flatnonzero(target)
            regs = np.zeros(M, dtype=np.int32)
            np.maximum.at(regs, idx[ti], pw[ti])
            return regs
        perm = aux["hll_perm"]
        pw_eff = np.where(target[perm], aux["hll_pw_sorted"], -1)
        starts = aux["hll_starts"]
        nexts = np.append(starts[1:], num_cats)
        # a trailing -1 sentinel keeps every starts value (up to
        # num_cats inclusive, for empty trailing registers) a valid
        # reduceat index WITHOUT clamping — clamping to num_cats-1
        # silently cut the last pair out of the topmost occupied
        # register's segment whenever any register above it was empty
        pw_ext = np.append(pw_eff, np.int32(-1))
        seg = np.maximum.reduceat(pw_ext, starts)
        seg = np.where(nexts > starts, seg, -1)
        return np.maximum(seg, 0).astype(np.int32)

    def _host_partial_plain(self, col, mask) -> ApproxCountDistinctState:
        from ..data import ColumnKind
        from ..native import native_block_hll, native_block_hll_strings
        from ..ops.hashing import DEFAULT_SEED

        if col.kind == ColumnKind.STRING:
            src = col.string_source
            if native_block_hll_strings is not None and (
                not isinstance(src, np.ndarray) or src.dtype == object
            ):
                regs = native_block_hll_strings(src, mask, DEFAULT_SEED)
                return ApproxCountDistinctState(regs.astype(np.int32))
        elif native_block_hll is not None and (
            col.kind.is_numeric or col.kind == ColumnKind.BOOLEAN
        ):
            vals = col.values
            if vals.dtype == np.bool_ or (
                np.issubdtype(vals.dtype, np.integer) and vals.dtype != np.int64
            ):
                vals = vals.astype(np.int64)
            if np.issubdtype(vals.dtype, np.number):
                regs = native_block_hll(vals, mask, DEFAULT_SEED)
                return ApproxCountDistinctState(regs.astype(np.int32))
        # numpy fallback: hash + scatter-max
        from ..ops.hashing import hash_column
        from ..ops.hll import M, hll_features

        pairs = hll_features(hash_column(col.values, col.mask, col.kind))
        regs = np.zeros(M, dtype=np.int32)
        np.maximum.at(regs, pairs[0][mask], pairs[1][mask])
        return ApproxCountDistinctState(regs)

    def update(self, state, features):
        from ..ops.hll import M

        packed = features[hll_feature(self.column).key]
        # wire format: uint16 (idx << 6) | pw — 2 bytes/row on the host feed
        # (see ops/hll.hll_pack_features); nulls arrive pre-packed as 0
        mask = self._row_mask(features) & features[mask_feature(self.column).key]
        # Per-register max via a CHUNKED ONE-HOT compare/max scan — neither
        # a scatter (segment_max lowers to a serialized loop on TPU, ~11ms
        # per 1M-row batch) nor a sort (~1.3ms): each scan step broadcasts a
        # (chunk, 1) key column against the (1, 512) register ids and
        # max-reduces the chunk axis, keeping the (chunk x 512) compare tile
        # in VMEM — measured 0.34ms per 1M rows, identical registers.
        # Within one register group the key max IS (idx<<6 | max pw), so
        # the masked-out rows' key 0 (idx 0, pw 0) never wins a max.
        from ..ops import chunked_key_fold

        keys = jnp.where(mask, packed, 0).astype(jnp.int32)
        regs = jnp.arange(M, dtype=jnp.int32)

        def fold_chunk(acc, row):
            hit = (row[:, None] >> 6) == regs[None, :]
            return jnp.maximum(acc, jnp.max(jnp.where(hit, row[:, None], 0), axis=0))

        acc = chunked_key_fold(keys, 0, jnp.zeros(M, jnp.int32), fold_chunk)
        batch_regs = (acc & 63).astype(jnp.int32)
        return ApproxCountDistinctState(jnp.maximum(state.registers, batch_regs))

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        # on empty data the estimate is 0.0, matching the reference where the
        # HLL agg buffer always exists (`ApproxCountDistinct.scala:49-56`)
        return state.metric_value()


# ---------------------------------------------------------------------------
# KLL-backed quantile analyzers
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class KLLParameters:
    """(reference `analyzers/KLLSketch.scala:82`)."""

    sketch_size: int = DEFAULT_SKETCH_SIZE
    shrinking_factor: float = DEFAULT_SHRINKING_FACTOR
    number_of_buckets: int = MAXIMUM_ALLOWED_DETAIL_BINS


class _KLLBackedAnalyzer(ScanShareableAnalyzer[KLLSketchState, KLLMetric]):
    """Shared plumbing for analyzers folding a column into a KLL sketch.
    Subclasses define ``_sketch_size`` and the metric finalization."""

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def _sketch_size(self) -> int:
        raise NotImplementedError

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [
            Preconditions.has_column(self.column),
            Preconditions.is_numeric(self.column),
        ]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), numeric_feature(self.column), mask_feature(self.column)]
        where = getattr(self, "where", None)
        if where is not None:
            specs.append(predicate_feature(where))
        return specs

    def init_state(self) -> KLLSketchState:
        return kll_init(self._sketch_size())

    def update(self, state, features):
        v = features[numeric_feature(self.column).key]
        mask = self._row_mask(features) & features[mask_feature(self.column).key]
        return kll_update(state, v, mask)

    def merge(self, a, b):
        return kll_merge(a, b)

    def metric_leaves(self):
        # KLLSketchState flattens as (items, sizes, parity, ticks, count,
        # g_min, g_max); the metric (HostKLL ranks/quantiles + the
        # compactor-buffer payload) reads everything EXCEPT the compaction
        # parity offsets and the update tick counter, which only steer
        # FUTURE folds/merges — the slim fetch drops them.
        return (0, 1, 4, 5, 6)

    supports_host_partial = True

    def host_partial(self, ctx):
        from ..config import ACC_DTYPE, COUNT_DTYPE
        from ..native import native_block_kll_pick, native_block_kll_sample

        col = ctx.batch.column(self.column)
        mask = ctx.column_mask(self, self.column)
        vals = col.values if np.issubdtype(col.values.dtype, np.number) else col.numeric_f64()
        k = self._sketch_size()
        stats = ctx.peek_block_stats(self, self.column)
        if stats is not None and native_block_kll_pick is not None:
            # a stats analyzer on the same column+mask already counted the
            # non-NaN values and found min/max: skip the sampler's counting
            # sweep (one less pass over the column's memory)
            nv = int(stats[5])
            if nv == 0:
                # identity partial — same (4k,) width as every sampler path
                items, m, h, mn, mx = (
                    np.full(4 * k, np.inf), 0, 0, np.inf, -np.inf
                )
            else:
                items, m, h = native_block_kll_pick(
                    vals, mask, k, ctx.batch_index, nv
                )
                mn, mx = float(stats[2]), float(stats[6])
        elif native_block_kll_sample is not None:
            items, m, h, nv, mn, mx = native_block_kll_sample(
                vals, mask, k, ctx.batch_index
            )
        else:
            items, m, h, nv, mn, mx = _np_kll_sample(vals, mask, k, ctx.batch_index)
        return (
            items.astype(np.float64),
            np.int32(m),
            np.int32(h),
            np.asarray(nv, dtype=COUNT_DTYPE),
            np.asarray(mn, dtype=ACC_DTYPE),
            np.asarray(mx, dtype=ACC_DTYPE),
        )

    def ingest_partial(self, state, partial):
        from ..ops.kll import kll_ingest_sampled

        items, m, h, nv, mn, mx = partial
        return kll_ingest_sampled(state, items, m, h, nv, mn, mx)


def _np_kll_sample(values: np.ndarray, mask: np.ndarray, k: int, tick: int):
    """numpy fallback for native block_kll_sample (same sampler semantics,
    incl. the up-to-two-levels-denser stride policy — compaction reduces the
    extra items with deterministic error instead of sampling variance)."""
    k = max(int(k), 1)  # non-positive sketch size must not hang the stride loop
    v = np.asarray(values, dtype=np.float64)
    ok = np.asarray(mask, dtype=bool) & ~np.isnan(v)
    vv = v[ok]
    nv = int(vv.size)
    items = np.full(4 * k, np.inf, dtype=np.float64)
    if nv == 0:
        return items, 0, 0, 0, np.inf, -np.inf
    h = 0
    stride = 1
    while stride * k < nv:
        stride <<= 1
        h += 1
    dense = 2 if h >= 2 else h
    h -= dense
    stride >>= dense
    cap = k << dense
    # batch index XOR valid-count mixing, bit-identical to the native
    # block_kll_sample_f64 (periodic streams must not phase-lock the stride;
    # uint32 wraparound is the intended mixing, hence the errstate guard)
    with np.errstate(over="ignore"):
        r = (
            (np.uint32(tick) * np.uint32(2654435761))
            ^ (np.uint32(nv) * np.uint32(2246822519))
        ) >> np.uint32(7)
    offset = int(r % np.uint32(stride))
    picked = np.sort(vv[offset::stride])[:cap]
    if dense == 2 and picked.size > 1:
        # one in-sampler compaction: every 2nd of the sorted dense pick,
        # weight doubles — keeps the dense sample's rank accuracy while
        # emitting <= 2k items (the state-buffer occupancy bound)
        parity = int((r >> np.uint32(8)) & np.uint32(1))
        picked = picked[parity::2]
        h += 1
    items[: picked.size] = picked
    return items, int(picked.size), h, nv, float(vv.min()), float(vv.max())


@dataclass(frozen=True)
class KLLSketch(_KLLBackedAnalyzer):
    """Quantile sketch of a numeric column, reported as an equi-width
    BucketDistribution over [globalMin, globalMax]
    (reference `analyzers/KLLSketch.scala:42-176`)."""

    column: str = ""
    kll_parameters: Optional[KLLParameters] = None
    where: Optional[Predicate] = None
    name: str = field(default="KLLSketch", init=False)

    @property
    def params(self) -> KLLParameters:
        return self.kll_parameters or KLLParameters()

    def _sketch_size(self) -> int:
        return self.params.sketch_size

    def preconditions(self) -> List[Callable[[Schema], None]]:
        def param_check(schema: Schema) -> None:
            if self.params.number_of_buckets > MAXIMUM_ALLOWED_DETAIL_BINS:
                raise IllegalAnalyzerParameterException(
                    f"Cannot return KLL Sketch related values for more than "
                    f"{MAXIMUM_ALLOWED_DETAIL_BINS} values"
                )
            if self.params.sketch_size < 1:
                raise IllegalAnalyzerParameterException(
                    f"KLL sketch size must be positive, got {self.params.sketch_size}"
                )

        return [param_check] + super().preconditions()

    def compute_metric_from(self, state: Optional[KLLSketchState]) -> KLLMetric:
        if state is None or int(state.count) == 0:
            return KLLMetric(
                Entity.COLUMN,
                self.name,
                self.column,
                Failure(
                    EmptyStateException(
                        f"Empty state for analyzer {self.name} on {self.column}, "
                        "all input values were NULL."
                    )
                ),
            )
        try:
            sketch = HostKLL.from_state(state)
            start = float(state.g_min)
            end = float(state.g_max)
            nb = self.params.number_of_buckets
            count = int(state.count)
            # bucket i covers (low_i, high_i]; the last bucket includes its
            # upper bound (reference `analyzers/KLLSketch.scala:136-146`).
            # The batch pre-collapse drops remainder items (n mod stride), so
            # the sketch's total weight can drift slightly below the exact
            # value count; scale the cumulative ranks so bucket counts
            # telescope to EXACTLY `count`, like the reference sketch whose
            # compactions preserve total weight (`NonSampleCompactor.scala:
            # 29-69`).
            bounds = [start + (end - start) * i / nb for i in range(nb + 1)]
            raw = [sketch.rank_exclusive(b) for b in bounds[:-1]]
            # anchor the ends at 0 and the FULL sketch weight, not at
            # rank(g_min)/rank(g_max): f32-quantized items can round a hair
            # past either f64 extreme and must still land in the end buckets
            raw[0] = 0
            raw.append(sketch.total_weight)
            tw = sketch.total_weight
            scale = (count / tw) if tw else 0.0
            cum = [int(np.floor(r * scale + 0.5)) for r in raw]
            buckets = [
                BucketValue(bounds[i], bounds[i + 1], cum[i + 1] - cum[i])
                for i in range(nb)
            ]
            dist = BucketDistribution(
                buckets,
                [self.params.shrinking_factor, float(self._sketch_size())],
                compactor_buffers(state),
            )
            return KLLMetric(Entity.COLUMN, self.name, self.column, Success(dist))
        except Exception as exc:  # noqa: BLE001
            return self.to_failure_metric(exc)

    def to_failure_metric(self, exception: BaseException) -> KLLMetric:
        from ..exceptions import wrap_if_necessary

        return KLLMetric(
            Entity.COLUMN, self.name, self.column, Failure(wrap_if_necessary(exception))
        )


def _sketch_size_for_error(relative_error: float) -> int:
    """Sketch size giving (empirically validated) rank error well inside
    ``relative_error``. The reference uses a Greenwald-Khanna digest with
    accuracy 1/relativeError (`analyzers/catalyst/DeequFunctions.scala:
    65-77`); KLL-backed needs O(1/eps) space for the same bound."""

    return max(256, int(math.ceil(4.0 / max(relative_error, 1e-4))))


def _check_quantile(q: float) -> None:
    if not 0.0 <= q <= 1.0:
        raise IllegalAnalyzerParameterException(
            "Quantile parameter must be in the closed interval [0, 1]. "
            f"Currently, the value is: {q}!"
        )


def _check_relative_error(relative_error: float) -> None:
    """The reference admits relativeError=0 as 'exact' GK mode
    (`ApproxQuantiles.scala:30`); a KLL sketch cannot be exact in bounded
    memory, so ``relative_error=0.0`` here routes the analyzer to a HOST
    full-sort accumulator (see :class:`ExactQuantileState`) whose result
    matches ``numpy.quantile`` exactly at O(n) host memory. Errors in
    (0, 1] stay KLL-backed, with 1e-4 as the smallest honored error."""
    if not 0.0 <= relative_error <= 1.0:
        raise IllegalAnalyzerParameterException(
            "Relative error parameter must be in the interval [0, 1]. "
            f"Currently, the value is: {relative_error}!"
        )


@dataclass(frozen=True)
class ExactQuantileState:
    """Host accumulator for ``relative_error=0.0`` (the reference's "exact"
    GK mode, `ApproxQuantiles.scala:30`): chunks of the column's non-null,
    non-NaN values, concatenated and full-sorted at metric time so the
    result is bit-identical to ``numpy.quantile`` (linear interpolation).
    Memory is O(values retained) — the documented price of exactness; the
    merge is chunk-list concatenation, so in-memory partition states
    aggregate like any other semigroup state. NOT registered with the
    state-persistence codec: persisting raw column values as "state"
    defeats the sketch contract, and ``save_states_with`` on an exact
    analyzer degrades to a typed failure metric naming the unregistered
    type."""

    chunks: Tuple[np.ndarray, ...] = ()

    def add(self, values: np.ndarray) -> "ExactQuantileState":
        return ExactQuantileState(
            self.chunks + (np.asarray(values, dtype=np.float64),)
        )

    def merge(self, other: "ExactQuantileState") -> "ExactQuantileState":
        return ExactQuantileState(self.chunks + other.chunks)

    @property
    def count(self) -> int:
        return int(sum(c.size for c in self.chunks))

    def values(self) -> np.ndarray:
        if not self.chunks:
            return np.empty(0, dtype=np.float64)
        return np.concatenate(self.chunks)


class _ExactQuantileMode:
    """Exact-mode plumbing shared by ApproxQuantile(+s): with
    ``relative_error == 0.0`` the analyzer leaves the fused scan
    (``host_exclusive``) and accumulates raw values host-side through the
    shared pass — still ONE pass over the data, like every other
    accumulator."""

    @property
    def host_exclusive(self) -> bool:
        return self.relative_error == 0.0

    def host_init(self) -> ExactQuantileState:
        return ExactQuantileState()

    def host_update(self, state: ExactQuantileState, batch) -> ExactQuantileState:
        col = batch.column(self.column)
        mask = batch.row_mask & col.mask
        if self.where is not None:
            from ..expr import evaluate_predicate
            from ..runners.features import _predicate_columns

            mask = mask & evaluate_predicate(
                self.where, _predicate_columns(batch), len(batch.row_mask)
            )
        vals = (
            col.values
            if np.issubdtype(col.values.dtype, np.number)
            else col.numeric_f64()
        )
        v = np.asarray(vals, dtype=np.float64)[mask]
        v = v[~np.isnan(v)]
        return state.add(v) if v.size else state

    def merge(self, a, b):
        if isinstance(a, ExactQuantileState) or isinstance(b, ExactQuantileState):
            return a.merge(b)
        return kll_merge(a, b)


@dataclass(frozen=True)
class ApproxQuantile(
    _ExactQuantileMode, _KLLBackedAnalyzer, StandardScanShareableAnalyzer[KLLSketchState]
):
    """Approximate single quantile (reference `analyzers/ApproxQuantile.scala:
    28-103`, default relativeError 0.01 at `:49`), KLL-backed;
    ``relative_error=0.0`` selects the exact host full-sort mode."""

    column: str = ""
    quantile: float = 0.5
    relative_error: float = 0.01
    where: Optional[Predicate] = None
    name: str = field(default="ApproxQuantile", init=False)

    def __post_init__(self):
        # metric name carries the quantile so several quantiles of one column
        # stay distinguishable (reference `ApproxQuantile.scala:90-97`)
        object.__setattr__(self, "name", f"ApproxQuantile-{self.quantile}")

    def _sketch_size(self) -> int:
        return _sketch_size_for_error(self.relative_error)

    def preconditions(self) -> List[Callable[[Schema], None]]:
        def param_checks(schema: Schema) -> None:
            _check_quantile(self.quantile)
            _check_relative_error(self.relative_error)

        return [param_checks] + super().preconditions()

    def metric_value(self, state) -> float:
        if isinstance(state, ExactQuantileState):
            return float(np.quantile(state.values(), self.quantile))
        return HostKLL.from_state(state).quantile(self.quantile)

    def is_empty(self, state) -> bool:
        return int(state.count) == 0


@dataclass(frozen=True)
class ApproxQuantiles(_ExactQuantileMode, _KLLBackedAnalyzer):
    """Several quantiles from one sketch -> KeyedDoubleMetric
    (reference `analyzers/ApproxQuantiles.scala:39-101`);
    ``relative_error=0.0`` selects the exact host full-sort mode."""

    column: str = ""
    quantiles: Tuple[float, ...] = ()
    relative_error: float = 0.01
    name: str = field(default="ApproxQuantiles", init=False)
    where: Optional[Predicate] = None

    def __post_init__(self):
        if not isinstance(self.quantiles, tuple):
            object.__setattr__(self, "quantiles", tuple(self.quantiles))

    def _sketch_size(self) -> int:
        return _sketch_size_for_error(self.relative_error)

    def preconditions(self) -> List[Callable[[Schema], None]]:
        def param_checks(schema: Schema) -> None:
            for q in self.quantiles:
                _check_quantile(q)
            _check_relative_error(self.relative_error)

        return [param_checks] + super().preconditions()

    def compute_metric_from(self, state) -> KeyedDoubleMetric:
        if state is None or int(state.count) == 0:
            empty = metric_from_empty(self.name, self.column, Entity.COLUMN)
            return KeyedDoubleMetric(Entity.COLUMN, self.name, self.column, empty.value)
        try:
            if isinstance(state, ExactQuantileState):
                vals = state.values()
                values = {
                    str(q): float(np.quantile(vals, q)) for q in self.quantiles
                }
                return KeyedDoubleMetric(
                    Entity.COLUMN, self.name, self.column, Success(values)
                )
            sketch = HostKLL.from_state(state)
            values = {str(q): sketch.quantile(q) for q in self.quantiles}
            return KeyedDoubleMetric(Entity.COLUMN, self.name, self.column, Success(values))
        except Exception as exc:  # noqa: BLE001
            from ..exceptions import wrap_if_necessary

            return KeyedDoubleMetric(
                Entity.COLUMN, self.name, self.column, Failure(wrap_if_necessary(exc))
            )

    def to_failure_metric(self, exception: BaseException) -> KeyedDoubleMetric:
        from ..exceptions import wrap_if_necessary

        return KeyedDoubleMetric(
            Entity.COLUMN, self.name, self.column, Failure(wrap_if_necessary(exception))
        )
