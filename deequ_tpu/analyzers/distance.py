"""Profile-vs-profile distances: L-infinity distance between two KLL
quantile sketches or two categorical count maps, with the two-sample
Kolmogorov-Smirnov small-sample correction
(reference `analyzers/Distance.scala:19-88`)."""

from __future__ import annotations

from typing import Mapping, Union

import numpy as np

from ..ops.kll import KLLSketchState
from ..ops.kll_host import HostKLL


class Distance:
    """Namespace mirroring the reference's `Distance` object."""

    @staticmethod
    def numerical_distance(
        sample1: Union[HostKLL, KLLSketchState],
        sample2: Union[HostKLL, KLLSketchState],
        correct_for_low_number_of_samples: bool = False,
    ) -> float:
        """L-inf distance between the CDFs of two KLL sketches, evaluated at
        the union of both sketches' item values (reference
        `Distance.numericalDistance`, `Distance.scala:22-41`: rank-map keys
        are the sketch items, ranks normalize by each sketch's total
        weight)."""
        s1 = sample1 if isinstance(sample1, HostKLL) else HostKLL.from_state(sample1)
        s2 = sample2 if isinstance(sample2, HostKLL) else HostKLL.from_state(sample2)
        keys = np.union1d(s1.values, s2.values)
        n = float(s1.total_weight)
        m = float(s2.total_weight)
        cdf1 = s1.cdf(keys)
        cdf2 = s2.cdf(keys)
        linf_simple = float(np.max(np.abs(cdf1 - cdf2))) if len(keys) else 0.0
        return _select_metrics(linf_simple, n, m, correct_for_low_number_of_samples)

    @staticmethod
    def categorical_distance(
        sample1: Mapping[str, int],
        sample2: Mapping[str, int],
        correct_for_low_number_of_samples: bool = False,
    ) -> float:
        """L-inf distance between two categorical count maps (reference
        `Distance.categoricalDistance`, `Distance.scala:44-68`; per the
        reference, the comparison is per-key probability mass, not a
        cumulative distribution). Accepts any mapping, including the
        pandas Series inside FrequenciesAndNumRows."""
        d1 = dict(sample1)  # normalizes Mapping and pandas Series alike
        d2 = dict(sample2)
        n = float(sum(d1.values()))
        m = float(sum(d2.values()))
        keys = set(d1) | set(d2)
        linf_simple = 0.0
        for key in keys:
            p1 = d1.get(key, 0) / n if n else 0.0
            p2 = d2.get(key, 0) / m if m else 0.0
            linf_simple = max(linf_simple, abs(p1 - p2))
        return _select_metrics(linf_simple, n, m, correct_for_low_number_of_samples)


def _select_metrics(
    linf_simple: float, n: float, m: float, correct_for_low_number_of_samples: bool
) -> float:
    """Reference `Distance.selectMetrics` (`Distance.scala:72-88`). NOTE the
    reference's naming is inverted relative to intuition and is reproduced
    exactly: with the flag TRUE the raw L-inf is returned; with the default
    FALSE the two-sample Kolmogorov-Smirnov robustness term is subtracted
    (distances indistinguishable from sampling noise floor at 0)."""
    if correct_for_low_number_of_samples:
        return linf_simple
    if n <= 0 or m <= 0:
        # an empty sample: the KS noise floor is infinite (the reference's
        # Scala double division yields Infinity), so the robust distance is 0
        return 0.0
    return max(0.0, linf_simple - 1.8 * np.sqrt((n + m) / (n * m)))
