"""State persistence: StateLoader / StatePersister.

Reference: `analyzers/StateProvider.scala:37-312` — states are loaded and
merged into a run (`aggregateWith`) or persisted after it (`saveStatesWith`),
enabling incremental computation on growing data and metric refresh over
partitioned tables without rescans (`runOnAggregatedStates`).

Here a state is either a numpy pytree (scan analyzers) or a
FrequenciesAndNumRows (grouping analyzers); the filesystem provider
serializes pytrees to .npz and frequency tables to parquet — the analog of
the reference's per-type binary blobs + parquet frequencies
(`StateProvider.scala:187-311`).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from .base import Analyzer
from .grouping import FrequenciesAndNumRows

#: Version of the persisted state layout (.npz leaf blobs + frequency
#: parquet/meta sidecars). Bump on ANY change to a state pytree's leaf
#: order/shapes or the sidecar schema; the loader refuses newer versions
#: instead of misreading them (SURVEY §7 hard part 5). v1 is frozen by
#: tests/test_state_serde.py::TestFormatVersioning::test_v1_npz_layout_pinned.
#: v2 replaced the v1 treedef PICKLE sidecar with a static state-type
#: registry recorded inside the .npz (name + static fields) — loading
#: never unpickles, so a blob from a shared object store cannot execute
#: code (the reference's fixed per-type binary codecs carry the same
#: property, `StateProvider.scala:187-241`). v1 .npz blobs still load:
#: their leaf order is identical and their structure derives from the
#: requesting analyzer, ignoring the legacy .pkl sidecar entirely.
#: v2 blobs additionally carry an OPTIONAL ``__checksum__`` member (an
#: xxhash64 content checksum verified on load; see `deequ_tpu.integrity`)
#: — optional members older readers ignore do not bump the version, and
#: legacy unchecksummed v2 blobs still load with a warn-once.
STATE_FORMAT_VERSION = 2


def _state_registry() -> Dict[str, type]:
    """Persistable state types by name — the reconstruction allowlist."""
    from ..ops.kll import KLLSketchState
    from . import states as s

    classes = [
        s.FrequencyCountsState, s.NumMatches, s.NumMatchesAndCount,
        s.MeanState, s.SumState, s.MinState, s.MaxState,
        s.StandardDeviationState, s.CorrelationState, s.DataTypeHistogram,
        s.ApproxCountDistinctState, KLLSketchState,
    ]
    return {cls.__name__: cls for cls in classes}


def _split_fields(cls) -> "tuple[list, list]":
    """(data field names in flatten order, static field names) of a
    flax.struct dataclass — the flatten order IS declaration order."""
    import dataclasses

    data, static = [], []
    for f in dataclasses.fields(cls):
        (data if f.metadata.get("pytree_node", True) else static).append(f.name)
    return data, static


def _reconstruct_state(type_name: str, static: Dict[str, Any], leaves: list) -> Any:
    registry = _state_registry()
    cls = registry.get(type_name)
    if cls is None:
        raise ValueError(
            f"persisted state type {type_name!r} is not in the reconstruction "
            f"registry ({sorted(registry)}); refusing to load"
        )
    data_fields, static_fields = _split_fields(cls)
    if len(leaves) != len(data_fields):
        raise ValueError(
            f"persisted {type_name} blob carries {len(leaves)} leaves, "
            f"expected {len(data_fields)} ({data_fields}); blob is corrupt "
            "or from an incompatible version"
        )
    if set(static) != set(static_fields):
        # exact match required: a MISSING static field would silently fall
        # back to the class default (e.g. a KLL blob reconstructing with the
        # wrong sketch_size against its own leaf shapes)
        raise ValueError(
            f"persisted {type_name} blob static fields {sorted(static)} do "
            f"not match the type's {sorted(static_fields)}"
        )
    return cls(**dict(zip(data_fields, leaves)), **static)


def _check_state_version(found: int, kind: str) -> None:
    if found > STATE_FORMAT_VERSION or found < 1:
        from ..exceptions import UnsupportedFormatVersionError

        raise UnsupportedFormatVersionError(kind, found, STATE_FORMAT_VERSION)


def _warn_once_unchecksummed(kind: str, source: str) -> None:
    from ..integrity import warn_once_unchecksummed

    warn_once_unchecksummed(kind, source)


def _blob_checksum(type_name: str, static: Dict[str, Any], leaves: list) -> str:
    """Content checksum of a v2 .npz state blob: the state-type name, the
    canonical static-field JSON and every leaf's dtype/shape/bytes. Computed
    from the SAME numpy arrays that np.savez writes (and that np.load hands
    back — savez round-trips arrays exactly), so persist and load hash
    identical payloads unless the bytes on disk changed underneath."""
    import json as _json

    from ..integrity import checksum_bytes

    parts = [
        type_name.encode("utf-8"),
        _json.dumps(static, sort_keys=True).encode("utf-8"),
    ]
    for leaf in leaves:
        arr = np.ascontiguousarray(leaf)
        parts.append(str(arr.dtype).encode("utf-8"))
        parts.append(str(arr.shape).encode("utf-8"))
        parts.append(arr.tobytes())
    return checksum_bytes(b"\x1f".join(parts))


def _sanitize_namespace_part(part: str) -> str:
    """One path segment of a state namespace: keep ASCII LOWERCASE
    alphanumerics, dot and dash; escape everything else — uppercase
    letters (two tenants differing only in case must stay distinct even
    on case-insensitive filesystems) and the ``_`` escape character
    itself — as ``_XX`` per UTF-8 byte. Escapes are fixed-width (two
    lowercase hex digits per byte), so the mapping is injective.
    ``.`` / ``..`` segments are prefixed so a namespace cannot traverse
    out of the store root."""
    out = []
    for ch in part:
        if ch.isascii() and (ch.islower() or ch.isdigit() or ch in ".-"):
            out.append(ch)
        else:
            out.extend(f"_{b:02x}" for b in ch.encode("utf-8"))
    safe = "".join(out)
    if safe in (".", ".."):
        return "_" + safe
    return safe


class StateLoader:
    def load(self, analyzer: Analyzer) -> Optional[Any]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: Analyzer, state: Any) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Thread-safe in-memory store (reference `StateProvider.scala:46-68`)."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._states: Dict[Analyzer, Any] = {}

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        with self._lock:
            return self._states.get(analyzer)

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        with self._lock:
            self._states[analyzer] = state

    def analyzers(self) -> list:
        """The analyzers with a persisted state (a long-lived streaming
        session's cheap "what do I hold" introspection)."""
        with self._lock:
            return list(self._states)

    def __len__(self) -> int:
        with self._lock:
            return len(self._states)

    def clear(self) -> None:
        """Drop every state — resets a streaming session's history."""
        with self._lock:
            self._states.clear()

    def __repr__(self) -> str:
        return f"InMemoryStateProvider({len(self)} states)"


class FileSystemStateProvider(StateLoader, StatePersister):
    """Directory-backed state store (reference `HdfsStateProvider`,
    `StateProvider.scala:73-312`). Each analyzer's state lands in files keyed
    by a stable hash of the analyzer's identity. ``path`` may be a local
    directory or any URI scheme `deequ_tpu.io` supports (``s3://``,
    ``gs://``, ``memory://``, ...), so a multi-host pod can merge
    day-partition states through shared storage the way the reference does
    through HDFS.

    ``namespace`` scopes the store to a subdirectory (path separators in it
    become nesting, every other unsafe character is escaped): the service's
    streaming sessions use one namespace per (tenant, dataset) so two
    tenants persisting the SAME analyzer never collide in one key space."""

    def __init__(
        self,
        path: str,
        allow_overwrite: bool = True,
        namespace: Optional[str] = None,
    ):
        from .. import io as dio

        if namespace:
            for part in str(namespace).split("/"):
                # an EMPTY part still yields a distinct segment ("a//b"
                # must not collide with "a/b"); "_" cannot collide with a
                # literal "_" part, which escapes to "_5f"
                path = dio.join(path, _sanitize_namespace_part(part) or "_")
        self.path = path
        self.allow_overwrite = allow_overwrite
        dio.makedirs(path)

    def _key(self, analyzer: Analyzer) -> str:
        import hashlib

        digest = hashlib.sha1(repr(analyzer).encode("utf-8")).hexdigest()[:16]
        return f"{analyzer.name}-{digest}"

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        from .. import io as dio

        base = dio.join(self.path, self._key(analyzer))
        if isinstance(state, FrequenciesAndNumRows):
            import io as _io

            import pyarrow as pa
            import pyarrow.parquet as pq

            from ..integrity import checksum_bytes

            # name index levels after the group columns: value_counts-built
            # series (Histogram) have unnamed indexes that would otherwise
            # round-trip as a column literally called "index"
            frame = (
                state.frequencies.rename("count")
                .rename_axis(state.group_columns)
                .reset_index()
            )
            # serialize to a buffer first so the checksum covers the EXACT
            # file bytes: any later flip — data page, footer, magic — fails
            # verification on load
            sink = _io.BytesIO()
            pq.write_table(
                pa.Table.from_pandas(frame, preserve_index=False), sink
            )
            payload = sink.getvalue()
            with dio.open_file(base + "-frequencies.parquet", "wb") as fh:
                fh.write(payload)
            with dio.open_file(base + "-meta.json", "w") as fh:
                json.dump(
                    {
                        "formatVersion": STATE_FORMAT_VERSION,
                        "num_rows": state.num_rows,
                        "group_columns": state.group_columns,
                        "checksum": checksum_bytes(payload),
                    },
                    fh,
                )
            return
        # numpy/jax pytree: leaves as .npz arrays + the state-type name and
        # static fields as plain JSON INSIDE the npz — no pickle anywhere
        import jax

        leaves, _ = jax.tree_util.tree_flatten(state)
        type_name = type(state).__name__
        if type_name not in _state_registry():
            raise ValueError(
                f"state type {type_name!r} is not registered for persistence; "
                "add it to _state_registry so it can be reconstructed "
                "without code execution on load"
            )
        _, static_fields = _split_fields(type(state))
        static = {name: getattr(state, name) for name in static_fields}
        host_leaves = [np.asarray(v) for v in leaves]
        with dio.open_file(base + "-state.npz", "wb") as fh:
            np.savez(
                fh,
                __format_version__=np.int64(STATE_FORMAT_VERSION),
                __state_type__=np.str_(type_name),
                __static__=np.str_(json.dumps(static)),
                __checksum__=np.str_(
                    _blob_checksum(type_name, static, host_leaves)
                ),
                **{f"leaf{i}": v for i, v in enumerate(host_leaves)},
            )

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        from .. import io as dio
        from ..exceptions import CorruptStateError
        from ..reliability.faults import fault_point

        base = dio.join(self.path, self._key(analyzer))
        # chaos site: an injected "corrupt" fault here stands in for a blob
        # whose bytes rotted after the existence check
        fault_point("state_load", tag=repr(analyzer))
        if dio.exists(base + "-frequencies.parquet"):
            import io as _io

            import pyarrow.parquet as pq

            from ..integrity import verify_checksum

            source = base + "-frequencies.parquet"
            with dio.open_file(source, "rb") as fh:
                payload = fh.read()
            try:
                with dio.open_file(base + "-meta.json", "r") as fh:
                    meta = json.load(fh)
            except ValueError as exc:
                raise CorruptStateError(
                    "frequency-state sidecar", base + "-meta.json", str(exc)
                ) from exc
            # sidecars from before versioning (round <=3) carry no marker
            # and ARE the v1 layout
            _check_state_version(
                int(meta.get("formatVersion", 1)), "frequency-state sidecar"
            )
            if "checksum" in meta:
                verify_checksum(
                    payload, meta["checksum"], "frequency-state parquet",
                    source,
                )
            else:
                _warn_once_unchecksummed("frequency-state parquet", source)
            try:
                frame = pq.read_table(_io.BytesIO(payload)).to_pandas()
            except Exception as exc:  # noqa: BLE001 - unparseable = corrupt
                raise CorruptStateError(
                    "frequency-state parquet", source, str(exc)
                ) from exc
            import pandas as pd

            cols = meta["group_columns"]
            series = frame.set_index(cols)["count"]
            if len(cols) == 1:
                series.index = series.index.get_level_values(0) if isinstance(
                    series.index, pd.MultiIndex
                ) else series.index
            return FrequenciesAndNumRows(series, meta["num_rows"], cols)
        if dio.exists(base + "-state.npz"):
            import io as _io

            import jax

            source = base + "-state.npz"
            with dio.open_file(source, "rb") as fh:
                raw = fh.read()
            # np.load is LAZY: member bytes decode (and zip CRCs fire) on
            # access, so every member read lives inside the corruption
            # guard — a torn zip anywhere surfaces as the one typed error
            try:
                data = np.load(_io.BytesIO(raw))
                files = set(data.files)
                version = (
                    int(data["__format_version__"])
                    if "__format_version__" in files
                    else None
                )
                n_leaves = sum(1 for f in files if f.startswith("leaf"))
                leaves = [data[f"leaf{i}"] for i in range(n_leaves)]
                type_name = (
                    str(data["__state_type__"])
                    if "__state_type__" in files
                    else None
                )
                static_raw = str(data["__static__"]) if type_name else "{}"
                stored = (
                    str(data["__checksum__"]) if "__checksum__" in files
                    else None
                )
            except Exception as exc:  # noqa: BLE001 - torn zip = corrupt
                raise CorruptStateError(".npz state blob", source, str(exc)) from exc
            if version is not None:
                _check_state_version(version, ".npz state blob")
            if type_name is not None:
                # v2: reconstruct via the static registry
                try:
                    static = json.loads(static_raw)
                except ValueError as exc:
                    raise CorruptStateError(
                        ".npz state blob", source, str(exc)
                    ) from exc
                if stored is not None:
                    actual = _blob_checksum(type_name, static, leaves)
                    if actual != stored:
                        raise CorruptStateError(
                            ".npz state blob", source,
                            f"checksum mismatch (stored {stored}, "
                            f"computed {actual})",
                        )
                else:
                    _warn_once_unchecksummed(".npz state blob", source)
                try:
                    return _reconstruct_state(type_name, static, leaves)
                except ValueError as exc:
                    # leaf-count / static-field mismatches are the torn-blob
                    # signature; surface them under the one typed error the
                    # recovery layers key on
                    raise CorruptStateError(
                        ".npz state blob", source, str(exc)
                    ) from exc
            # v1 blob: same leaf order, but the structure rode a pickle
            # sidecar. Never unpickle it — the requesting analyzer's own
            # state structure (class + static fields) is authoritative and
            # reproduces the treedef exactly.
            shapes = jax.eval_shape(analyzer.init_state)
            treedef = jax.tree_util.tree_structure(shapes)
            if treedef.num_leaves != len(leaves):
                raise ValueError(
                    f"v1 state blob for {analyzer} carries {len(leaves)} "
                    f"leaves but the analyzer's state has "
                    f"{treedef.num_leaves}; blob is corrupt or from an "
                    "incompatible analyzer"
                )
            return jax.tree_util.tree_unflatten(treedef, leaves)
        return None
