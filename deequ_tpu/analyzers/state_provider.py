"""State persistence: StateLoader / StatePersister.

Reference: `analyzers/StateProvider.scala:37-312` — states are loaded and
merged into a run (`aggregateWith`) or persisted after it (`saveStatesWith`),
enabling incremental computation on growing data and metric refresh over
partitioned tables without rescans (`runOnAggregatedStates`).

Here a state is either a numpy pytree (scan analyzers) or a
FrequenciesAndNumRows (grouping analyzers); the filesystem provider
serializes pytrees to .npz and frequency tables to parquet — the analog of
the reference's per-type binary blobs + parquet frequencies
(`StateProvider.scala:187-311`).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional

import numpy as np

from .base import Analyzer
from .grouping import FrequenciesAndNumRows


class StateLoader:
    def load(self, analyzer: Analyzer) -> Optional[Any]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: Analyzer, state: Any) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Thread-safe in-memory store (reference `StateProvider.scala:46-68`)."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._states: Dict[Analyzer, Any] = {}

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        with self._lock:
            return self._states.get(analyzer)

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        with self._lock:
            self._states[analyzer] = state

    def __repr__(self) -> str:
        return f"InMemoryStateProvider({len(self._states)} states)"


class FileSystemStateProvider(StateLoader, StatePersister):
    """Directory-backed state store (reference `HdfsStateProvider`,
    `StateProvider.scala:73-312`). Each analyzer's state lands in files keyed
    by a stable hash of the analyzer's identity."""

    def __init__(self, path: str, allow_overwrite: bool = True):
        self.path = path
        self.allow_overwrite = allow_overwrite
        os.makedirs(path, exist_ok=True)

    def _key(self, analyzer: Analyzer) -> str:
        import hashlib

        digest = hashlib.sha1(repr(analyzer).encode("utf-8")).hexdigest()[:16]
        return f"{analyzer.name}-{digest}"

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        base = os.path.join(self.path, self._key(analyzer))
        if isinstance(state, FrequenciesAndNumRows):
            # name index levels after the group columns: value_counts-built
            # series (Histogram) have unnamed indexes that would otherwise
            # round-trip as a column literally called "index"
            frame = (
                state.frequencies.rename("count")
                .rename_axis(state.group_columns)
                .reset_index()
            )
            frame.to_parquet(base + "-frequencies.parquet")
            with open(base + "-meta.json", "w", encoding="utf-8") as fh:
                json.dump(
                    {"num_rows": state.num_rows, "group_columns": state.group_columns}, fh
                )
            return
        # numpy/jax pytree: flatten to arrays + structure pickle
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(state)
        np.savez(
            base + "-state.npz", **{f"leaf{i}": np.asarray(v) for i, v in enumerate(leaves)}
        )
        with open(base + "-treedef.pkl", "wb") as fh:
            pickle.dump((type(state).__name__, treedef), fh)

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        base = os.path.join(self.path, self._key(analyzer))
        if os.path.exists(base + "-frequencies.parquet"):
            import pandas as pd

            frame = pd.read_parquet(base + "-frequencies.parquet")
            with open(base + "-meta.json", "r", encoding="utf-8") as fh:
                meta = json.load(fh)
            cols = meta["group_columns"]
            series = frame.set_index(cols)["count"]
            if len(cols) == 1:
                series.index = series.index.get_level_values(0) if isinstance(
                    series.index, pd.MultiIndex
                ) else series.index
            return FrequenciesAndNumRows(series, meta["num_rows"], cols)
        if os.path.exists(base + "-state.npz"):
            import jax

            with open(base + "-treedef.pkl", "rb") as fh:
                _, treedef = pickle.load(fh)
            data = np.load(base + "-state.npz")
            leaves = [data[f"leaf{i}"] for i in range(len(data.files))]
            return jax.tree_util.tree_unflatten(treedef, leaves)
        return None
