"""State persistence: StateLoader / StatePersister.

Reference: `analyzers/StateProvider.scala:37-312` — states are loaded and
merged into a run (`aggregateWith`) or persisted after it (`saveStatesWith`),
enabling incremental computation on growing data and metric refresh over
partitioned tables without rescans (`runOnAggregatedStates`).

Here a state is either a numpy pytree (scan analyzers) or a
FrequenciesAndNumRows (grouping analyzers); the filesystem provider
serializes pytrees to .npz and frequency tables to parquet — the analog of
the reference's per-type binary blobs + parquet frequencies
(`StateProvider.scala:187-311`).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional

import numpy as np

from .base import Analyzer
from .grouping import FrequenciesAndNumRows

#: Version of the persisted state layout (.npz leaf blobs + frequency
#: parquet/meta sidecars). Bump on ANY change to a state pytree's leaf
#: order/shapes or the sidecar schema; the loader refuses newer versions
#: instead of misreading them (SURVEY §7 hard part 5). v1 is frozen by
#: tests/test_state_serde.py::TestFormatVersioning::test_v1_npz_layout_pinned.
STATE_FORMAT_VERSION = 1


def _check_state_version(found: int, kind: str) -> None:
    if found > STATE_FORMAT_VERSION or found < 1:
        from ..exceptions import UnsupportedFormatVersionError

        raise UnsupportedFormatVersionError(kind, found, STATE_FORMAT_VERSION)


class StateLoader:
    def load(self, analyzer: Analyzer) -> Optional[Any]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: Analyzer, state: Any) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Thread-safe in-memory store (reference `StateProvider.scala:46-68`)."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._states: Dict[Analyzer, Any] = {}

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        with self._lock:
            return self._states.get(analyzer)

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        with self._lock:
            self._states[analyzer] = state

    def __repr__(self) -> str:
        return f"InMemoryStateProvider({len(self._states)} states)"


class FileSystemStateProvider(StateLoader, StatePersister):
    """Directory-backed state store (reference `HdfsStateProvider`,
    `StateProvider.scala:73-312`). Each analyzer's state lands in files keyed
    by a stable hash of the analyzer's identity. ``path`` may be a local
    directory or any URI scheme `deequ_tpu.io` supports (``s3://``,
    ``gs://``, ``memory://``, ...), so a multi-host pod can merge
    day-partition states through shared storage the way the reference does
    through HDFS."""

    def __init__(self, path: str, allow_overwrite: bool = True):
        from .. import io as dio

        self.path = path
        self.allow_overwrite = allow_overwrite
        dio.makedirs(path)

    def _key(self, analyzer: Analyzer) -> str:
        import hashlib

        digest = hashlib.sha1(repr(analyzer).encode("utf-8")).hexdigest()[:16]
        return f"{analyzer.name}-{digest}"

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        from .. import io as dio

        base = dio.join(self.path, self._key(analyzer))
        if isinstance(state, FrequenciesAndNumRows):
            import pyarrow as pa

            # name index levels after the group columns: value_counts-built
            # series (Histogram) have unnamed indexes that would otherwise
            # round-trip as a column literally called "index"
            frame = (
                state.frequencies.rename("count")
                .rename_axis(state.group_columns)
                .reset_index()
            )
            dio.write_parquet_table(
                pa.Table.from_pandas(frame, preserve_index=False),
                base + "-frequencies.parquet",
            )
            with dio.open_file(base + "-meta.json", "w") as fh:
                json.dump(
                    {
                        "formatVersion": STATE_FORMAT_VERSION,
                        "num_rows": state.num_rows,
                        "group_columns": state.group_columns,
                    },
                    fh,
                )
            return
        # numpy/jax pytree: flatten to arrays + structure pickle
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(state)
        with dio.open_file(base + "-state.npz", "wb") as fh:
            np.savez(
                fh,
                __format_version__=np.int64(STATE_FORMAT_VERSION),
                **{f"leaf{i}": np.asarray(v) for i, v in enumerate(leaves)},
            )
        with dio.open_file(base + "-treedef.pkl", "wb") as fh:
            pickle.dump((type(state).__name__, treedef), fh)

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        from .. import io as dio

        base = dio.join(self.path, self._key(analyzer))
        if dio.exists(base + "-frequencies.parquet"):
            frame = dio.read_parquet_table(base + "-frequencies.parquet").to_pandas()
            with dio.open_file(base + "-meta.json", "r") as fh:
                meta = json.load(fh)
            # sidecars from before versioning (round <=3) carry no marker
            # and ARE the v1 layout
            _check_state_version(
                int(meta.get("formatVersion", 1)), "frequency-state sidecar"
            )
            import pandas as pd

            cols = meta["group_columns"]
            series = frame.set_index(cols)["count"]
            if len(cols) == 1:
                series.index = series.index.get_level_values(0) if isinstance(
                    series.index, pd.MultiIndex
                ) else series.index
            return FrequenciesAndNumRows(series, meta["num_rows"], cols)
        if dio.exists(base + "-state.npz"):
            import io as _io

            import jax

            with dio.open_file(base + "-treedef.pkl", "rb") as fh:
                _, treedef = pickle.load(fh)
            with dio.open_file(base + "-state.npz", "rb") as fh:
                data = np.load(_io.BytesIO(fh.read()))
            if "__format_version__" in data.files:
                _check_state_version(int(data["__format_version__"]), ".npz state blob")
            n_leaves = sum(1 for f in data.files if f.startswith("leaf"))
            leaves = [data[f"leaf{i}"] for i in range(n_leaves)]
            return jax.tree_util.tree_unflatten(treedef, leaves)
        return None
