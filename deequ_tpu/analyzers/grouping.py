"""Frequency/grouping analyzers.

The reference computes one `GROUP BY` per distinct grouping-column set and
shares the resulting frequency table between all analyzers on that set
(reference `analyzers/GroupingAnalyzers.scala:29-157`, scheduler sharing at
`analyzers/runners/AnalysisRunner.scala:259-287`). Here the frequency table
is an exact host-side group-by (pandas C kernels over the Arrow batch)
accumulated *in the same single pass* as the device scan — so a verification
run with grouping analyzers still touches the data once, beating the
reference's extra jobs.

State semantics (verified against the reference):
- frequencies exclude rows where any grouping column is null;
- ``num_rows`` counts ALL rows (`FrequencyBasedAnalyzer.computeFrequencies`,
  `GroupingAnalyzers.scala:53-80`: numRows = data.count());
- merge = outer join adding counts (`GroupingAnalyzers.scala:128-148`).
"""

from __future__ import annotations

import abc
import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from ..data import Batch, ColumnKind, Schema
from ..metrics import (
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    Failure,
    HistogramMetric,
    Success,
    metric_from_empty,
    metric_from_failure,
    metric_from_value,
)
from ..exceptions import (
    IllegalAnalyzerParameterException,
    wrap_if_necessary,
)
from .base import Analyzer, Preconditions

COUNT_COL = "count"


class FrequenciesAndNumRows:
    """Host state: group -> count plus total row count
    (reference `GroupingAnalyzers.scala:128-157`)."""

    def __init__(self, frequencies: pd.Series, num_rows: int, group_columns: Sequence[str]):
        self.frequencies = frequencies  # index = group keys (tuples for multi-col)
        self.num_rows = int(num_rows)
        self.group_columns = list(group_columns)

    def sum(self, other: "FrequenciesAndNumRows") -> "FrequenciesAndNumRows":
        merged = _add_series(self.frequencies, other.frequencies)
        return FrequenciesAndNumRows(merged, self.num_rows + other.num_rows, self.group_columns)

    @staticmethod
    def empty(group_columns: Sequence[str]) -> "FrequenciesAndNumRows":
        return FrequenciesAndNumRows(
            pd.Series([], dtype=np.int64), 0, group_columns
        )

    def update(self, batch: Batch) -> "FrequenciesAndNumRows":
        """Fold one batch of rows into the frequency table."""
        mask = batch.row_mask
        cols = {}
        for name in self.group_columns:
            col = batch.column(name)
            mask = mask & col.mask
            cols[name] = col.values
        num_rows = self.num_rows + batch.num_rows
        if not mask.any():
            return FrequenciesAndNumRows(self.frequencies, num_rows, self.group_columns)
        frame = pd.DataFrame({n: v[mask] for n, v in cols.items()})
        counts = frame.groupby(self.group_columns, sort=False, dropna=False).size()
        if len(self.group_columns) == 1:
            counts.index = counts.index.get_level_values(0) if isinstance(
                counts.index, pd.MultiIndex
            ) else counts.index
        merged = _add_series(self.frequencies, counts)
        return FrequenciesAndNumRows(merged, num_rows, self.group_columns)


def _add_series(a: pd.Series, b: pd.Series) -> pd.Series:
    """Outer-join add of two count series; tolerates empty operands whose
    index types don't match the other side's (Range vs MultiIndex)."""
    if len(a) == 0:
        return b.astype(np.int64)
    if len(b) == 0:
        return a.astype(np.int64)
    return a.add(b, fill_value=0).astype(np.int64)


class GroupingAnalyzer(Analyzer[FrequenciesAndNumRows, DoubleMetric]):
    """Analyzer computed from a shared frequency table."""

    columns: Sequence[str]

    def grouping_columns(self) -> List[str]:
        return list(self.columns)

    @property
    def instance(self) -> str:
        return ",".join(self.grouping_columns())

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN if len(self.grouping_columns()) == 1 else Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        cols = self.grouping_columns()
        out: List[Callable[[Schema], None]] = [Preconditions.at_least_one(cols)]
        for c in cols:
            out.append(Preconditions.has_column(c))
            out.append(Preconditions.is_not_nested(c))
        return out

    def merge(self, a: FrequenciesAndNumRows, b: FrequenciesAndNumRows) -> FrequenciesAndNumRows:
        return a.sum(b)


class ScanShareableFrequencyBasedAnalyzer(GroupingAnalyzer):
    """Base for analyzers that reduce the frequency table to a double
    (reference `GroupingAnalyzers.scala:85-123`)."""

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> DoubleMetric:
        if state is None:
            return metric_from_empty(self.name, self.instance, self.entity)
        try:
            value = self.metric_from_frequencies(state)
        except Exception as exc:  # noqa: BLE001
            return metric_from_failure(wrap_if_necessary(exc), self.name, self.instance, self.entity)
        if value is None or (isinstance(value, float) and math.isnan(value)):
            return metric_from_empty(self.name, self.instance, self.entity)
        return metric_from_value(float(value), self.name, self.instance, self.entity)

    @abc.abstractmethod
    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        ...


@dataclass(frozen=True)
class Uniqueness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of rows whose group occurs exactly once: sum(count==1)/numRows
    (reference `analyzers/Uniqueness.scala:26-38`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="Uniqueness", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        if state.num_rows == 0:
            return float("nan")
        return float((state.frequencies == 1).sum()) / state.num_rows


@dataclass(frozen=True)
class Distinctness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of distinct groups over rows: sum(count>=1)/numRows
    (reference `analyzers/Distinctness.scala:29-41`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="Distinctness", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        if state.num_rows == 0:
            return float("nan")
        return float((state.frequencies >= 1).sum()) / state.num_rows


@dataclass(frozen=True)
class UniqueValueRatio(ScanShareableFrequencyBasedAnalyzer):
    """sum(count==1) / number of distinct groups
    (reference `analyzers/UniqueValueRatio.scala:25-44`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="UniqueValueRatio", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        num_groups = len(state.frequencies)
        if num_groups == 0:
            return float("nan")
        return float((state.frequencies == 1).sum()) / num_groups


@dataclass(frozen=True)
class CountDistinct(ScanShareableFrequencyBasedAnalyzer):
    """Number of distinct groups (reference `analyzers/CountDistinct.scala:24-40`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="CountDistinct", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        return float(len(state.frequencies))


@dataclass(frozen=True)
class Entropy(ScanShareableFrequencyBasedAnalyzer):
    """Shannon entropy over the value distribution, with N = total row count:
    -sum (c/N) ln(c/N) (reference `analyzers/Entropy.scala:28-42`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="Entropy", init=False)

    def __init__(self, column):
        object.__setattr__(self, "columns", _as_tuple(column))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        n = state.num_rows
        if n == 0:
            return float("nan")
        c = state.frequencies.to_numpy(dtype=np.float64)
        c = c[c > 0]
        p = c / n
        return float(-(p * np.log(p)).sum())


@dataclass(frozen=True)
class MutualInformation(GroupingAnalyzer):
    """MI of two columns from the joint frequency table
    (reference `analyzers/MutualInformation.scala:35-103`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="MutualInformation", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.exactly_n_columns(self.columns, 2)] + super().preconditions()

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> DoubleMetric:
        if state is None or len(state.frequencies) == 0:
            return metric_from_empty(self.name, self.instance, self.entity)
        try:
            total = state.num_rows
            joint = state.frequencies  # MultiIndex (col1, col2) -> count
            px = joint.groupby(level=0).sum()
            py = joint.groupby(level=1).sum()
            pxy = joint.to_numpy(dtype=np.float64) / total
            px_row = px.loc[joint.index.get_level_values(0)].to_numpy(dtype=np.float64) / total
            py_row = py.loc[joint.index.get_level_values(1)].to_numpy(dtype=np.float64) / total
            value = float((pxy * np.log(pxy / (px_row * py_row))).sum())
        except Exception as exc:  # noqa: BLE001
            return metric_from_failure(wrap_if_necessary(exc), self.name, self.instance, self.entity)
        return metric_from_value(value, self.name, self.instance, self.entity)


def _as_tuple(columns) -> Tuple[str, ...]:
    if isinstance(columns, str):
        return (columns,)
    return tuple(columns)


def _spark_string_cast(value) -> str:
    """Format a value the way Spark's cast-to-string would (booleans
    lowercase, floats like '1.0')."""
    if isinstance(value, (bool, np.bool_)):
        return "true" if value else "false"
    if isinstance(value, (float, np.floating)):
        return repr(float(value)) if not float(value).is_integer() else f"{value:.1f}"
    if isinstance(value, (int, np.integer)):
        return str(int(value))
    return str(value)


NULL_FIELD_REPLACEMENT = "NullValue"  # reference `analyzers/Histogram.scala:108`
MAXIMUM_ALLOWED_DETAIL_BINS = 1000  # reference `analyzers/Histogram.scala:109`


@dataclass(frozen=True)
class Histogram(Analyzer["FrequenciesAndNumRows", HistogramMetric]):
    """Exact value histogram of one column: values cast to string, nulls
    replaced by "NullValue", optional binning function, top-K detail bins by
    count (reference `analyzers/Histogram.scala:41-116`)."""

    column: str = ""
    binning_func: Optional[Callable] = None
    max_detail_bins: int = MAXIMUM_ALLOWED_DETAIL_BINS
    name: str = field(default="Histogram", init=False)

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        def param_check(schema: Schema) -> None:
            if self.max_detail_bins > MAXIMUM_ALLOWED_DETAIL_BINS:
                raise IllegalAnalyzerParameterException(
                    f"Cannot return histogram values for more than "
                    f"{MAXIMUM_ALLOWED_DETAIL_BINS} values"
                )

        return [param_check, Preconditions.has_column(self.column)]

    # host accumulation protocol (driven by the runner's single pass)

    def host_init(self) -> FrequenciesAndNumRows:
        return FrequenciesAndNumRows.empty([self.column])

    def host_update(self, state: FrequenciesAndNumRows, batch: Batch) -> FrequenciesAndNumRows:
        col = batch.column(self.column)
        mask = batch.row_mask
        values = col.values[mask]
        present = col.mask[mask]
        if self.binning_func is None:
            # vectorized: count raw PRESENT values first (cheap),
            # Spark-string-cast only the distinct keys; nullness comes from
            # the validity mask, never from the value (a genuine float NaN
            # keys as 'nan', a null as NullValue)
            present_values = values[present]
            if present_values.dtype == object:
                counts = pd.Series(present_values).value_counts(sort=False, dropna=False)
                distinct, cnts = list(counts.index), counts.to_numpy()
            else:
                distinct, cnts = np.unique(present_values, return_counts=True)
            counts = pd.Series(
                cnts, index=[_spark_string_cast(k) for k in distinct], dtype=np.int64
            )
            counts = counts.groupby(level=0, sort=False).sum()
            num_null = int(len(values) - present.sum())
            if num_null:
                counts = counts.add(
                    pd.Series({NULL_FIELD_REPLACEMENT: num_null}), fill_value=0
                ).astype(np.int64)
        else:
            keys = np.empty(len(values), dtype=object)
            for i in range(len(values)):
                if not present[i]:
                    keys[i] = NULL_FIELD_REPLACEMENT
                else:
                    v = self.binning_func(values[i])
                    keys[i] = (
                        _spark_string_cast(v) if v is not None else NULL_FIELD_REPLACEMENT
                    )
            counts = pd.Series(keys).value_counts(sort=False)
        merged = state.frequencies.add(counts, fill_value=0).astype(np.int64)
        return FrequenciesAndNumRows(merged, state.num_rows + batch.num_rows, [self.column])

    def merge(self, a: FrequenciesAndNumRows, b: FrequenciesAndNumRows) -> FrequenciesAndNumRows:
        return a.sum(b)

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> HistogramMetric:
        if state is None:
            from ..exceptions import EmptyStateException

            return HistogramMetric(
                self.entity,
                self.name,
                self.instance,
                Failure(EmptyStateException(f"Empty state for analyzer {self}")),
                self.column,
            )
        try:
            bin_count = len(state.frequencies)
            top = state.frequencies.sort_values(ascending=False).head(self.max_detail_bins)
            values = {
                str(k): DistributionValue(int(v), int(v) / state.num_rows)
                for k, v in top.items()
            }
            dist = Distribution(values, number_of_bins=bin_count)
            return HistogramMetric(self.entity, self.name, self.instance, Success(dist), self.column)
        except Exception as exc:  # noqa: BLE001
            return HistogramMetric(
                self.entity, self.name, self.instance, Failure(wrap_if_necessary(exc)), self.column
            )
