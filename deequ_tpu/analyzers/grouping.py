"""Frequency/grouping analyzers.

The reference computes one `GROUP BY` per distinct grouping-column set and
shares the resulting frequency table between all analyzers on that set
(reference `analyzers/GroupingAnalyzers.scala:29-157`, scheduler sharing at
`analyzers/runners/AnalysisRunner.scala:259-287`). Here the frequency table
is an exact host-side group-by (pandas C kernels over the Arrow batch)
accumulated *in the same single pass* as the device scan — so a verification
run with grouping analyzers still touches the data once, beating the
reference's extra jobs.

State semantics (verified against the reference):
- frequencies exclude rows where any grouping column is null;
- ``num_rows`` counts ALL rows (`FrequencyBasedAnalyzer.computeFrequencies`,
  `GroupingAnalyzers.scala:53-80`: numRows = data.count());
- merge = outer join adding counts (`GroupingAnalyzers.scala:128-148`).
"""

from __future__ import annotations

import abc
import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from ..data import Batch, ColumnKind, Schema
from ..metrics import (
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    Failure,
    HistogramMetric,
    Success,
    metric_from_empty,
    metric_from_failure,
    metric_from_value,
)
from ..exceptions import (
    IllegalAnalyzerParameterException,
    wrap_if_necessary,
)
from .base import Analyzer, Preconditions, ScanShareableAnalyzer

COUNT_COL = "count"


#: flush the run buffer once it holds at least this many entries (and at
#: least as many as the merged table, so every flush is amortized against
#: fresh input — total merge work stays O(total entries appended), never
#: O(batches x distinct))
MIN_FLUSH_ENTRIES = 1 << 17

#: optional hard cap on the frequency table's resident entry count; a run
#: whose distinct-group count exceeds it fails with a clear resource error
#: (a failure METRIC via the runner, not an opaque OOM). 0 = unlimited.
FREQ_BUDGET_ENV = "DEEQU_TPU_MAX_FREQUENCY_ENTRIES"


class FrequencyBudgetExceeded(RuntimeError):
    """Distinct-group count crossed DEEQU_TPU_MAX_FREQUENCY_ENTRIES (with
    spilling disabled), or a spilled table was asked to fully materialize."""


#: set to "0" to disable spilling and restore the hard budget failure
FREQ_SPILL_ENV = "DEEQU_TPU_FREQUENCY_SPILL"
#: number of hash partitions a spilled table is scattered over
FREQ_SPILL_PARTITIONS_ENV = "DEEQU_TPU_FREQUENCY_SPILL_PARTITIONS"
_DEFAULT_SPILL_PARTITIONS = 64


class _SpillStore:
    """Hash-partitioned spill files for an over-budget frequency table —
    the analog of the Spark shuffle spill the reference leans on
    (`GroupingAnalyzers.scala:53-80` runs on Spark's hash aggregation,
    which spills sorted run files per hash partition when memory runs out).

    Every spill event scatters the resident table over P partitions by a
    stable row hash and appends one parquet run file per non-empty
    partition. A key lives in exactly ONE partition, so reading a
    partition's runs + one concat/groupby yields FINAL counts for its keys
    with peak memory ~ (total appended entries)/P — never the whole table.
    """

    #: sentinel column names inside spill parquet files — user key columns
    #: may be named anything (including "count"), so frames never use the
    #: user-visible names
    _COUNT = "__deequ_count__"

    def __init__(self, group_columns: Sequence[str]):
        import shutil
        import tempfile
        import weakref

        self.group_columns = list(group_columns)
        self._key_cols = [f"__deequ_key{i}__" for i in range(len(self.group_columns))]
        from ..utils import env_number

        self.partitions = env_number(
            FREQ_SPILL_PARTITIONS_ENV, _DEFAULT_SPILL_PARTITIONS, int,
            minimum=1,
        )
        self.dir = tempfile.mkdtemp(prefix="deequ-tpu-freq-spill-")
        self._runs = 0
        self.entries_spilled = 0
        self._finalizer = weakref.finalize(self, shutil.rmtree, self.dir, True)

    def close(self) -> None:
        """Remove the spill directory NOW (idempotent). The GC finalizer is
        only the backstop: a long-lived process whose collected states are
        kept alive by stray references would otherwise leak one
        ``deequ-tpu-freq-spill-*`` temp dir per spilled table until exit.
        The runner closes its pass-local grouping states as soon as their
        metrics are derived."""
        self._finalizer()

    def _partition_of(self, frame: pd.DataFrame) -> np.ndarray:
        """Stable per-row hash partition from the KEY COLUMNS (hashing the
        index directly trips pandas' Categorical factorization on NaN level
        values; plain columns hash NaN by bit pattern)."""
        codes = pd.util.hash_pandas_object(
            frame[self._key_cols], index=False
        ).to_numpy()
        return (codes % np.uint64(self.partitions)).astype(np.int64)

    def _to_frame(self, counts: pd.Series) -> pd.DataFrame:
        return counts.rename(self._COUNT).rename_axis(self._key_cols).reset_index()

    def _check_open(self) -> None:
        if not self._finalizer.alive:
            raise RuntimeError(
                "frequency spill store was closed; its partition files are "
                "gone — serving from it would silently drop counts"
            )

    def append(self, counts: pd.Series) -> None:
        """Scatter one resident table over the hash partitions."""
        import os

        self._check_open()
        if len(counts) == 0:
            return
        frame = self._to_frame(counts)
        part_of = self._partition_of(frame)
        run = self._runs
        self._runs += 1
        for p in np.unique(part_of):
            sub = frame.iloc[np.flatnonzero(part_of == p)]
            pdir = os.path.join(self.dir, f"part{p:05d}")
            os.makedirs(pdir, exist_ok=True)
            sub.to_parquet(os.path.join(pdir, f"run{run:05d}.parquet"), index=False)
        self.entries_spilled += len(counts)

    def iter_partition_counts(self, extra: Optional[pd.Series] = None):
        """Yield one FINAL count Series per partition (every key exactly
        once across all yields). ``extra`` is a not-yet-spilled resident
        table folded in (hashed with the same function)."""
        import os

        self._check_open()

        extra_parts: Dict[int, pd.Series] = {}
        if extra is not None and len(extra):
            part_of = self._partition_of(self._to_frame(extra))
            for p in np.unique(part_of):
                extra_parts[int(p)] = extra.iloc[np.flatnonzero(part_of == p)]
        for p in range(self.partitions):
            pdir = os.path.join(self.dir, f"part{p:05d}")
            pieces: List[pd.Series] = []
            if os.path.isdir(pdir):
                for name in sorted(os.listdir(pdir)):
                    frame = pd.read_parquet(os.path.join(pdir, name))
                    series = frame.set_index(self._key_cols)[self._COUNT]
                    if len(self._key_cols) == 1 and isinstance(
                        series.index, pd.MultiIndex
                    ):
                        series.index = series.index.get_level_values(0)
                    # restore the user-visible level names for consumers that
                    # read keys (Histogram, MutualInformation marginals)
                    series = series.rename_axis(
                        self.group_columns if len(self.group_columns) > 1
                        else self.group_columns[0]
                    )
                    pieces.append(series)
            if p in extra_parts:
                pieces.append(extra_parts[p])
            if not pieces:
                continue
            if len(pieces) == 1:
                yield pieces[0].astype(np.int64)
                continue
            cat = pd.concat(pieces)
            levels = (
                list(range(cat.index.nlevels))
                if isinstance(cat.index, pd.MultiIndex)
                else 0
            )
            yield (
                cat.groupby(level=levels, sort=False, dropna=False)
                .sum()
                .astype(np.int64)
            )


class FrequenciesAndNumRows:
    """Host state: group -> count plus total row count
    (reference `GroupingAnalyzers.scala:128-157`).

    Accumulation is amortized: per-batch count runs buffer in a list and are
    merged with ONE concat + groupby once the buffer outweighs the merged
    table (the reference leans on Spark's hash-aggregation shuffle for the
    same reason, `GroupingAnalyzers.scala:53-80`). The old per-batch
    ``Series.add`` outer join re-touched every distinct group per batch —
    quadratic over a run on high-cardinality columns.
    """

    #: total entries processed by flush merges across the process — tests
    #: assert this stays O(total entries appended), see tests/test_grouping_scale.py
    merge_work: int = 0

    def __init__(self, frequencies: pd.Series, num_rows: int, group_columns: Sequence[str]):
        self._merged = frequencies  # index = group keys (tuples for multi-col)
        self._runs: List[pd.Series] = []
        self._buffered = 0
        self._spill: Optional[_SpillStore] = None
        self._summary: Optional[Tuple[int, int, int, float]] = None
        self.num_rows = int(num_rows)
        self.group_columns = list(group_columns)

    @property
    def spilled(self) -> bool:
        """True once the table crossed the budget and lives (partly) in
        hash-partitioned spill files instead of RAM."""
        return self._spill is not None

    @property
    def frequencies(self) -> pd.Series:
        """The merged frequency table (forces a flush of buffered runs).

        A SPILLED table refuses to materialize: consumers that need the
        whole table at once (state persistence, incremental ``sum`` merge)
        fail with the same clean FrequencyBudgetExceeded the hard budget
        used to raise; streaming consumers use ``iter_merged_chunks``."""
        self._flush()
        if self._spill is not None:
            raise FrequencyBudgetExceeded(
                f"frequency table for {self.group_columns} spilled "
                f"{self._spill.entries_spilled} entries to disk under the "
                f"{FREQ_BUDGET_ENV} budget; full-table materialization is "
                "not available (set a larger budget, or use a streaming "
                "consumer)"
            )
        return self._merged

    @frequencies.setter
    def frequencies(self, value: pd.Series) -> None:
        self._merged = value
        self._runs = []
        self._buffered = 0
        self._spill = None
        self._summary = None

    def iter_merged_chunks(self):
        """Yield FINAL count Series chunks, each group exactly once across
        all chunks — the streaming read every frequency reduction uses.
        Unspilled tables yield themselves in one chunk; spilled tables
        k-way-merge their hash partitions at ~1/P of the table per step."""
        self._flush()
        if self._spill is None:
            if len(self._merged):
                yield self._merged
            return
        yield from self._spill.iter_partition_counts(
            self._merged if len(self._merged) else None
        )

    def num_distinct(self) -> int:
        """Number of distinct groups; streams when spilled."""
        self._flush()  # may create the spill store
        if self._spill is None:
            return len(self._merged)
        return self.stream_summary()[0]

    def stream_summary(self) -> Tuple[int, int, int, float]:
        """(num_distinct, singleton_count, sum(count), sum(count*ln(count)))
        computed in ONE streaming pass and cached — every scalar frequency
        reduction (Uniqueness, Distinctness, UniqueValueRatio,
        CountDistinct, Entropy) reads these, so a 5-analyzer battery over a
        spilled table costs one disk pass, not five. Invalidated whenever
        new counts are appended."""
        if self._summary is None:
            nd = 0
            singles = 0
            total = 0
            # count-of-counts histogram accumulated chunk-by-chunk: holds
            # one entry per distinct COUNT VALUE (O(sqrt(rows)) worst
            # case), never the table itself — the spill tier's chunks stay
            # bounded. Reduced through the same canonical function the
            # device path's _sum_c_ln_c uses, so Entropy stays
            # bit-identical across paths and chunkings.
            hist: dict = {}
            for chunk in self.iter_merged_chunks():
                c = chunk.to_numpy(dtype=np.int64)
                nd += len(c)
                singles += int((c == 1).sum())
                total += int(c.sum())
                uc, mult = np.unique(c[c > 0], return_counts=True)
                for v, m in zip(uc.tolist(), mult.tolist()):
                    hist[v] = hist.get(v, 0) + int(m)
            if hist:
                uc = np.fromiter(sorted(hist), np.int64, count=len(hist))
                mult = np.array([hist[int(v)] for v in uc], dtype=np.int64)
            else:
                uc = np.empty(0, np.int64)
                mult = np.empty(0, np.int64)
            self._summary = (
                nd, singles, total, _reduce_count_histogram(uc, mult)
            )
        return self._summary

    def is_empty(self) -> bool:
        self._flush()  # may create the spill store
        if self._spill is not None:
            return False  # a spilled table crossed the budget: never empty
        return len(self._merged) == 0

    def _budget(self) -> int:
        from ..utils import env_number

        return env_number(FREQ_BUDGET_ENV, 0, int, minimum=0)

    def _spill_enabled(self) -> bool:
        from ..utils import env_flag

        return env_flag(FREQ_SPILL_ENV, True)

    def _flush(self) -> None:
        if not self._runs:
            return
        parts = ([self._merged] if len(self._merged) else []) + self._runs
        FrequenciesAndNumRows.merge_work += sum(len(p) for p in parts)
        if len(parts) == 1:
            merged = parts[0].astype(np.int64)
        else:
            cat = pd.concat(parts)
            levels = (
                list(range(cat.index.nlevels))
                if isinstance(cat.index, pd.MultiIndex)
                else 0
            )
            # dropna=False: NaN is a real group key (update() groups with
            # dropna=False; a float column's NaN VALUES form a group, only
            # nulls are excluded)
            merged = (
                cat.groupby(level=levels, sort=False, dropna=False)
                .sum()
                .astype(np.int64)
            )
        budget = self._budget()
        if budget and len(merged) > budget:
            if not self._spill_enabled():
                raise FrequencyBudgetExceeded(
                    f"frequency table for {self.group_columns} holds {len(merged)} "
                    f"distinct groups, over the {FREQ_BUDGET_ENV}={budget} budget"
                )
            # over budget: scatter the resident table to the hash-partition
            # spill files and keep RAM bounded by ~budget entries (the Spark
            # shuffle-spill analog, `GroupingAnalyzers.scala:53-80`)
            if self._spill is None:
                self._spill = _SpillStore(self.group_columns)
            self._spill.append(merged)
            merged = pd.Series([], dtype=np.int64)
        self._merged = merged
        self._runs = []
        self._buffered = 0

    def _append_run(self, counts: pd.Series) -> None:
        if len(counts) == 0:
            return
        self._summary = None
        self._runs.append(counts)
        self._buffered += len(counts)
        if self._buffered >= max(len(self._merged), MIN_FLUSH_ENTRIES):
            self._flush()

    def close(self) -> None:
        """Release the hash-partition spill directory NOW (idempotent,
        no-op when nothing spilled). After closing, a spilled state refuses
        to serve (its partition files are gone); the runner closes its
        pass-local states once their metrics are derived, and the GC
        finalizer remains the backstop for everything else."""
        if self._spill is not None:
            self._spill.close()

    def sum(self, other: "FrequenciesAndNumRows") -> "FrequenciesAndNumRows":
        if not isinstance(other, FrequenciesAndNumRows):
            raise TypeError(
                f"cannot merge a value-keyed frequency table with "
                f"{type(other).__name__}: hashed device-frequency states "
                "and host group-by states never mix (the runner gates the "
                "device table engine off runs that persist or aggregate)"
            )
        merged = _add_series(self.frequencies, other.frequencies)
        return FrequenciesAndNumRows(merged, self.num_rows + other.num_rows, self.group_columns)

    @staticmethod
    def empty(group_columns: Sequence[str]) -> "FrequenciesAndNumRows":
        return FrequenciesAndNumRows(
            pd.Series([], dtype=np.int64), 0, group_columns
        )

    def update(self, batch: Batch) -> "FrequenciesAndNumRows":
        """Fold one batch of rows into the frequency table. O(batch) work per
        batch (the per-batch group-by); merges amortize via `_append_run`.
        Mutates and returns self — per-batch copies of a potentially huge
        table are exactly the cost this accumulator exists to avoid."""
        mask = batch.row_mask
        columns = {name: batch.column(name) for name in self.group_columns}
        for col in columns.values():
            mask = mask & col.mask  # validity masks only: values stay lazy
        self.num_rows += batch.num_rows
        if not mask.any():
            return self
        if len(self.group_columns) == 1:
            col = next(iter(columns.values()))
            if col.arrow is not None and batch.row_mask.all():
                # string keys kept as an Arrow array (its nulls ARE the
                # validity mask): C-speed value_counts, no python-object
                # materialization — touching col.values here would defeat it;
                # the null group is excluded inside _arrow_value_counts
                counts = _arrow_value_counts(col.arrow)
                if counts is not None:
                    self._append_run(counts)
                    return self
            vals = col.values
            if vals.dtype != object and np.issubdtype(vals.dtype, np.integer):
                sel = vals[mask]
                if sel.size:
                    smn, smx = sel.min(), sel.max()
                    if int(smx) - int(smn) < (1 << 16):
                        # small-range integer keys (flags, line numbers,
                        # ordinals): an offset bincount beats the sort
                        # inside np.unique ~5x. Dtype care: signed narrow
                        # dtypes wrap on in-dtype subtraction (int8:
                        # 127-(-128) -> -1) so they widen first; uint64
                        # values above 2^63 overflow int64 so unsigned
                        # subtracts in-dtype (exact — range < 2^16) and
                        # rebuilds keys in-dtype too.
                        if np.issubdtype(sel.dtype, np.signedinteger):
                            offs = sel.astype(np.int64) - int(smn)
                        else:
                            offs = (sel - smn).astype(np.int64)
                        cnts = np.bincount(offs, minlength=int(smx) - int(smn) + 1)
                        nz = np.flatnonzero(cnts)
                        if np.issubdtype(sel.dtype, np.signedinteger):
                            keys = (nz + int(smn)).astype(sel.dtype)
                        else:
                            keys = nz.astype(sel.dtype) + smn
                        self._append_run(
                            pd.Series(cnts[nz].astype(np.int64), index=keys)
                        )
                        return self
                # integer keys: np.unique sorts + counts ~6x faster than a
                # pandas groupby (floats stay on the groupby path — NaN
                # group-key identity is pandas' job)
                uniques, cnts = np.unique(sel, return_counts=True)
                self._append_run(pd.Series(cnts.astype(np.int64), index=uniques))
                return self
        frame = pd.DataFrame({n: c.values[mask] for n, c in columns.items()})
        counts = frame.groupby(self.group_columns, sort=False, dropna=False).size()
        if len(self.group_columns) == 1:
            counts.index = counts.index.get_level_values(0) if isinstance(
                counts.index, pd.MultiIndex
            ) else counts.index
        self._append_run(counts)
        return self


def _with_null_bin(counts: pd.Series, num_null: int) -> pd.Series:
    """Add the NullValue bin (reference `analyzers/Histogram.scala:108`:
    nulls count under the "NullValue" key) — the single definition all three
    Histogram accumulation paths share."""
    if not num_null:
        return counts
    return counts.add(
        pd.Series({NULL_FIELD_REPLACEMENT: num_null}), fill_value=0
    ).astype(np.int64)


def _arrow_value_counts(arr) -> Optional[pd.Series]:
    """Distinct-value counts of an Arrow array as an int64 Series (null
    entry dropped), or None when Arrow cannot count this type."""
    import pyarrow.compute as pc

    try:
        vc = pc.value_counts(arr)
    except Exception:  # noqa: BLE001 - unsupported type: caller falls back
        return None
    values = vc.field("values")
    keys = values.to_numpy(zero_copy_only=False)
    counts = vc.field("counts").to_numpy(zero_copy_only=False)
    if values.null_count:
        keep = np.asarray(pc.is_valid(values))
        keys, counts = keys[keep], counts[keep]
    return pd.Series(counts.astype(np.int64), index=keys)


def _sum_c_ln_c(counts: np.ndarray) -> float:
    """sum(count * ln(count)) over a count multiset in CANONICAL order: the
    count-of-counts histogram reduced in ascending count value. The device
    frequency engine surfaces counts keyed by 64-bit hashes, the host
    group-by keys them by value — same multiset, different array order, and
    float addition is not associative. The histogram form is a pure
    function of the multiset, so the two paths (and any chunking of the
    host spill — see ``FrequenciesAndNumRows.stream_summary``, which
    accumulates the same histogram chunk-by-chunk in bounded memory)
    produce the bit-identical Entropy."""
    counts = np.asarray(counts, dtype=np.int64)
    uc, mult = np.unique(counts[counts > 0], return_counts=True)
    return _reduce_count_histogram(uc, mult)


def _reduce_count_histogram(uc: np.ndarray, mult: np.ndarray) -> float:
    """The shared canonical reduction: ``uc`` ascending unique count
    values, ``mult`` their multiplicities. Every c*ln(c) consumer must
    reach this exact function for bit-identical results."""
    pos = uc.astype(np.float64)
    return float((mult.astype(np.float64) * (pos * np.log(pos))).sum())


def _add_series(a: pd.Series, b: pd.Series) -> pd.Series:
    """Outer-join add of two count series; tolerates empty operands whose
    index types don't match the other side's (Range vs MultiIndex)."""
    if len(a) == 0:
        return b.astype(np.int64)
    if len(b) == 0:
        return a.astype(np.int64)
    return a.add(b, fill_value=0).astype(np.int64)


#: dictionary sizes up to this ride the fused device scan (one-hot /
#: sort-based counting, see DeviceFrequencyScan.update); larger
#: dictionaries fall back to the device frequency TABLE engine (hashed
#: keys) or the amortized host group-by. Env-overridable via
#: DEEQU_TPU_DEVICE_FREQ_MAX_CARDINALITY (read through
#: :func:`device_freq_max_cardinality`).
DEVICE_FREQ_MAX_CARDINALITY = 1 << 16

DEVICE_FREQ_MAX_CARDINALITY_ENV = "DEEQU_TPU_DEVICE_FREQ_MAX_CARDINALITY"

#: env var switching the device frequency TABLE engine ("0" disables; the
#: dense dictionary path above stays on — it predates the table engine)
DEVICE_FREQ_ENV = "DEEQU_TPU_DEVICE_FREQ"

#: env var sizing the frequency table: distinct-group capacity per grouping
#: set (rounded up to a power of two; capped per run at the row count,
#: since distinct <= rows). Bigger tables push the overflow knee out at the
#: cost of HBM and per-compaction sort width.
FREQ_TABLE_SLOTS_ENV = "DEEQU_TPU_FREQ_TABLE_SLOTS"
DEFAULT_FREQ_TABLE_SLOTS = 1 << 22

#: env var capping the raw key buffer (entries, 8B each; rounded up to a
#: power of two). Runs whose padded row count fits under the cap ride the
#: RESIDENT trace: every per-row key stays buffered on device and the
#: drain aggregates once — no in-pass compaction sorts at all. Larger runs
#: fall back to the conditional-compaction trace (the sorted fixed-shape
#: table bounds drain work; the sort amortizes over buffer/batch batches),
#: whose buffer floor is one padded batch — the cap cannot shrink it below
#: that.
FREQ_BUFFER_ENTRIES_ENV = "DEEQU_TPU_FREQ_BUFFER_ENTRIES"
DEFAULT_FREQ_BUFFER_ENTRIES = 1 << 25  # 256MB of u64 keys

#: env var gating the pre-routing cardinality probe ("0" disables it, so
#: every eligible grouping set takes the device table no matter how small
#: it looks — tools/grouping_sweep uses this to measure the raw table
#: curve). With the probe on, sets that confidently look low-cardinality
#: stay on the host group-by, whose value_counts fast path wins below the
#: sweep knee.
FREQ_HOST_ROUTE_ENV = "DEEQU_TPU_FREQ_HOST_ROUTE"

#: warn-once latches for unparseable env overrides (the watchdog/trace
#: convention: never crash a run over a typo'd knob, never spam the log)
_ENV_WARNED: set = set()


def device_freq_max_cardinality() -> int:
    """The dense dictionary-path cardinality ceiling (registry-resolved:
    env override > tuned > static)."""
    from ..tuning import knobs

    return knobs.value("device_freq_max_cardinality")


def freq_table_slots() -> int:
    """Configured distinct-group capacity of the device frequency table."""
    from ..tuning import knobs

    return knobs.value("freq_table_slots")


def freq_buffer_entries() -> int:
    """Configured raw-key buffer cap (the resident-mode ceiling)."""
    from ..tuning import knobs

    return knobs.value("freq_buffer_entries")


def device_freq_enabled() -> bool:
    import logging
    import os

    raw = os.environ.get(DEVICE_FREQ_ENV)
    if raw is None or raw in ("0", "1"):
        return raw != "0"
    if DEVICE_FREQ_ENV not in _ENV_WARNED:
        _ENV_WARNED.add(DEVICE_FREQ_ENV)
        logging.getLogger(__name__).warning(
            "ignoring invalid %s=%r (expected 0 or 1); device frequency "
            "engine stays enabled", DEVICE_FREQ_ENV, raw,
        )
    return True


@dataclass(frozen=True)
class DeviceFrequencyScan(ScanShareableAnalyzer):
    """Frequency table of one dictionary-encoded column computed ON DEVICE:
    a scatter-free count over the column's codes joins the fused scan
    (chunked one-hot sum for small dictionaries, sort + boundary diffs for
    large ones — see ``update``), so low-cardinality grouping costs zero
    extra host work (SURVEY §7 step 6's hybrid; the reference instead runs
    a Spark groupBy shuffle per set, `GroupingAnalyzers.scala:53-80`).

    Runner-internal: `AnalysisRunner` instantiates it for eligible grouping
    sets and converts the state back into FrequenciesAndNumRows, so every
    grouping analyzer's metric code sees one state type."""

    column: str = ""
    num_categories: int = 0
    name: str = field(default="DeviceFrequencyScan", init=False)

    supports_host_partial = True

    @property
    def instance(self) -> str:
        return self.column

    def feature_specs(self):
        from .base import codes_feature, mask_feature, rows_feature

        return [rows_feature(), mask_feature(self.column), codes_feature(self.column)]

    def init_state(self):
        from .states import FrequencyCountsState

        return FrequencyCountsState.init(self.num_categories)

    def update(self, state, features):
        import jax.numpy as jnp

        from .base import codes_feature, mask_feature

        rows = features["rows"]
        mask = rows & features[mask_feature(self.column).key]
        codes = features[codes_feature(self.column).key]
        K = self.num_categories
        # No scatter-add: `segment_sum` lowers to a serialized loop on TPU
        # (measured 72-123ms per 1M-row batch). Small dictionaries count via
        # a chunked one-hot compare/sum scan (1.3ms — the VMEM-tile trick
        # the HLL register max uses); larger ones sort the codes and take
        # boundary differences (3-10ms, exact for any cardinality). Masked
        # rows map to the sentinel code K, which both paths drop.
        keys = jnp.where(mask, codes, K).astype(jnp.int32)
        if K <= 4096:
            from ..ops import chunked_key_fold

            cats = jnp.arange(K, dtype=jnp.int32)

            def fold_chunk(acc, row):
                hits = jnp.sum(
                    row[:, None] == cats[None, :], axis=0, dtype=acc.dtype
                )
                return acc + hits

            batch_counts = chunked_key_fold(
                keys, K, jnp.zeros(K, jnp.int32), fold_chunk
            )
        else:
            sorted_keys = jnp.sort(keys)
            bounds = jnp.searchsorted(
                sorted_keys, jnp.arange(K + 1, dtype=jnp.int32), side="left"
            )
            batch_counts = bounds[1:] - bounds[:-1]
        from .states import FrequencyCountsState

        return FrequencyCountsState(
            state.counts + batch_counts.astype(state.counts.dtype),
            state.num_rows + jnp.sum(rows, dtype=state.num_rows.dtype),
        )

    def host_partial(self, ctx):
        from .states import FrequencyCountsState

        col = ctx.batch.column(self.column)
        shared = ctx.dict_code_counts(self.column)
        if shared is not None:
            # the shared one-pass native count (also feeds DataType/HLL)
            counts = shared[: self.num_categories]
        else:
            mask = ctx.batch.row_mask & col.mask
            counts = np.bincount(
                col.codes[mask], minlength=self.num_categories + 1
            )[: self.num_categories]
        return FrequencyCountsState(
            counts.astype(np.int64), np.asarray(ctx.batch.num_rows, dtype=np.int64)
        )

    def merge(self, a, b):
        return a.merge(b)

    def to_frequencies(self, state, dictionary: np.ndarray) -> FrequenciesAndNumRows:
        counts = np.asarray(state.counts)
        nz = counts > 0
        series = pd.Series(
            counts[nz].astype(np.int64), index=pd.Index(np.asarray(dictionary)[nz])
        )
        return FrequenciesAndNumRows(series, int(state.num_rows), [self.column])

    def compute_metric_from(self, state):  # pragma: no cover - runner-internal
        raise NotImplementedError(
            "DeviceFrequencyScan states convert via to_frequencies; the "
            "grouping analyzers sharing the set own the metrics"
        )


def _u64_value_counts(keys: np.ndarray, weights):
    """Exact (unique key -> summed weight) over u64 hash keys: the
    cache-partitioned native kernel when built (hundreds of ms for 25M
    keys), a numpy argsort + reduceat otherwise. ``weights=None`` counts
    each key once (the resident-buffer fast path — no materialized ones
    array); explicit weights must be positive (the native kernel treats
    zero as the empty-slot marker)."""
    if len(keys) == 0:
        return keys.astype(np.uint64), np.zeros(0, dtype=np.int64)
    from ..native import native_u64_value_counts

    if native_u64_value_counts is not None:
        out = native_u64_value_counts(keys, weights)
        if out is not None:
            return out
    order = np.argsort(keys, kind="stable")
    k = keys[order]
    w = (
        np.ones(len(k), dtype=np.int64)
        if weights is None
        else weights[order].astype(np.int64)
    )
    starts = np.flatnonzero(np.concatenate([[True], k[1:] != k[:-1]]))
    return k[starts], np.add.reduceat(w, starts)


class HashedFrequencies:
    """Exact count multiset keyed by 64-bit GROUP-KEY HASHES — the drained
    host view of a :class:`~..analyzers.states.FrequencyTableState`.

    The scalar frequency reductions (Uniqueness, Distinctness,
    UniqueValueRatio, CountDistinct, Entropy) are pure functions of the
    count multiset plus ``num_rows``; hashing the keys loses nothing for
    them. Key-READING consumers (Histogram bins, MutualInformation
    marginals) never receive one — runner eligibility keeps those on the
    dictionary or host paths. Reads through the same
    ``stream_summary``/``num_distinct``/``is_empty`` protocol as
    :class:`FrequenciesAndNumRows`, so the analyzers' metric code is
    state-type agnostic."""

    __slots__ = ("keys", "counts", "num_rows", "group_columns", "_summary")

    def __init__(
        self,
        keys: np.ndarray,
        counts: np.ndarray,
        num_rows: int,
        group_columns: Sequence[str],
    ):
        self.keys = np.asarray(keys, dtype=np.uint64)
        self.counts = np.asarray(counts, dtype=np.int64)
        self.num_rows = int(num_rows)
        self.group_columns = list(group_columns)
        self._summary: Optional[Tuple[int, int, int, float]] = None

    def num_distinct(self) -> int:
        return len(self.counts)

    def is_empty(self) -> bool:
        return len(self.counts) == 0

    def stream_summary(self) -> Tuple[int, int, int, float]:
        """(num_distinct, singleton_count, sum(count), sum(count*ln(count)))
        — the same cached quadruple FrequenciesAndNumRows serves."""
        if self._summary is None:
            self._summary = (
                len(self.counts),
                int((self.counts == 1).sum()),
                int(self.counts.sum()),
                _sum_c_ln_c(self.counts),
            )
        return self._summary

    def close(self) -> None:  # protocol parity with FrequenciesAndNumRows
        pass

    def sum(self, other: "HashedFrequencies") -> "HashedFrequencies":
        if not isinstance(other, HashedFrequencies):
            raise TypeError(
                f"cannot merge a hashed frequency state with "
                f"{type(other).__name__}: hashed device-frequency states "
                "and value-keyed host states never mix"
            )
        keys, counts = _u64_value_counts(
            np.concatenate([self.keys, other.keys]),
            np.concatenate([self.counts, other.counts]),
        )
        return HashedFrequencies(
            keys, counts, self.num_rows + other.num_rows, self.group_columns
        )


@dataclass(frozen=True)
class DeviceFrequencyTableScan(ScanShareableAnalyzer):
    """ARBITRARY-cardinality grouping frequencies computed ON DEVICE inside
    the fused pass (ROADMAP item 3: the refactor that kills the host
    ``value_counts`` + hash-partitioned spill default).

    Per batch, each grouping column contributes one 64-bit column key —
    integral/boolean columns mix their raw ``num`` feature through the
    bijective SplitMix64 avalanche ON DEVICE (zero host hashing);
    string/fractional columns ship per-row xxhash64 keys computed by the
    host feature frontend (dictionary columns gather cached per-entry
    hashes). Multi-column sets chain column keys with xxhash64, each key
    seeding the next (the Spark ``XxHash64`` chaining shape), so a combined
    key depends on every column and on column order — and multi-column
    grouping finally leaves the host path. The per-row keys append into the
    state's pow2 buffer at memcpy speed; a sort-merge compaction
    (:func:`deequ_tpu.ops.freq_compact`) folds the buffer into the sorted
    fixed-shape table only when it would overflow, keeping the trace
    shape-static and signature-bundleable.

    Tiering: groups beyond the table's ``slots`` capacity are dropped with
    EXACT loss accounting (``lost_rows``); the runner detects a lossy drain
    and re-runs just those grouping sets through the host accumulator
    (whose ``_SpillStore`` is thereby the LAST-RESORT tier instead of the
    default path).

    Runner-internal, like :class:`DeviceFrequencyScan`: the runner
    instantiates it for eligible sets and drains the state into a
    :class:`HashedFrequencies` every member analyzer reads."""

    columns: Tuple[str, ...] = ()
    #: per-column key derivation, positionally parallel to ``columns``:
    #: "num" (device SplitMix64 over the shared numeric feature) or "hash"
    #: (host xxhash64 feature). Part of the frozen identity — it changes
    #: the traced update.
    column_kinds: Tuple[str, ...] = ()
    slots: int = 0
    buffer_entries: int = 0
    #: RESIDENT mode: the planner proved ``buffer_entries`` covers every
    #: padded batch of the run, so the update emits NO compaction cond —
    #: the hot path is a pure donated-carry append (frozen identity: it
    #: changes the traced program)
    resident: bool = False
    name: str = field(default="DeviceFrequencyTableScan", init=False)

    supports_host_partial = False  # raw keys must stream to the device;
    # on a feed-starved link the runner keeps the set on the host group-by

    @property
    def instance(self) -> str:
        return ",".join(self.columns)

    def scan_program_key(self) -> Tuple:
        # ``resident`` flips ``assume_fits`` inside ``update`` — a traced
        # control-flow difference invisible to state shapes and feature
        # kinds. Without this key a non-resident run whose (slots, buffer)
        # happen to match a cached resident program would execute the
        # cond-free trace and overflow the buffer silently.
        return (self.resident,)

    def feature_specs(self):
        from .base import (
            hash_feature,
            mask_feature,
            numeric_feature,
            rows_feature,
        )

        specs = [rows_feature()]
        for col, kind in zip(self.columns, self.column_kinds):
            specs.append(mask_feature(col))
            specs.append(
                numeric_feature(col) if kind == "num" else hash_feature(col)
            )
        return specs

    def init_state(self):
        from .states import FrequencyTableState

        return FrequencyTableState.init(self.slots, self.buffer_entries)

    def update(self, state, features):
        import jax.numpy as jnp

        from ..ops.hashing import (
            FREQ_KEY_SENTINEL,
            splitmix64_jnp,
            xxhash64_u64_jnp,
        )
        from .base import hash_feature, mask_feature, numeric_feature

        rows = features["rows"]
        valid = rows
        for col in self.columns:
            valid = valid & features[mask_feature(col).key]
        key = None
        for col, kind in zip(self.columns, self.column_kinds):
            if kind == "num":
                # value conversion (not a bitcast — the TPU x64 emulation
                # implements no 64-bit bitcasts): int dtypes wrap modulo
                # 2^64 (bijective per dtype), boolean rides its f64 0/1
                # feature. Masked slots hold arbitrary bytes and are
                # sentinel-keyed below.
                ck = splitmix64_jnp(
                    features[numeric_feature(col).key].astype(jnp.uint64)
                )
            else:
                ck = features[hash_feature(col).key]
            key = ck if key is None else xxhash64_u64_jnp(ck, key)
        sent = jnp.uint64(FREQ_KEY_SENTINEL)
        # a real key colliding with the sentinel would read as a masked row:
        # count those rows exactly instead (they form one group per the
        # bijective single-column mixes; for hashed keys two such groups
        # colliding is a ~2^-64 event) and restore the group at drain time
        is_sent = valid & (key == sent)
        keys = jnp.where(valid & (key != sent), key, sent)
        return state.append_keys(
            keys,
            jnp.sum(is_sent, dtype=jnp.int64),
            jnp.sum(rows, dtype=jnp.int64),
            assume_fits=self.resident,
        )

    def merge(self, a, b):
        return a.merge(b)

    def drain(self, state) -> Optional[HashedFrequencies]:
        """Fetched (host numpy) state -> exact HashedFrequencies, or None
        when compactions dropped groups (``lost_rows > 0``) — the runner
        then re-runs this set through the host accumulator tier."""
        from ..ops.hashing import FREQ_KEY_SENTINEL

        if int(state.lost_rows) > 0:
            return None
        sent_key = np.uint64(FREQ_KEY_SENTINEL)
        buf = np.asarray(state.buf)[: int(state.buf_fill)]  # contiguous view
        if int(state.n_table) == 0:
            # resident fast path: the whole run lives in the buffer — feed
            # the view straight to the aggregation (no concat copy, no
            # 25M-row sentinel pre-filter; the sentinel aggregates into ONE
            # output entry dropped below)
            keys, counts = _u64_value_counts(buf, None)
        else:
            tcounts = np.asarray(state.sorted_counts)
            nz = tcounts > 0
            tkeys = np.asarray(state.sorted_keys)[nz]
            tcounts = tcounts[nz]
            keys, counts = _u64_value_counts(
                np.concatenate([tkeys, buf]),
                np.concatenate([tcounts, np.ones(len(buf), dtype=np.int64)]),
            )
        # drop the aggregated sentinel group (masked/null rows, structural
        # batch padding, and valid rows whose key collided with the
        # sentinel — the last counted exactly in sent_rows and restored as
        # their own group here)
        at = np.flatnonzero(keys == sent_key)
        if len(at):
            keys = np.delete(keys, at)
            counts = np.delete(counts, at)
        sent = int(state.sent_rows)
        if sent:
            keys = np.concatenate([keys, [sent_key]])
            counts = np.concatenate([counts, [np.int64(sent)]])
        return HashedFrequencies(
            keys, counts, int(state.num_rows), list(self.columns)
        )

    def compute_metric_from(self, state):  # pragma: no cover - runner-internal
        raise NotImplementedError(
            "DeviceFrequencyTableScan states convert via drain; the "
            "grouping analyzers sharing the set own the metrics"
        )


def _next_pow2(v: int) -> int:
    p = 1
    while p < v:
        p <<= 1
    return p


# The probe's thresholds — the union-distinct ceiling for confidently
# routing host (~the PERF.md knee / 4: below ~100k distinct the host
# value_counts fast path wins ~3x, above it the device table wins up to
# ~13x), the rows per probe slice, and the row floor below which the
# probe never answers host — are registered tuning knobs
# (freq_host_route_max_distinct / freq_probe_rows /
# freq_host_route_min_rows in tuning/knobs.py) carrying the measured
# dev-box values as static defaults; boot-time calibration re-derives
# them per substrate.


def probably_low_cardinality(
    data, columns: Sequence[str], limit: Optional[int] = None
) -> bool:
    """Cheap pre-routing probe: True when EVERY column of the grouping set
    confidently looks low-cardinality, so the host group-by's
    ``value_counts`` fast path will beat the device frequency table (the
    sweep knee sits ~100k distinct; at 100 distinct the host path is ~3x
    faster). Mirrors the adaptive dictionary-encode probe in
    ``data._maybe_dictionary_encode``: head/mid/tail slices, and a
    clustered/sorted layout — whose later slices keep revealing NEW keys —
    is rejected via cross-slice novelty, because its low per-slice counts
    say nothing about total cardinality. Mis-detection is perf-only and
    asymmetric (a false "device" costs ~3x at tiny cardinalities, a false
    "host" forfeits up to ~13x at scale), so uncertainty answers False."""
    import logging
    import os

    import pyarrow as pa
    import pyarrow.compute as pc

    raw = os.environ.get(FREQ_HOST_ROUTE_ENV)
    if raw is not None and raw not in ("0", "1"):
        if FREQ_HOST_ROUTE_ENV not in _ENV_WARNED:
            _ENV_WARNED.add(FREQ_HOST_ROUTE_ENV)
            logging.getLogger(__name__).warning(
                "ignoring invalid %s=%r (expected 0 or 1); cardinality "
                "pre-routing stays enabled", FREQ_HOST_ROUTE_ENV, raw,
            )
        raw = None
    if raw == "0":
        return False
    from ..tuning import knobs

    if limit is None:
        limit = knobs.value("freq_host_route_max_distinct")
    probe_rows = knobs.value("freq_probe_rows")
    n = int(data.num_rows)
    if n <= knobs.value("freq_host_route_min_rows"):
        return False
    estimate = 1
    for col in columns:
        dictionary = data.dictionary_values(col)
        if dictionary is not None:
            card = len(dictionary)  # exact
        else:
            try:
                column = data.arrow.column(col)
                # disjoint head/mid/tail slices (n > MIN_ROWS >> 3 probes)
                slices = [
                    column.slice(start, probe_rows)
                    for start in (
                        0,
                        (n - probe_rows) // 2,
                        n - probe_rows,
                    )
                ]
                per_slice = [pc.count_distinct(s).as_py() for s in slices]
                union = pc.count_distinct(
                    pa.chunked_array([c for s in slices for c in s.chunks])
                ).as_py()
                if union > 1.5 * max(per_slice):
                    # later slices kept revealing new keys: clustered
                    # high-cardinality layout (or genuinely growing key
                    # space) — not confident, take the device table
                    return False
                card = union
            except Exception:  # noqa: BLE001 - exotic layout: stay on device
                return False
        estimate *= max(card, 1)
        if estimate > limit:
            return False
    return True


def plan_table_scan(
    schema, columns: Sequence[str], num_rows: int, batch_rows: int,
    sharded: bool = False,
) -> Optional[DeviceFrequencyTableScan]:
    """Size a DeviceFrequencyTableScan for one grouping set, or None when a
    column's kind cannot derive a 64-bit key.

    Shapes are pow2-bucketed so the compiled-program space stays small and
    warm across runs. When every padded batch of the run fits the key
    buffer (cap :func:`freq_buffer_entries`, default 2^25), the scan runs
    RESIDENT: per-row keys append at memcpy speed with NO compaction cond
    in the trace, and the single drain-time aggregation is exact for ANY
    cardinality up to the buffer — the fast path the bench grouping stage
    measures. An UNSHARDED resident plan gets a minimal table (the trace
    never compacts into it and drain ignores it, so full slots would be
    ~67MB of dead HBM + fetch transfer per set); sharded resident states
    DO compact into the table at collective merge, so they keep full
    capacity. Larger runs get the conditional-compaction trace: ``slots``
    is the configured table capacity capped at the row count (distinct <=
    rows, so a table with slots >= rows can NEVER overflow) and the
    buffer covers at least one padded batch so the compaction sort
    amortizes."""
    from ..data import ColumnKind

    kinds: List[str] = []
    for col in columns:
        kind = schema[col].kind
        if kind in (ColumnKind.INTEGRAL, ColumnKind.BOOLEAN):
            kinds.append("num")
        elif kind in (ColumnKind.FRACTIONAL, ColumnKind.STRING):
            kinds.append("hash")
        else:
            return None
    slots = _next_pow2(
        min(freq_table_slots(), max(int(num_rows), 1024))
    )
    batch_rows = max(int(batch_rows), 1)
    # every batch appends its PADDED length (masked padding rows are
    # sentinel-keyed but still occupy buffer slots), so resident mode must
    # cover ceil(rows/batch) full batches
    padded_rows = -(-max(int(num_rows), 1) // batch_rows) * batch_rows
    # the knob is documented "rounded up to a power of two": compare
    # against the rounded cap so a non-pow2 setting admits exactly the
    # runs its allocated (pow2) buffer can hold
    buffer_cap = _next_pow2(freq_buffer_entries())
    if padded_rows <= buffer_cap:
        return DeviceFrequencyTableScan(
            tuple(columns), tuple(kinds), slots if sharded else 8,
            _next_pow2(max(padded_rows, batch_rows)), resident=True,
        )
    buffer_entries = _next_pow2(
        max(batch_rows, min(slots, 1 << 20, buffer_cap))
    )
    return DeviceFrequencyTableScan(
        tuple(columns), tuple(kinds), slots, buffer_entries
    )


class GroupingAnalyzer(Analyzer[FrequenciesAndNumRows, DoubleMetric]):
    """Analyzer computed from a shared frequency table."""

    columns: Sequence[str]

    def grouping_columns(self) -> List[str]:
        return list(self.columns)

    @property
    def instance(self) -> str:
        return ",".join(self.grouping_columns())

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN if len(self.grouping_columns()) == 1 else Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        cols = self.grouping_columns()
        out: List[Callable[[Schema], None]] = [Preconditions.at_least_one(cols)]
        for c in cols:
            out.append(Preconditions.has_column(c))
            out.append(Preconditions.is_not_nested(c))
        return out

    def merge(self, a: FrequenciesAndNumRows, b: FrequenciesAndNumRows) -> FrequenciesAndNumRows:
        return a.sum(b)


class ScanShareableFrequencyBasedAnalyzer(GroupingAnalyzer):
    """Base for analyzers that reduce the frequency table to a double
    (reference `GroupingAnalyzers.scala:85-123`)."""

    #: an EMPTY frequency table (e.g. every grouping value null) yields an
    #: empty metric: the reference's SUM aggregation over an empty relation
    #: returns null -> EmptyStateException (`NullHandlingTests.scala`).
    #: CountDistinct overrides this — COUNT over an empty relation is 0.
    empty_frequencies_are_empty_metric: bool = True

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> DoubleMetric:
        if state is None:
            return metric_from_empty(self.name, self.instance, self.entity)
        if self.empty_frequencies_are_empty_metric and state.is_empty():
            return metric_from_empty(self.name, self.instance, self.entity)
        try:
            value = self.metric_from_frequencies(state)
        except Exception as exc:  # noqa: BLE001
            return metric_from_failure(wrap_if_necessary(exc), self.name, self.instance, self.entity)
        if value is None or (isinstance(value, float) and math.isnan(value)):
            return metric_from_empty(self.name, self.instance, self.entity)
        return metric_from_value(float(value), self.name, self.instance, self.entity)

    @abc.abstractmethod
    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        ...


@dataclass(frozen=True)
class Uniqueness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of rows whose group occurs exactly once: sum(count==1)/numRows
    (reference `analyzers/Uniqueness.scala:26-38`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="Uniqueness", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        if state.num_rows == 0:
            return float("nan")
        return float(state.stream_summary()[1]) / state.num_rows


@dataclass(frozen=True)
class Distinctness(ScanShareableFrequencyBasedAnalyzer):
    """Fraction of distinct groups over rows: sum(count>=1)/numRows
    (reference `analyzers/Distinctness.scala:29-41`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="Distinctness", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        if state.num_rows == 0:
            return float("nan")
        return float(state.num_distinct()) / state.num_rows


@dataclass(frozen=True)
class UniqueValueRatio(ScanShareableFrequencyBasedAnalyzer):
    """sum(count==1) / number of distinct groups
    (reference `analyzers/UniqueValueRatio.scala:25-44`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="UniqueValueRatio", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        num_groups, singletons, _, _ = state.stream_summary()
        if num_groups == 0:
            return float("nan")
        return float(singletons) / num_groups


@dataclass(frozen=True)
class CountDistinct(ScanShareableFrequencyBasedAnalyzer):
    """Number of distinct groups (reference `analyzers/CountDistinct.scala:24-40`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="CountDistinct", init=False)
    empty_frequencies_are_empty_metric = False  # COUNT of no groups is 0.0

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        return float(state.num_distinct())


@dataclass(frozen=True)
class Entropy(ScanShareableFrequencyBasedAnalyzer):
    """Shannon entropy over the value distribution, with N = total row count:
    -sum (c/N) ln(c/N) (reference `analyzers/Entropy.scala:28-42`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="Entropy", init=False)

    def __init__(self, column):
        object.__setattr__(self, "columns", _as_tuple(column))

    def metric_from_frequencies(self, state: FrequenciesAndNumRows) -> float:
        n = state.num_rows
        if n == 0:
            return float("nan")
        # -sum (c/n) ln(c/n) = ln(n) * sum(c)/n - sum(c ln c)/n
        _, _, total, c_ln_c = state.stream_summary()
        return float(math.log(n) * total / n - c_ln_c / n)


@dataclass(frozen=True)
class MutualInformation(GroupingAnalyzer):
    """MI of two columns from the joint frequency table
    (reference `analyzers/MutualInformation.scala:35-103`)."""

    columns: Tuple[str, ...] = ()
    name: str = field(default="MutualInformation", init=False)

    def __init__(self, columns):
        object.__setattr__(self, "columns", _as_tuple(columns))

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.exactly_n_columns(self.columns, 2)] + super().preconditions()

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> DoubleMetric:
        if state is None or state.is_empty():
            return metric_from_empty(self.name, self.instance, self.entity)
        try:
            total = state.num_rows
            # two streaming passes over the joint table: marginals first,
            # then the MI sum. Memory = marginal cardinalities (always <=
            # the joint's), so a spilled joint still completes as long as
            # the per-column distinct counts fit in RAM.
            px: Optional[pd.Series] = None
            py: Optional[pd.Series] = None
            for joint in state.iter_merged_chunks():
                cx = joint.groupby(level=0).sum()
                cy = joint.groupby(level=1).sum()
                px = cx if px is None else px.add(cx, fill_value=0)
                py = cy if py is None else py.add(cy, fill_value=0)
            value = 0.0
            for joint in state.iter_merged_chunks():
                pxy = joint.to_numpy(dtype=np.float64) / total
                px_row = px.loc[joint.index.get_level_values(0)].to_numpy(dtype=np.float64) / total
                py_row = py.loc[joint.index.get_level_values(1)].to_numpy(dtype=np.float64) / total
                value += float((pxy * np.log(pxy / (px_row * py_row))).sum())
        except Exception as exc:  # noqa: BLE001
            return metric_from_failure(wrap_if_necessary(exc), self.name, self.instance, self.entity)
        return metric_from_value(value, self.name, self.instance, self.entity)


def _as_tuple(columns) -> Tuple[str, ...]:
    if isinstance(columns, str):
        return (columns,)
    return tuple(columns)


def _java_double_to_string(x: float) -> str:
    """Java ``Double.toString`` semantics: shortest round-trip digits,
    plain decimal for 1e-3 <= |x| < 1e7, otherwise computerized scientific
    notation ``d.dddEn`` (no '+', no leading exponent zeros). Spark's
    cast-to-string on DoubleType delegates to this, so Histogram bin keys
    and suggestion category lists must match it exactly (e.g. 1e7 keys as
    '1.0E7', not '10000000.0')."""
    import math

    if math.isnan(x):
        return "NaN"
    if math.isinf(x):
        return "Infinity" if x > 0 else "-Infinity"
    if x == 0.0:
        return "-0.0" if math.copysign(1.0, x) < 0 else "0.0"
    sign = "-" if x < 0 else ""
    a = abs(x)
    if 1e-3 <= a < 1e7:
        # Python repr is also shortest-round-trip and stays in plain
        # decimal over exactly this range (it switches to sci only below
        # 1e-4 or at/above 1e16), so the strings coincide digit for digit
        return sign + repr(a)
    # normalize shortest-round-trip digits to d.ddd * 10^dec_exp
    r = repr(a)
    if "e" in r:
        mant, _, exp_s = r.partition("e")
        digits = mant.replace(".", "")
        dec_exp = int(exp_s)
    else:
        int_part, _, frac = r.partition(".")
        if int_part != "0":
            digits = (int_part + frac).lstrip("0")
            dec_exp = len(int_part) - 1
        else:
            stripped = frac.lstrip("0")
            digits = stripped
            dec_exp = -(len(frac) - len(stripped) + 1)
    digits = digits.rstrip("0") or "0"
    mantissa = digits[0] + "." + (digits[1:] or "0")
    return f"{sign}{mantissa}E{dec_exp}"


def _spark_string_cast(value) -> str:
    """Format a value the way Spark's cast-to-string would (booleans
    lowercase, doubles via Java ``Double.toString``)."""
    if isinstance(value, (bool, np.bool_)):
        return "true" if value else "false"
    if isinstance(value, (float, np.floating)):
        return _java_double_to_string(float(value))
    if isinstance(value, (int, np.integer)):
        return str(int(value))
    return str(value)


def device_counts_to_histogram_frequencies(
    scan: "DeviceFrequencyScan", state, dictionary: np.ndarray
) -> FrequenciesAndNumRows:
    """Device frequency counts -> the Histogram state shape: keys become
    their Spark string casts and null rows land in the NullValue bin, so
    the resulting FrequenciesAndNumRows is indistinguishable from the host
    accumulator's (merge/persist/metric all behave identically)."""
    counts = np.asarray(state.counts)
    nz = np.flatnonzero(counts)
    keys = [_spark_string_cast(v) for v in np.asarray(dictionary)[nz]]
    series = pd.Series(counts[nz].astype(np.int64), index=keys)
    if series.index.has_duplicates:
        series = series.groupby(level=0, sort=False).sum()
    num_rows = int(state.num_rows)
    series = _with_null_bin(series, num_rows - int(counts.sum()))
    return FrequenciesAndNumRows(series.astype(np.int64), num_rows, [scan.column])


NULL_FIELD_REPLACEMENT = "NullValue"  # reference `analyzers/Histogram.scala:108`
MAXIMUM_ALLOWED_DETAIL_BINS = 1000  # reference `analyzers/Histogram.scala:109`


@dataclass(frozen=True)
class Histogram(Analyzer["FrequenciesAndNumRows", HistogramMetric]):
    """Exact value histogram of one column: values cast to string, nulls
    replaced by "NullValue", optional binning function, top-K detail bins by
    count (reference `analyzers/Histogram.scala:41-116`).

    ``binning_func`` MUST be a pure ``value -> bin`` mapping: it is applied
    once per DISTINCT value, not once per row (the engine counts raw values
    first and bins each distinct key once, turning an O(rows) Python loop
    into O(distinct)). A non-pure or row-position-dependent function would
    silently produce different counts than per-row application; the
    reference's binning UDF (`analyzers/Histogram.scala:63-66`) carries the
    same value-determinism assumption. Returning ``None`` buckets the value
    as "NullValue"."""

    column: str = ""
    binning_func: Optional[Callable] = None
    max_detail_bins: int = MAXIMUM_ALLOWED_DETAIL_BINS
    name: str = field(default="Histogram", init=False)

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        def param_check(schema: Schema) -> None:
            if self.max_detail_bins > MAXIMUM_ALLOWED_DETAIL_BINS:
                raise IllegalAnalyzerParameterException(
                    f"Cannot return histogram values for more than "
                    f"{MAXIMUM_ALLOWED_DETAIL_BINS} values"
                )

        return [param_check, Preconditions.has_column(self.column)]

    # host accumulation protocol (driven by the runner's single pass)

    def host_init(self) -> FrequenciesAndNumRows:
        return FrequenciesAndNumRows.empty([self.column])

    def _dict_keys(self, col) -> np.ndarray:
        """Spark-string-cast of each DISTINCT dictionary entry, computed
        once per dataset (cached in col.aux across batches)."""
        keys = col.aux.get("hist_keys")
        if keys is None:
            keys = np.array(
                [_spark_string_cast(v) for v in col.dictionary], dtype=object
            )
            col.aux["hist_keys"] = keys
        return keys

    def host_update(self, state: FrequenciesAndNumRows, batch: Batch) -> FrequenciesAndNumRows:
        col = batch.column(self.column)
        mask = batch.row_mask
        if self.binning_func is None and col.has_dictionary and col.codes is not None:
            # dictionary column: one O(rows) code bincount; keys are the
            # cached per-entry Spark string casts — no per-row values at all
            from ..native import native_dict_masked_bincount

            num_cats = col.num_categories
            valid = mask & col.mask
            if native_dict_masked_bincount is not None:
                by_code = native_dict_masked_bincount(col.codes, valid, num_cats)[
                    :num_cats
                ]
            else:
                sel = col.codes[valid]
                by_code = np.bincount(
                    sel[(sel >= 0) & (sel < num_cats)], minlength=num_cats
                )
            n_null = int(np.count_nonzero(mask)) - int(by_code.sum())
            nz = np.flatnonzero(by_code)
            if len(nz):
                keys = self._dict_keys(col)
                counts = (
                    pd.Series(by_code[nz].astype(np.int64), index=keys[nz])
                    .groupby(level=0, sort=False)
                    .sum()
                )
            else:
                counts = pd.Series([], dtype=np.int64)
            counts = _with_null_bin(counts, n_null)
            state._append_run(counts.astype(np.int64))
            state.num_rows += batch.num_rows
            return state
        if (
            self.binning_func is None
            and col.arrow is not None
            and mask.all()
        ):
            # arrow-backed strings: count distincts C-speed without object
            # materialization; string keys are their own Spark-string-cast
            counts = _arrow_value_counts(col.arrow)
            if counts is not None:
                counts = _with_null_bin(counts, int(col.arrow.null_count))
                state._append_run(counts)
                state.num_rows += batch.num_rows
                return state
        values = col.values[mask]
        present = col.mask[mask]
        if self.binning_func is None:
            # vectorized: count raw PRESENT values first (cheap),
            # Spark-string-cast only the distinct keys; nullness comes from
            # the validity mask, never from the value (a genuine float NaN
            # keys as 'NaN' per Java Double.toString, a null as NullValue)
            present_values = values[present]
            if present_values.dtype == object:
                counts = pd.Series(present_values).value_counts(sort=False, dropna=False)
                distinct, cnts = list(counts.index), counts.to_numpy()
            else:
                distinct, cnts = np.unique(present_values, return_counts=True)
            counts = pd.Series(
                cnts, index=[_spark_string_cast(k) for k in distinct], dtype=np.int64
            )
            counts = counts.groupby(level=0, sort=False).sum()
            counts = _with_null_bin(counts, int(len(values) - present.sum()))
        else:
            # bin the DISTINCT values, not every row: the binning function is
            # a pure value->bin mapping (the reference's binning UDF carries
            # the same assumption), so counting raw values first and binning
            # each distinct once turns an O(rows) python loop into
            # O(distinct) — the no-binning path's cost profile
            present_values = values[present]
            if present_values.dtype == object:
                vc = pd.Series(present_values).value_counts(sort=False, dropna=False)
                distinct, cnts = list(vc.index), vc.to_numpy()
            else:
                distinct, cnts = np.unique(present_values, return_counts=True)
            keys = []
            for v in distinct:
                b = self.binning_func(v)
                keys.append(
                    _spark_string_cast(b) if b is not None else NULL_FIELD_REPLACEMENT
                )
            counts = (
                pd.Series(cnts, index=keys, dtype=np.int64)
                .groupby(level=0, sort=False)
                .sum()
            )
            counts = _with_null_bin(counts, int(len(values) - present.sum()))
        state._append_run(counts.astype(np.int64))
        state.num_rows += batch.num_rows
        return state

    def merge(self, a: FrequenciesAndNumRows, b: FrequenciesAndNumRows) -> FrequenciesAndNumRows:
        return a.sum(b)

    def compute_metric_from(self, state: Optional[FrequenciesAndNumRows]) -> HistogramMetric:
        if state is None:
            from ..exceptions import EmptyStateException

            return HistogramMetric(
                self.entity,
                self.name,
                self.instance,
                Failure(EmptyStateException(f"Empty state for analyzer {self}")),
                self.column,
            )
        try:
            bin_count = 0
            top: Optional[pd.Series] = None
            for chunk in state.iter_merged_chunks():
                bin_count += len(chunk)
                cand = chunk.nlargest(self.max_detail_bins)
                top = cand if top is None else pd.concat([top, cand]).nlargest(
                    self.max_detail_bins
                )
            if top is None:
                top = pd.Series([], dtype=np.int64)
            values = {
                str(k): DistributionValue(int(v), int(v) / state.num_rows)
                for k, v in top.items()
            }
            dist = Distribution(values, number_of_bins=bin_count)
            return HistogramMetric(self.entity, self.name, self.instance, Success(dist), self.column)
        except Exception as exc:  # noqa: BLE001
            return HistogramMetric(
                self.entity, self.name, self.instance, Failure(wrap_if_necessary(exc)), self.column
            )
